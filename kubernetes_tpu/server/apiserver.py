"""HTTP REST API server over the object store.

The reference's kube-apiserver reduced to its load-bearing walls:

  handler chain   authn -> authz -> admission -> storage
                  (apiserver/pkg/server/config.go
                   DefaultBuildHandlerChainFunc; admission runs inside the
                   create/update handlers, endpoints/handlers/create.go)
  REST mapping    /api/v1/... and /apis/<group>/<version>/... routes to
                  per-resource CRUD (endpoints/installer.go ->
                  registry/generic/registry/store.go)
  watch           ?watch=true streams JSON-lines watch events served from
                  the broadcaster's in-memory window (storage/cacher.go);
                  a too-old resourceVersion returns 410 Gone
  subresources    pods/<name>/binding (the scheduler's bind POST,
                  registry/core/pod/storage BindingREST), pods/<name>/status,
                  nodes/<name>/status, namespaces/<name>/finalize
  ops endpoints   /healthz, /metrics, /version, /api, /apis

Wire format: JSON with camelCase keys via api/scheme.py codecs.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, quote, urlparse

from ..api import scheme
from ..api import types as api
from ..runtime.store import Conflict, ObjectStore
from ..runtime.watch import Broadcaster, TooOld
from ..api import validation
from .admission import AdmissionChain, AdmissionError
from .auth import RBACAuthorizer, TokenAuthenticator, UserInfo


class APIError(Exception):
    def __init__(self, code: int, reason: str, message: str):
        super().__init__(message)
        self.code, self.reason, self.message = code, reason, message


def _status_body(code: int, reason: str, message: str,
                 status: str = "Failure") -> bytes:
    return json.dumps({"kind": "Status", "apiVersion": "v1", "status": status,
                       "reason": reason, "message": message, "code": code}).encode()


# verbs per HTTP method (reference: endpoints/installer.go mapping)
_VERBS = {"GET": "get", "POST": "create", "PUT": "update",
          "PATCH": "patch", "DELETE": "delete"}


def _openapi_type(t) -> dict:
    """Python type annotation -> OpenAPI v2 schema fragment."""
    import dataclasses
    import typing

    origin = typing.get_origin(t)
    args = typing.get_args(t)
    if origin is list:
        return {"type": "array",
                "items": _openapi_type(args[0]) if args else {}}
    if origin is dict or t in (dict, typing.Dict):
        # bare Dict/dict (e.g. ControllerRevision.data): untyped object
        return {"type": "object",
                "additionalProperties":
                    _openapi_type(args[1]) if len(args) == 2 else {}}
    if origin is typing.Union:  # Optional[X]
        inner = [a for a in typing.get_args(t) if a is not type(None)]
        return _openapi_type(inner[0]) if inner else {}
    if t is str:
        return {"type": "string"}
    if t is int:
        return {"type": "integer"}
    if t is float:
        return {"type": "number"}
    if t is bool:
        return {"type": "boolean"}
    if dataclasses.is_dataclass(t):
        return {"$ref": f"#/definitions/{t.__name__}"}
    return {}


_openapi_cache: Dict[frozenset, dict] = {}


def _openapi_spec() -> dict:
    """Swagger 2.0 document over every registered kind (definitions from
    dataclass reflection; paths list the CRUD routes the REST mapper
    serves). Cached per registered-kind set — the reflection walk is
    dozens of types deep and kinds only change on CRD (de)registration."""
    import dataclasses
    import typing

    cache_key = frozenset(scheme.all_kinds())
    hit = _openapi_cache.get(cache_key)
    if hit is not None:
        return hit

    definitions: Dict[str, dict] = {}

    def add_def(t):
        name = t.__name__
        if name in definitions or not dataclasses.is_dataclass(t):
            return
        definitions[name] = {"type": "object", "properties": {}}
        try:
            hints = typing.get_type_hints(t)
        except Exception:
            hints = {f.name: f.type for f in dataclasses.fields(t)}
        for f in dataclasses.fields(t):
            ft = hints.get(f.name, f.type)
            definitions[name]["properties"][f.name] = _openapi_type(ft)
            for sub in _walk_types(ft):
                add_def(sub)

    def _walk_types(t):
        origin = typing.get_origin(t)
        if origin in (list, dict):
            for a in typing.get_args(t):
                yield from _walk_types(a)
        elif origin is typing.Union:
            for a in typing.get_args(t):
                if a is not type(None):
                    yield from _walk_types(a)
        elif dataclasses.is_dataclass(t):
            yield t
        return

    paths = {}
    for kind in sorted(scheme.all_kinds()):
        typ = scheme.type_for_kind(kind)
        add_def(typ)
        plural = scheme.plural_for_kind(kind)
        gv = scheme.api_version_for(kind)
        prefix = (f"/api/{gv}" if "/" not in gv else f"/apis/{gv}")
        base = (f"{prefix}/namespaces/{{namespace}}/{plural}"
                if scheme.is_namespaced(kind) else f"{prefix}/{plural}")
        ref = {"$ref": f"#/definitions/{typ.__name__}"}
        paths[base] = {"get": {"responses": {"200": {}}},
                       "post": {"parameters": [{"in": "body",
                                               "schema": ref}],
                                "responses": {"201": {}}}}
        paths[base + "/{name}"] = {
            "get": {"responses": {"200": {"schema": ref}}},
            "put": {"responses": {"200": {}}},
            "delete": {"responses": {"200": {}}}}
    spec = {"swagger": "2.0",
            "info": {"title": "kubernetes_tpu", "version": "v1.11-tpu"},
            "paths": paths, "definitions": definitions}
    _openapi_cache.clear()  # one live entry: kind-set changes are rare
    _openapi_cache[cache_key] = spec
    return spec


class APIServer:
    def __init__(self, store: ObjectStore,
                 authenticator: Optional[TokenAuthenticator] = None,
                 authorizer: Optional[RBACAuthorizer] = None,
                 admission: Optional[AdmissionChain] = None,
                 audit_sink: Optional[Callable[[dict], None]] = None,
                 metrics_providers: Optional[List[Callable[[], str]]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 reconcile_endpoints: bool = False,
                 max_in_flight: int = 0, max_mutating_in_flight: int = 0,
                 audit_policy: str = "Metadata", tls=None):
        self.store = store
        self.broadcaster = Broadcaster(store)
        self.authenticator = authenticator
        self.authorizer = authorizer
        self.admission = admission if admission is not None else AdmissionChain()
        self.audit_sink = audit_sink
        self.metrics_providers = metrics_providers or []
        self.request_count: Dict[str, int] = {}
        self._count_lock = threading.Lock()
        self._reconcile_endpoints = reconcile_endpoints
        self.endpoint_reconciler = None
        # flow control (filters/maxinflight.go): bounded concurrent
        # requests, split readonly/mutating; saturation -> 429
        self._readonly_sem = (threading.BoundedSemaphore(max_in_flight)
                              if max_in_flight > 0 else None)
        self._mutating_sem = (
            threading.BoundedSemaphore(max_mutating_in_flight)
            if max_mutating_in_flight > 0 else None)
        # audit policy level (auditpolicy: "None" disables the sink,
        # "Metadata" records verb/resource/user — the reference's levels
        # minus request-body capture)
        self.audit_policy = audit_policy
        # CRD-lite (apiextensions-apiserver): creating a
        # CustomResourceDefinition registers its kind in the scheme so
        # /apis/<group>/<version>/<plural> CRUD+watch routes resolve;
        # deleting it unregisters. Pre-existing CRDs (durable store
        # restart) register during the informer's initial list.
        from ..runtime.informer import SharedInformer

        def _crd_add(crd):
            try:
                scheme.register_dynamic(crd)
            except ValueError:
                pass  # conflicting CRD written by a direct store writer

        def _crd_update(old, new):
            if old.spec.names.kind != new.spec.names.kind:
                scheme.unregister(old.spec.names.kind)
            try:
                scheme.register_dynamic(new, replacing=old.spec.names.kind)
            except ValueError:
                pass  # conflicting CRD from a direct store writer

        self._crd_informer = SharedInformer(store, "customresourcedefinitions")
        self._crd_informer.add_event_handler(
            on_add=_crd_add, on_update=_crd_update,
            on_delete=lambda crd: scheme.unregister(crd.spec.names.kind))

        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence default stderr logging
                pass

            def _dispatch(self):
                try:
                    server._handle(self)
                except APIError as e:
                    self._send(e.code, _status_body(e.code, e.reason, e.message))
                except BrokenPipeError:
                    pass
                except Exception as e:  # 500 InternalError
                    self._send(500, _status_body(500, "InternalError", repr(e)))

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _dispatch

            def _send(self, code: int, body: bytes,
                      content_type: str = "application/json"):
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", content_type)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except BrokenPipeError:
                    pass

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        # tls: a pki.ClusterCA. Serve HTTPS with a CA-issued serving
        # cert; client certs are verified by the handshake against the
        # same CA and their subject becomes the request's x509 identity
        # (authentication/request/x509/x509.go:76 reads the verified
        # peer chain from the TLS layer — the real thing, not a header).
        self._tls = tls
        self._kubelet_client_ctx = None
        if tls is not None:
            from . import pki

            key_pem, cert_pem = pki.issue_server_cert(
                tls, "kube-apiserver",
                dns_sans=("localhost", "kubernetes", "kubernetes.default",
                          "kubernetes.default.svc"),
                ip_sans=("127.0.0.1",))
            pki.wrap_http_server(self.httpd, pki.server_ssl_context(
                tls.ca_cert_pem, cert_pem, key_pem))
            # the apiserver is itself a TLS CLIENT toward kubelets (the
            # exec/log proxy); kubelet servers demand a CA-issued client
            # cert, so mint the kubelet-client identity the reference
            # keeps in apiserver-kubelet-client.crt
            ck_pem, ccsr = pki.make_csr("kube-apiserver",
                                        ("system:masters",))
            self._kubelet_client_ctx = pki.client_ssl_context(
                tls.ca_cert_pem, tls.sign_csr(ccsr), ck_pem)
        scheme_str = "https" if tls is not None else "http"
        self.url = f"{scheme_str}://{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "APIServer":
        self._bootstrap_priority_classes()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="apiserver", daemon=True)
        self._thread.start()
        if self._reconcile_endpoints:
            # HA scale-out: publish this replica in the shared
            # "kubernetes" Endpoints under a lease (master.go:199-248)
            from .reconciler import EndpointReconciler

            host, port = self.httpd.server_address[:2]
            # host:port as the replica identity — unlike the reference's
            # one-IP-per-master assumption, in-process replicas share the
            # host and differ by port
            self.endpoint_reconciler = EndpointReconciler(
                self.store, f"{host}:{port}", port).start()
        return self

    def stop(self):
        if self.endpoint_reconciler is not None:
            self.endpoint_reconciler.stop()
            self.endpoint_reconciler = None
        self.httpd.shutdown()
        self.httpd.server_close()
        # deregister this server's store watchers: a replaced apiserver
        # (kubeadm upgrade) must not keep consuming every event through
        # its dead broadcaster/CRD informer forever
        unwatch = getattr(self.store, "unwatch", None)
        if unwatch is not None:
            unwatch(self.broadcaster._on_event)
            unwatch(self._crd_informer._handle)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- handler chain ---------------------------------------------------------

    def _handle(self, h):
        parsed = urlparse(h.path)
        parts = [p for p in parsed.path.split("/") if p]
        query = parse_qs(parsed.query)

        # authn runs first — ops endpoints bypass authz/admission but not
        # authentication (healthz stays open, like the reference's
        # always-allowed /healthz delegating authorizer path)
        user = None
        if self.authenticator is not None and parts != ["healthz"]:
            peer = None
            if self._tls is not None:
                from . import pki

                peer = pki.peer_identity(h.connection)
            auth_req = getattr(self.authenticator, "authenticate_request",
                               None)
            user = (auth_req(h.headers, peer) if auth_req is not None else
                    self.authenticator.authenticate(
                        h.headers.get("Authorization")))
            if user is None:
                raise APIError(401, "Unauthorized", "authentication failed")
        if parts == ["healthz"]:
            return h._send(200, b"ok", "text/plain")
        if parts == ["version"]:
            return h._send(200, json.dumps(
                {"major": "1", "minor": "11", "gitVersion": "v1.11.0-tpu"}).encode())
        if parts == ["metrics"]:
            text = self._metrics_text()
            return h._send(200, text.encode(), "text/plain")
        if parts == ["api"]:
            return h._send(200, json.dumps({"kind": "APIVersions",
                                            "versions": ["v1"]}).encode())
        if parts == ["apis"]:
            groups = sorted({gv.split("/")[0]
                             for k in scheme.all_kinds()
                             for gv in scheme.served_versions(k)
                             if "/" in gv})
            return h._send(200, json.dumps({"kind": "APIGroupList",
                                            "groups": groups}).encode())
        if parts == ["openapi", "v2"]:
            # OpenAPI v2 spec generated from the dataclass model
            # (apiserver's /openapi/v2, k8s.io/kube-openapi; consumed by
            # kubectl explain/validation in the reference)
            return h._send(200, json.dumps(_openapi_spec()).encode())
        # per-group resource discovery (endpoints/installer.go's
        # APIResourceList; what a RESTMapper consumes)
        gv = None
        if len(parts) == 2 and parts[0] == "api":
            gv = parts[1]
        elif len(parts) == 3 and parts[0] == "apis":
            gv = f"{parts[1]}/{parts[2]}"
        if gv is not None and h.command == "GET":
            resources = [
                {"name": scheme.plural_for_kind(k), "kind": k,
                 "namespaced": scheme.is_namespaced(k)}
                for k in sorted(scheme.all_kinds())
                if scheme.serves(k, gv)]
            if resources:
                return h._send(200, json.dumps(
                    {"kind": "APIResourceList", "groupVersion": gv,
                     "resources": resources}).encode())

        route = self._route(parts)
        if route is None:
            # aggregation (kube-aggregator): an APIService claiming this
            # group/version proxies the request to its backing service
            # (pkg/apiserver/handler_proxy.go). The aggregator sits
            # BEHIND the standard filters: authz, flow control, and
            # audit all apply before the proxy hop.
            backend = (self._aggregated_backend(parts)
                       if len(parts) >= 3 and parts[0] == "apis" else None)
            if backend is not None:
                # attribute extraction mirrors _route: the RBAC resource
                # is the aggregated plural, not the 'namespaces' path
                # segment, and a collection GET authorizes as 'list'
                rest = parts[3:]
                res_ns = None
                if rest and rest[0] == "namespaces" and len(rest) >= 3:
                    res_ns, rest = rest[1], rest[2:]
                plural = rest[0] if rest else None
                name = rest[1] if len(rest) > 1 else None
                verb = _VERBS[h.command]
                if verb == "get" and name is None:
                    verb = "list"
                if plural is None:
                    # group-root (/apis/<group>/<version>) is a
                    # nonResourceURL in the reference, not a resource named
                    # after the group. GET discovery is granted to every
                    # subject (the system:discovery bootstrap binding covers
                    # authenticated AND unauthenticated in 1.11); any other
                    # verb must still be authorized, against the path
                    plural = "/" + "/".join(parts)
                    if (verb not in ("get", "list")
                            and self.authorizer is not None
                            and user is not None
                            and not self.authorizer.authorize(
                                user, verb, plural, namespace=res_ns,
                                name=name)):
                        raise APIError(403, "Forbidden",
                                       f"user {user.name} cannot {verb} "
                                       f"{plural}")
                elif self.authorizer is not None and user is not None:
                    if not self.authorizer.authorize(user, verb, plural,
                                                     namespace=res_ns,
                                                     name=name):
                        raise APIError(403, "Forbidden",
                                       f"user {user.name} cannot {verb} "
                                       f"{plural}")
                sem = (self._readonly_sem if verb in ("get", "list")
                       else self._mutating_sem)
                if sem is not None and not sem.acquire(blocking=False):
                    raise APIError(429, "TooManyRequests",
                                   "server request limit reached, retry later")
                try:
                    self._audit(user, verb, plural, res_ns, name)
                    return self._serve_aggregated(h, backend, parsed)
                finally:
                    if sem is not None:
                        sem.release()
            raise APIError(404, "NotFound", f"path {parsed.path!r} not found")
        plural, namespace, name, sub, gv = route
        verb = _VERBS[h.command]
        if verb == "get" and query.get("watch", ["false"])[0] == "true":
            verb = "watch"
        if verb == "get" and name is None:
            verb = "list"
        if verb == "delete" and name is None:
            # DELETE on a collection URL (installer.go maps it to the
            # "deletecollection" verb — its own RBAC attribute)
            verb = "deletecollection"

        # flow control: watches are long-lived and exempt (the reference
        # exempts them too, maxinflight.go:49)
        sem = None
        if verb != "watch":
            # nonMutatingRequestVerbs is exactly get/list/watch
            # (maxinflight.go): patch and the subresource writes are
            # mutating
            sem = (self._readonly_sem if verb in ("get", "list") else
                   self._mutating_sem)
        if sem is not None and not sem.acquire(blocking=False):
            raise APIError(429, "TooManyRequests",
                           "server request limit reached, retry later")
        try:
            return self._serve_authorized(h, query, user, plural, namespace,
                                          name, sub, verb, gv)
        finally:
            if sem is not None:
                sem.release()

    def _serve_authorized(self, h, query, user, plural, namespace, name,
                          sub, verb, gv=None):

        # authz (filters/authorization.go) — namespace/name make
        # namespaced Roles and resourceNames evaluable; subresources
        # authorize as their own attribute ("pods/exec", "pods/status")
        # so a create-pods grant does NOT imply exec into pods
        attr = f"{plural}/{sub}" if sub else plural
        if self.authorizer is not None and user is not None:
            if not self.authorizer.authorize(user, verb, attr,
                                             namespace=namespace, name=name):
                raise APIError(403, "Forbidden",
                               f"user {user.name} cannot {verb} {attr}")

        with self._count_lock:
            key = f"{verb}:{plural}"
            self.request_count[key] = self.request_count.get(key, 0) + 1

        self._audit(user, verb, plural, namespace, name)

        if verb == "watch":
            return self._serve_watch(h, plural, query, gv)
        if verb == "list":
            return self._serve_list(h, plural, namespace, query, gv)
        if verb == "get":
            if sub == "log" and plural == "pods":
                return self._serve_pod_log(h, namespace, name, query)
            if sub == "attach" and plural == "pods":
                return self._serve_pod_attach(h, namespace, name, query)
            if sub == "scale":
                return self._serve_scale(h, plural, namespace, name, user,
                                         write=False)
            return self._serve_get(h, plural, namespace, name, gv)
        if verb == "create":
            if sub == "binding":
                return self._serve_binding(h, namespace, name)
            if sub == "eviction":
                return self._serve_eviction(h, user, namespace, name)
            if sub == "exec" and plural == "pods":
                return self._serve_pod_exec(h, namespace, name)
            if sub == "portforward" and plural == "pods":
                return self._serve_pod_portforward(h, namespace, name)
            return self._serve_create(h, plural, namespace, user, gv)
        if verb in ("update", "patch"):
            if sub == "scale":
                return self._serve_scale(h, plural, namespace, name, user,
                                         write=True)
            return self._serve_update(h, plural, namespace, name, sub, user,
                                      patch=(verb == "patch"), gv=gv)
        if verb == "delete":
            return self._serve_delete(h, plural, namespace, name, user,
                                      query=query)
        if verb == "deletecollection":
            return self._serve_delete_collection(h, plural, namespace,
                                                 query, user)
        raise APIError(405, "MethodNotAllowed", f"{h.command} unsupported")

    # -- kubelet proxy subresources (pods/<name>/log, /exec) -------------------

    def _kubelet_target(self, namespace, name):
        """Resolve a pod's kubelet serving endpoint through its Node's
        daemon endpoint (registry/core/pod/rest/log.go LogLocation ->
        pod.Spec.NodeName -> NodeDaemonEndpoints)."""
        pod = self._find("pods", namespace, name)
        if pod is None:
            raise APIError(404, "NotFound", f"pod {name!r} not found")
        if not pod.spec.node_name:
            raise APIError(400, "BadRequest",
                           f"pod {name!r} is not scheduled to a node")
        from ..utils.net import node_daemon_endpoint

        ep = node_daemon_endpoint(self.store, pod.spec.node_name)
        if ep is None:
            raise APIError(400, "BadRequest",
                           f"node {pod.spec.node_name!r} does not expose "
                           f"a kubelet endpoint")
        container = (pod.spec.containers[0].name
                     if pod.spec.containers else "")
        return pod, ep[0], ep[1], container

    def _kubelet_proxy(self, h, method, host, port, path, body=None,
                       timeout: float = 10.0):
        import http.client

        if self._kubelet_client_ctx is not None:
            conn = http.client.HTTPSConnection(
                host, port, timeout=timeout,
                context=self._kubelet_client_ctx)
        else:
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request(method, path, body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            h._send(resp.status, data,
                    resp.getheader("Content-Type", "text/plain"))
            return True
        except OSError as e:
            hint = ""
            if self._kubelet_client_ctx is not None:
                # a TLS cluster requires TLS kubelets (the reference's
                # kubelet always serves HTTPS); a plain-HTTP kubelet
                # registered in a secure cluster fails the handshake here
                hint = (" (secure cluster: the kubelet must serve TLS — "
                        "Kubelet.serve(tls=cluster_ca))")
            raise APIError(503, "ServiceUnavailable",
                           f"kubelet unreachable: {e}{hint}")
        finally:
            conn.close()

    def _serve_pod_log(self, h, namespace, name, query):
        """GET pods/<name>/log — proxied to the kubelet's
        /containerLogs/<ns>/<pod>/<container> (pod/rest/log.go)."""
        pod, host, port, default_c = self._kubelet_target(namespace, name)
        container = query.get("container", [default_c])[0]
        tail = query.get("tailLines", [None])[0]
        if tail is not None:
            try:
                int(tail)
            except ValueError:
                raise APIError(400, "BadRequest",
                               f"tailLines {tail!r} is not an integer")
        # quote: the container name is client-controlled — unescaped
        # '/', '?', '#' would rewrite the proxied kubelet path
        path = (f"/containerLogs/{quote(pod.metadata.namespace, safe='')}/"
                f"{quote(pod.metadata.name, safe='')}/"
                f"{quote(container, safe='')}")
        params = []
        if tail:
            params.append(f"tailLines={tail}")
        if query.get("previous", ["false"])[0] == "true":
            params.append("previous=true")
        if params:
            path += "?" + "&".join(params)
        return self._kubelet_proxy(h, "GET", host, port, path)

    def _serve_pod_exec(self, h, namespace, name):
        """POST pods/<name>/exec — proxied to the kubelet's /exec
        (server.go:325 getExec; one-shot JSON here, not SPDY). Admission
        runs on the subresource attribute (DenyEscalatingExec gates
        privileged pods, plugin/pkg/admission/exec)."""
        pod, host, port, default_c = self._kubelet_target(namespace, name)
        try:
            self.admission.admit("create", "pods/exec", pod, None, None,
                                 self.store)
        except AdmissionError as e:
            raise APIError(getattr(e, "code", 403), "Forbidden", str(e))
        data = self._read_body(h)
        container = data.get("container") or default_c
        path = (f"/exec/{quote(pod.metadata.namespace, safe='')}/"
                f"{quote(pod.metadata.name, safe='')}/"
                f"{quote(str(container), safe='')}")
        return self._kubelet_proxy(h, "POST", host, port, path,
                                   body=json.dumps(
                                       {"command": data.get("command"),
                                        "stdin": data.get("stdin")}))

    def _serve_pod_attach(self, h, namespace, name, query):
        """GET pods/<name>/attach — proxied to the kubelet's /attach
        long-poll (server.go:640 getAttach; SPDY collapsed to follow-mode
        polling, see kubelet/server.py)."""
        pod, host, port, default_c = self._kubelet_target(namespace, name)
        try:
            self.admission.admit("create", "pods/attach", pod, None, None,
                                 self.store)
        except AdmissionError as e:
            raise APIError(getattr(e, "code", 403), "Forbidden", str(e))
        container = query.get("container", [default_c])[0]
        q = []
        wait = 2.0
        for key in ("since", "waitSeconds"):
            v = query.get(key, [None])[0]
            if v is not None:
                if key == "waitSeconds":
                    try:
                        wait = min(float(v), 30.0)
                    except ValueError:
                        raise APIError(400, "BadRequest",
                                       f"waitSeconds {v!r} is not a number")
                q.append(f"{key}={quote(v, safe='')}")
        path = (f"/attach/{quote(pod.metadata.namespace, safe='')}/"
                f"{quote(pod.metadata.name, safe='')}/"
                f"{quote(container, safe='')}")
        if q:
            path += "?" + "&".join(q)
        # the proxy must outlive the kubelet's long-poll window or an
        # idle container turns into a bogus 503 at waitSeconds > 10
        return self._kubelet_proxy(h, "GET", host, port, path,
                                   timeout=wait + 10.0)

    def _serve_pod_portforward(self, h, namespace, name):
        """POST pods/<name>/portforward — proxied to the kubelet, which
        opens a TCP relay to the pod's listener and returns its address
        (server.go:751 getPortForward; the SPDY data channel is a real
        TCP relay here, so bytes genuinely flow end to end)."""
        pod, host, port, _c = self._kubelet_target(namespace, name)
        data = self._read_body(h)
        path = (f"/portForward/{quote(pod.metadata.namespace, safe='')}/"
                f"{quote(pod.metadata.name, safe='')}")
        return self._kubelet_proxy(h, "POST", host, port, path,
                                   body=json.dumps(
                                       {"port": data.get("port")}))

    # -- aggregation (kube-aggregator) -----------------------------------------

    def _aggregated_backend(self, parts):
        """APIServiceSpec claiming /apis/<group>/<version>, or None."""
        group, version = parts[1], parts[2]
        for apisvc in self.store.list("apiservices"):
            if (apisvc.spec.group == group
                    and apisvc.spec.version == version
                    and apisvc.spec.service_name):
                return apisvc.spec
        return None

    def _serve_aggregated(self, h, svc_ref, parsed):
        """Proxy /apis/<group>/<version>/... to the APIService's backing
        service endpoints (handler_proxy.go:109 ServeHTTP: resolve the
        service, forward verbatim, relay the response). svc_ref is the
        APIServiceSpec resolved by the caller — re-resolving here could
        race a concurrent APIService deletion into a 500."""
        group, version = svc_ref.group, svc_ref.version
        ep = self.store.get("endpoints", svc_ref.service_namespace,
                            svc_ref.service_name)
        # pick the subset port matching the APIService's service_port
        # (handler_proxy.go resolves the named/numbered service port, not
        # blindly the first one); fall back to the declared port itself
        backends = [(a.ip, next((p.port for p in s.ports
                                 if p.port == svc_ref.service_port),
                                next((p.port for p in s.ports),
                                     svc_ref.service_port)))
                    for s in (ep.subsets if ep else [])
                    for a in s.addresses]
        if not backends:
            raise APIError(503, "ServiceUnavailable",
                           f"no endpoints for aggregated API "
                           f"{version}.{group}")
        host, port = backends[0]
        import http.client

        body = b""
        length = int(h.headers.get("Content-Length") or 0)
        if length:
            body = h.rfile.read(length)
        conn = http.client.HTTPConnection(host, port, timeout=10)
        try:
            url = parsed.path + (f"?{parsed.query}" if parsed.query else "")
            conn.request(h.command, url, body=body or None,
                         headers={"Content-Type":
                                  h.headers.get("Content-Type",
                                                "application/json")})
            resp = conn.getresponse()
            data = resp.read()
            h._send(resp.status, data,
                    resp.getheader("Content-Type", "application/json"))
            return True
        except OSError as e:
            raise APIError(503, "ServiceUnavailable",
                           f"aggregated API backend unreachable: {e}")
        finally:
            conn.close()

    # -- routing ---------------------------------------------------------------

    def _route(self, parts: List[str]):
        """path segments -> (plural, namespace, name, subresource,
        requested groupVersion). A plural addressed under a groupVersion
        its kind is not served at does not route (404 — the reference's
        installer only registers served versions)."""
        if len(parts) >= 2 and parts[0] == "api" and parts[1] == "v1":
            rest, gv = parts[2:], "v1"
        elif len(parts) >= 3 and parts[0] == "apis":
            rest, gv = parts[3:], f"{parts[1]}/{parts[2]}"
        else:
            return None
        if not rest:
            return None
        if rest[0] == "namespaces" and len(rest) >= 3:
            ns, rest2 = rest[1], rest[2:]
        else:
            ns, rest2 = None, rest
        plural = rest2[0]
        kind = scheme.kind_for_plural(plural)
        if kind is None or not scheme.serves(kind, gv):
            return None
        name = rest2[1] if len(rest2) > 1 else None
        sub = rest2[2] if len(rest2) > 2 else None
        return plural, ns, name, sub, gv

    def _find(self, plural: str, namespace: Optional[str], name: str):
        kind = scheme.kind_for_plural(plural)
        for ns in ([namespace] if namespace is not None
                   else ["default", ""]):
            obj = self.store.get(plural, ns, name)
            if obj is not None:
                return obj
        if namespace is not None and not scheme.is_namespaced(kind):
            for ns in ("default", ""):
                obj = self.store.get(plural, ns, name)
                if obj is not None:
                    return obj
        return None

    def _bootstrap_priority_classes(self):
        """The built-in system PriorityClasses every cluster serves
        (registry/scheduling/rest/storage_scheduling.go
        PostStartHook: system-node-critical 2000001000,
        system-cluster-critical 2000000000) — control-plane pods name
        them and the kubelet's critical-pod preemption keys off their
        values."""
        for name, value in (("system-node-critical", 2_000_001_000),
                            ("system-cluster-critical", 2_000_000_000)):
            try:
                self.store.create("priorityclasses", api.PriorityClass(
                    metadata=api.ObjectMeta(name=name, namespace=""),
                    value=value,
                    description="Built-in system priority class"))
            except Conflict:
                pass  # already bootstrapped (durable store restart)

    # -- custom resource validation/subresources -------------------------------

    def _crd_for_kind(self, kind: str):
        from ..api import scale as scaleapi

        return scaleapi.crd_for_kind(self.store, kind)

    @staticmethod
    def _check_crd_schema(crd):
        """Structural 422 for a CRD's openAPIV3Schema and subresource
        declarations — one gate for create AND update (a replace must
        not smuggle in the broken pattern create would have refused)."""
        sub = crd.spec.subresources
        if sub is not None and sub.scale is not None:
            # apiextensions validation.go ValidateCustomResourceDefinition
            # Subresources: the dotted replica paths must live under
            # .spec/.status — anything else would make every /scale write
            # a silent no-op (dotted_set grafts into a dead branch) while
            # the HPA retry-loops against it
            sc = sub.scale

            def _under(path, root):
                # dot-boundary check: '.specSelector.n' must NOT pass as
                # being under '.spec'
                return path == root or (path or "").startswith(root + ".")

            if not _under(sc.spec_replicas_path, ".spec"):
                raise APIError(
                    422, "Invalid",
                    f"spec.subresources.scale.specReplicasPath: "
                    f"{sc.spec_replicas_path!r} must begin with .spec")
            if not _under(sc.status_replicas_path, ".status"):
                raise APIError(
                    422, "Invalid",
                    f"spec.subresources.scale.statusReplicasPath: "
                    f"{sc.status_replicas_path!r} must begin with .status")
        if crd.spec.validation is None:
            return
        from ..api.crdschema import schema_errors

        serrs = schema_errors(crd.spec.validation.open_api_v3_schema)
        if serrs:
            raise APIError(422, "Invalid",
                           "; ".join(f"{p}: {m}" for p, m in serrs))

    def _validate_custom(self, obj, crd):
        """CustomResourceValidation enforcement: the whole wire object
        is checked against the CRD's openAPIV3Schema; failures are
        field-addressed 422s like built-in kinds
        (apiextensions-apiserver pkg/apiserver/validation)."""
        if crd is None or crd.spec.validation is None:
            return
        from ..api.crdschema import validate_schema

        wire = scheme.encode_object(obj)
        errors = validate_schema(
            wire, crd.spec.validation.open_api_v3_schema)
        if errors:
            msg = "; ".join(f"{p}: {m}" for p, m in errors)
            raise APIError(422, "Invalid",
                           f"{obj.kind} {obj.metadata.name!r} is invalid: "
                           f"{msg}")

    # -- scale subresource -----------------------------------------------------

    def _scale_mapping(self, plural, obj):
        """-> (spec_path, status_path, selector_str) or None when the
        kind has no scale subresource (shared mapping: api/scale.py)."""
        from ..api import scale as scaleapi

        return scaleapi.mapping_for(self.store, plural, obj)

    def _scale_wire(self, obj, plural, mapping):
        from ..api import scale as scaleapi

        spec_path, status_path, sel = mapping
        wire = scheme.encode_object(obj)
        status = {"replicas": scaleapi.dotted_get(wire, status_path, 0) or 0}
        if sel:
            status["selector"] = sel
        return {
            "kind": "Scale", "apiVersion": "autoscaling/v1",
            "metadata": {"name": obj.metadata.name,
                         "namespace": obj.metadata.namespace,
                         "resourceVersion":
                             obj.metadata.resource_version},
            "spec": {"replicas":
                     scaleapi.dotted_get(wire, spec_path, 0) or 0},
            "status": status,
        }

    def _serve_scale(self, h, plural, namespace, name, user, write):
        """GET/PUT <plural>/<name>/scale: the polymorphic Scale
        subresource every scalable kind serves
        (registry ScaleREST Get/Update)."""
        obj = self._find(plural, namespace, name)
        if obj is None:
            raise APIError(404, "NotFound", f"{plural} {name!r} not found")
        mapping = self._scale_mapping(plural, obj)
        if mapping is None:
            raise APIError(
                404, "NotFound",
                f"the server could not find the requested resource "
                f"({plural}/{name}/scale)")
        if write:
            import copy

            body = self._read_body(h)
            want = body.get("spec", {}).get("replicas")
            if not isinstance(want, int) or want < 0:
                raise APIError(422, "Invalid",
                               "spec.replicas must be a non-negative "
                               "integer")
            rv = body.get("metadata", {}).get("resourceVersion")
            if rv and str(rv) != str(obj.metadata.resource_version):
                raise APIError(409, "Conflict",
                               f"resourceVersion {rv} != "
                               f"{obj.metadata.resource_version}")
            # mutate a CLONE: the stored object must not change until
            # admission + validation admit the write (a rejected scale
            # must leave the store untouched, like every other verb)
            from ..api import scale as scaleapi

            new = copy.deepcopy(obj)
            scaleapi.set_spec_replicas(new, mapping[0], want)
            try:
                self.admission.admit("update", plural, new, obj, user,
                                     self.store)
            except AdmissionError as e:
                raise APIError(getattr(e, "code", 403), "Forbidden", str(e))
            # the scale path enforces the SAME rules as a direct update:
            # schema caps on CRs, field validation on built-ins
            if isinstance(new, api.CustomObject):
                self._validate_custom(new, self._crd_for_kind(new.kind))
            else:
                errs = validation.validate(plural, new, old=obj)
                if errs:
                    raise APIError(422, "Invalid", errs.message())
            try:
                self.store.update(plural, new)
            except Conflict as e:
                raise APIError(409, "Conflict", str(e))
            except KeyError:
                raise APIError(404, "NotFound",
                               f"{plural} {name!r} not found")
            obj = new
        return h._send(200, json.dumps(
            self._scale_wire(obj, plural, mapping)).encode())

    # -- verbs -----------------------------------------------------------------

    @staticmethod
    def _filter_by_selectors(objs, query):
        """?labelSelector / ?fieldSelector filtering, shared by list and
        deletecollection (the reference routes both through the same
        storage predicate)."""
        sel = query.get("labelSelector", [None])[0]
        if sel:
            from ..api.labels import Selector

            # full set-based syntax (labels.Parse: =, !=, in, notin,
            # exists, !key); malformed selectors are client errors
            try:
                parsed = Selector.parse(sel)
            except ValueError:
                raise APIError(400, "BadRequest",
                               f"unparseable labelSelector {sel!r}")
            objs = [o for o in objs
                    if parsed.matches(o.metadata.labels or {})]
        fsel = query.get("fieldSelector", [None])[0]
        if fsel:
            for kv in fsel.split(","):
                k, _, v = kv.partition("=")
                if k == "spec.nodeName":
                    # non-pod kinds have no spec.nodeName: match nothing
                    # rather than 500 on the attribute access
                    objs = [o for o in objs
                            if getattr(getattr(o, "spec", None),
                                       "node_name", None) == v]
                elif k == "metadata.name":
                    objs = [o for o in objs if o.metadata.name == v]
                elif k == "metadata.namespace":
                    objs = [o for o in objs
                            if o.metadata.namespace == v]
                elif k == "status.phase":
                    objs = [o for o in objs
                            if getattr(getattr(o, "status", None),
                                       "phase", None) == v]
                else:
                    raise APIError(400, "BadRequest",
                                   f"unsupported fieldSelector {k!r}")
        return objs

    def _serve_list(self, h, plural, namespace, query, gv=None):
        # items and resourceVersion must come from ONE store view: read
        # separately, a write landing between them yields a list whose
        # rv claims to cover objects it does not contain — a reflector
        # then watches from that rv and the missed writes are invisible
        # until a forced relist (the exact silent-wedge the watch-stream
        # staleness watchdog exists to break, but the server must not
        # manufacture it)
        with self.store._lock:
            listed = self.store.list(plural, namespace)
            list_rv = self.store.latest_resource_version
        objs = self._filter_by_selectors(listed, query)
        kind = scheme.kind_for_plural(plural)
        # APIListChunking (1.11 beta; apiserver/pkg/storage continue
        # tokens): ?limit=N pages a deterministic (namespace, name)
        # ordering, ?continue resumes strictly after the token's last
        # key — the same key-range resumption etcd pagination gives the
        # reference (objects created mid-walk before the cursor are
        # skipped, after it are included; no duplicates either way).
        cont_out = None
        limit = query.get("limit", [None])[0]
        cont_in = query.get("continue", [None])[0]
        if limit is not None or cont_in:
            import base64

            objs = sorted(objs, key=lambda o: (o.metadata.namespace or "",
                                               o.metadata.name))
            if cont_in:
                try:
                    last_ns, _, last_name = base64.urlsafe_b64decode(
                        cont_in.encode()).decode().partition("/")
                except Exception:
                    raise APIError(400, "BadRequest",
                                   "malformed continue token")
                objs = [o for o in objs
                        if ((o.metadata.namespace or ""), o.metadata.name)
                        > (last_ns, last_name)]
            if limit is not None:
                try:
                    n = int(limit)
                except ValueError:
                    raise APIError(400, "BadRequest",
                                   f"invalid limit {limit!r}")
                if 0 < n < len(objs):
                    last = objs[n - 1]
                    cont_out = base64.urlsafe_b64encode(
                        f"{last.metadata.namespace or ''}/"
                        f"{last.metadata.name}".encode()).decode()
                    objs = objs[:n]
        if self._wants_binary(h) and self._binary_ok(kind, gv) \
                and cont_out is None:
            from ..api import binary

            h._send(200, binary.dumps_list(kind, objs, list_rv),
                    content_type=binary.CONTENT_TYPE)
            return
        meta = {"resourceVersion": str(list_rv)}
        if cont_out:
            meta["continue"] = cont_out
        body = json.dumps({
            "kind": kind + "List",
            "apiVersion": gv or scheme.api_version_for(kind),
            "metadata": meta,
            "items": [scheme.encode_object(o, version=gv)
                      for o in objs]}).encode()
        h._send(200, body)

    @staticmethod
    def _wants_binary(h) -> bool:
        """Content negotiation (the reference negotiates
        application/vnd.kubernetes.protobuf the same way)."""
        from ..api import binary

        return binary.CONTENT_TYPE in (h.headers.get("Accept") or "")

    @staticmethod
    def _binary_ok(kind, gv) -> bool:
        """The binary codec writes hub-form objects only; a request at a
        converted version must get JSON (silently serving hub-tagged
        bytes would flip the served version on the Accept header)."""
        return gv is None or gv == scheme.api_version_for(kind)

    def _serve_get(self, h, plural, namespace, name, gv=None):
        obj = self._find(plural, namespace, name)
        if obj is None:
            raise APIError(404, "NotFound", f"{plural} {name!r} not found")
        if self._wants_binary(h) and \
                self._binary_ok(scheme.kind_for_plural(plural), gv):
            from ..api import binary

            h._send(200, binary.dumps(obj), content_type=binary.CONTENT_TYPE)
            return
        h._send(200, json.dumps(scheme.encode_object(obj, version=gv)).encode())

    def _read_body(self, h) -> dict:
        length = int(h.headers.get("Content-Length", 0))
        raw = h.rfile.read(length) if length else b"{}"
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise APIError(400, "BadRequest", f"invalid JSON: {e}")

    def _serve_create(self, h, plural, namespace, user, gv=None):
        kind = scheme.kind_for_plural(plural)
        data = self._read_body(h)
        data.setdefault("kind", kind)
        if gv is not None:
            # the path's groupVersion governs decoding; an untagged body
            # posted to a versioned path is that version (create.go
            # decodes with the request-scope kind)
            data.setdefault("apiVersion", gv)
        try:
            obj = scheme.decode_request(kind, data)
        except Exception as e:
            raise APIError(400, "BadRequest", f"cannot decode {kind}: {e}")
        if namespace is not None and scheme.is_namespaced(kind):
            obj.metadata.namespace = namespace
        if plural == "selfsubjectaccessreviews":
            # virtual resource: evaluated against the live authorizer,
            # never stored (registry/authorization/selfsubjectaccessreview/
            # rest.go:48). With no authorizer configured every request is
            # allowed, matching this server's open-by-default posture.
            ra = obj.spec.resource_attributes
            if self.authorizer is None or user is None:
                obj.status.allowed = True
                obj.status.reason = "no authorizer configured"
            else:
                obj.status.allowed = self.authorizer.authorize(
                    user, ra.verb, ra.resource, namespace=ra.namespace,
                    name=ra.name)
            return h._send(201, json.dumps(
                scheme.encode_object(obj, version=gv)).encode())
        if plural == "certificatesigningrequests" and user is not None:
            # the requestor identity is SERVER-stamped from the request
            # context, never client-claimed — INCLUDING anonymous: an
            # anonymous CSR carrying forged system:bootstrappers groups
            # must not reach the auto-approver (pkg/registry/certificates/
            # certificates/strategy.go PrepareForCreate)
            obj.spec.username = user.name
            obj.spec.groups = list(user.groups)
        try:
            self.admission.admit("create", plural, obj, None, user, self.store)
        except AdmissionError as e:
            code = getattr(e, "code", 403)
            raise APIError(code,
                           "TooManyRequests" if code == 429 else "Forbidden",
                           str(e))
        # validation runs AFTER admission mutators, like the registry
        # strategies' Validate (registry/core/pod/strategy.go:79); a bad
        # object reports every field error at once as a 422
        errs = validation.validate(plural, obj)
        if errs:
            raise APIError(422, "Invalid", errs.message())
        if isinstance(obj, api.CustomObject):
            crd = self._crd_for_kind(obj.kind)
            if crd is not None and crd.spec.subresources is not None and \
                    crd.spec.subresources.status:
                # status subresource enabled: the main resource never
                # accepts client status (apiextensions strategy
                # PrepareForCreate drops it) — BEFORE validation, so a
                # discarded status can't fail the create
                obj.status = {}
            self._validate_custom(obj, crd)
        if plural == "services":
            self._allocate_service(obj)
        if plural == "customresourcedefinitions":
            msg = scheme.crd_conflict(obj)
            if msg is not None:
                raise APIError(409, "AlreadyExists", msg)
            self._check_crd_schema(obj)
        try:
            self.store.create(plural, obj)
        except Conflict as e:
            raise APIError(409, "AlreadyExists", str(e))
        if plural == "customresourcedefinitions":
            # register synchronously too: with async event dispatch
            # (NativeObjectStore) the informer may run after this 201 is
            # sent, 404ing an immediately-following instance create;
            # register_dynamic is idempotent so the informer's later
            # delivery is harmless
            scheme.register_dynamic(obj)
        h._send(201, json.dumps(scheme.encode_object(obj, version=gv)).encode())

    # service-cluster-ip-range / --service-node-port-range defaults
    # (cmd/kube-apiserver/app/options: 10.0.0.0/24, 30000-32767)
    SERVICE_IP_PREFIX = "10.0.0."
    NODE_PORT_RANGE = (30000, 32767)

    def _allocate_service(self, svc):
        """Service REST allocation (registry/core/service/rest.go +
        ipallocator/portallocator): assign a free clusterIP unless
        headless ("None") or ExternalName; assign free NodePorts for
        NodePort/LoadBalancer ports. User-supplied values that collide
        with an existing allocation are 422s, like the reference's
        ErrAllocated path."""
        # exclusion is by IDENTITY (namespace, name) — never by uid: a
        # created manifest may carry a copied uid from `get -o yaml` of
        # another service, which must still collide
        me = (svc.metadata.namespace, svc.metadata.name)
        existing = [s for s in self.store.list("services")
                    if (s.metadata.namespace, s.metadata.name) != me]
        used_ips = {s.spec.cluster_ip for s in existing
                    if s.spec.cluster_ip not in ("", "None")}
        used_ports = {p.node_port for s in existing
                      for p in s.spec.ports if p.node_port}
        if svc.spec.type not in ("NodePort", "LoadBalancer"):
            # releasing a type change: stale nodePorts would otherwise
            # stay allocated forever (the reference clears them when the
            # type stops needing them)
            for p in svc.spec.ports:
                p.node_port = 0
        if svc.spec.type != "ExternalName" \
                and svc.spec.cluster_ip not in ("None",):
            if svc.spec.cluster_ip:
                if svc.spec.cluster_ip in used_ips:
                    raise APIError(
                        422, "Invalid",
                        f"spec.clusterIP: {svc.spec.cluster_ip} "
                        f"is already allocated")
            else:
                for i in range(1, 255):
                    ip = f"{self.SERVICE_IP_PREFIX}{i}"
                    if ip not in used_ips:
                        svc.spec.cluster_ip = ip
                        break
                else:
                    raise APIError(500, "InternalError",
                                   "service IP range exhausted")
        if svc.spec.type in ("NodePort", "LoadBalancer"):
            lo, hi = self.NODE_PORT_RANGE
            for p in svc.spec.ports:
                if p.node_port:
                    if p.node_port in used_ports:
                        raise APIError(
                            422, "Invalid",
                            f"spec.ports: nodePort {p.node_port} "
                            f"is already allocated")
                    used_ports.add(p.node_port)
            for p in svc.spec.ports:
                if not p.node_port:
                    for cand in range(lo, hi + 1):
                        if cand not in used_ports:
                            p.node_port = cand
                            used_ports.add(cand)
                            break
                    else:
                        raise APIError(500, "InternalError",
                                       "node port range exhausted")

    def _serve_update(self, h, plural, namespace, name, sub, user, patch,
                      gv=None):
        kind = scheme.kind_for_plural(plural)
        old = self._find(plural, namespace, name)
        if old is None:
            raise APIError(404, "NotFound", f"{plural} {name!r} not found")
        data = self._read_body(h)
        if gv is not None and not patch and sub in ("status", "finalize"):
            # subresource graft happens in HUB form below; a body sent at
            # a non-hub version must convert first or version-specific
            # fields would silently vanish into unknown hub keys
            kind_hub = scheme.api_version_for(kind)
            if gv != kind_hub:
                from ..api import conversion as _conv

                if not ({"status", "spec", "kind"} & set(data)):
                    data = {"status": data}  # bare-status body
                data.setdefault("apiVersion", gv)
                data = _conv.to_hub(kind, data, gv, kind_hub)
        if patch:
            # the patch applies against the object AS SERVED at the
            # request's version (patch.go works on versioned bytes), and
            # the merged result converts back through the hub
            merged = scheme.encode_object(old, version=gv)
            _merge_patch(merged, data)
            data = merged
        elif sub == "status":
            # status subresource: replace status, keep spec (registry
            # UpdateStatus strategy)
            full = scheme.encode_object(old)
            full["status"] = data.get("status", data)
            data = full
        elif sub == "finalize":
            full = scheme.encode_object(old)
            if "spec" in data:
                full["spec"] = data["spec"]
            data = full
        if gv is not None:
            data.setdefault("apiVersion", gv)
        try:
            obj = scheme.decode_request(kind, data)
        except Exception as e:
            raise APIError(400, "BadRequest", f"cannot decode {kind}: {e}")
        # optimistic concurrency: a nonzero stale resourceVersion is a 409
        # (GuaranteedUpdate / etcd3 ModRevision CAS, storage/etcd3/store.go:262)
        if obj.metadata.resource_version and \
                obj.metadata.resource_version != old.metadata.resource_version:
            raise APIError(409, "Conflict",
                           f"resourceVersion {obj.metadata.resource_version} "
                           f"!= {old.metadata.resource_version}")
        obj.metadata.namespace = old.metadata.namespace
        obj.metadata.name = old.metadata.name
        obj.metadata.uid = old.metadata.uid
        if plural == "certificatesigningrequests":
            # the requestor identity is SERVER-owned on update too
            # (strategy PrepareForUpdate copies it) — rewriting
            # spec.username would otherwise re-aim the self-node
            # approval check at someone else's identity
            obj.spec.username = old.spec.username
            obj.spec.groups = list(old.spec.groups)
        try:
            self.admission.admit("update", plural, obj, old, user, self.store)
        except AdmissionError as e:
            code = getattr(e, "code", 403)
            raise APIError(code,
                           "TooManyRequests" if code == 429 else "Forbidden",
                           str(e))
        if isinstance(obj, api.CustomObject):
            crd = self._crd_for_kind(obj.kind)
            subres = crd.spec.subresources if crd is not None else None
            if sub == "status" and (subres is None or not subres.status):
                # /status is only served once the CRD opts in
                # (apiextensions customresource_handler.go serveStatus)
                raise APIError(404, "NotFound",
                               f"{plural}/status not enabled")
            if subres is not None and subres.status:
                if sub == "status":
                    # status writes never touch spec
                    obj.spec = old.spec
                else:
                    # spec writes never touch status (strategy
                    # PrepareForUpdate with status subresource on)
                    obj.status = old.status
            # the WHOLE object validates on every write path — status
            # updates included (the reference's status strategy runs the
            # same schema, so a typed status stays typed)
            self._validate_custom(obj, crd)
        if sub not in ("status", "finalize"):
            errs = validation.validate(plural, obj, old=old)
            if errs:
                raise APIError(422, "Invalid", errs.message())
        if plural == "services" and not sub:
            # updates can add NodePort ports / switch type — allocate
            # the same way creates do (clusterIP immutability is already
            # enforced by validation above)
            self._allocate_service(obj)
        if plural == "customresourcedefinitions":
            # validate BEFORE touching the registry or the store: a
            # rejected rename must leave the old kind fully served
            self._check_crd_schema(obj)
            msg = scheme.crd_conflict(obj, replacing=old.spec.names.kind)
            if msg is not None:
                raise APIError(409, "Conflict", msg)
        # deletionTimestamp is SERVER-owned in both directions: a PUT
        # can neither clear a pending deletion nor SET one (a client-
        # supplied mark would delete through the update verb, bypassing
        # delete admission, or falsely Terminate a live object)
        obj.metadata.deletion_timestamp = old.metadata.deletion_timestamp
        try:
            self.store.update(plural, obj)
        except Conflict as e:
            raise APIError(409, "Conflict", str(e))
        completed = False
        if obj.metadata.deletion_timestamp is not None and \
                not obj.metadata.finalizers:
            # the last finalizer was just removed from an object marked
            # for deletion: complete it (store.go
            # deleteWithoutFinalizers)
            completed = True
            try:
                self.store.delete(plural, obj.metadata.namespace,
                                  obj.metadata.name)
            except KeyError:
                pass
        if plural == "customresourcedefinitions":
            if completed:
                # the CRD just ceased to exist: the kind must stop being
                # served, not get re-registered
                scheme.unregister(obj.spec.names.kind)
            else:
                # with the in-process store the CRD informer already
                # applied this synchronously inside store.update; this
                # inline pass is for stores with async watch dispatch
                # (NativeObjectStore). Both paths are idempotent.
                if obj.spec.names.kind != old.spec.names.kind:
                    scheme.unregister(old.spec.names.kind)
                scheme.register_dynamic(obj, replacing=old.spec.names.kind)
        h._send(200, json.dumps(scheme.encode_object(obj, version=gv)).encode())

    def _serve_delete(self, h, plural, namespace, name, user, query=None):
        obj = self._find(plural, namespace, name)
        if obj is None:
            raise APIError(404, "NotFound", f"{plural} {name!r} not found")
        try:
            self.admission.admit("delete", plural, None, obj, user, self.store)
        except AdmissionError as e:
            code = getattr(e, "code", 403)
            raise APIError(code,
                           "TooManyRequests" if code == 429 else "Forbidden",
                           str(e))
        # graceful pod deletion (registry/core/pod/strategy.go
        # CheckGracefulDelete + store.go updateForGracefulDeletion):
        # an EXPLICIT ?gracePeriodSeconds on a running, node-bound pod
        # only MARKS the object; the pod's kubelet runs preStop/stops
        # containers and then force-deletes. -1 asks for the spec's
        # terminationGracePeriodSeconds; 0 is an immediate force delete.
        # (Divergence, documented: with no query at all the delete is
        # immediate — the in-process controllers and tests drive the
        # store directly and never wait on a kubelet.)
        raw = (query or {}).get("gracePeriodSeconds", [None])[0]
        if raw is not None and plural == "pods":
            try:
                grace = int(raw)
            except ValueError:
                raise APIError(400, "BadRequest",
                               f"invalid gracePeriodSeconds {raw!r}")
            if grace == -1:
                grace = obj.spec.termination_grace_period_seconds
            elif grace < 0:
                # only -1 is a sentinel; any other negative is a typo
                # that must NOT silently force-delete
                raise APIError(422, "Invalid",
                               f"gracePeriodSeconds must be >= 0 "
                               f"(or -1 for the spec default), "
                               f"got {grace}")
            is_mirror = "kubernetes.io/config.mirror" in (
                obj.metadata.annotations or {})
            if grace > 0 and obj.spec.node_name and not is_mirror and \
                    obj.status.phase in ("", "Pending", "Running"):
                if obj.metadata.deletion_timestamp is None:
                    obj.metadata.deletion_timestamp = time.time()
                obj.metadata.deletion_grace_period_seconds = grace
                self.store.update(plural, obj)
                h._send(200, _status_body(
                    200, "Success",
                    f"{name} marked for graceful deletion "
                    f"(grace {grace}s)", status="Success"))
                return
        self._delete_or_mark(plural, obj)
        h._send(200, _status_body(200, "Success", f"{name} deleted",
                                  status="Success"))

    def _serve_delete_collection(self, h, plural, namespace, query, user):
        """DELETE on a collection URL (registry Store.DeleteCollection):
        every object the label/field selectors match is deleted through
        the same admission + finalizer gate as a single delete."""
        objs = self._filter_by_selectors(self.store.list(plural, namespace),
                                         query)
        deleted = 0
        for obj in objs:
            try:
                self.admission.admit("delete", plural, None, obj, user,
                                     self.store)
            except AdmissionError:
                continue  # per-object admission veto skips, not aborts
            self._delete_or_mark(plural, obj)
            deleted += 1
        h._send(200, _status_body(
            200, "Success", f"{deleted} {plural} deleted",
            status="Success"))

    def _delete_or_mark(self, plural, obj) -> bool:
        """Finalizer-gated deletion (registry/generic/registry/store.go
        Delete -> updateForGracefulDeletionAndFinalizers): with
        finalizers present, only mark deletion_timestamp — the object
        disappears when the last finalizer clears (see _serve_update).
        EVERY server-side delete (DELETE verb, eviction) goes through
        here. Returns True when the object was actually removed."""
        if getattr(obj.metadata, "finalizers", None):
            if obj.metadata.deletion_timestamp is None:
                obj.metadata.deletion_timestamp = time.time()
                self.store.update(plural, obj)
            return False
        self.store.delete(plural, obj.metadata.namespace,
                          obj.metadata.name)
        if plural == "customresourcedefinitions":
            scheme.unregister(obj.spec.names.kind)
        return True

    def _serve_binding(self, h, namespace, name):
        """POST pods/<name>/binding (BindingREST.Create,
        registry/core/pod/storage/storage.go)."""
        data = self._read_body(h)
        target = (data.get("target") or {}).get("name", "")
        if not target:
            raise APIError(400, "BadRequest", "binding.target.name required")
        pod = self._find("pods", namespace, name)
        if pod is None:
            raise APIError(404, "NotFound", f"pod {name!r} not found")
        try:
            self.store.bind(pod, target)
        except Conflict as e:
            raise APIError(409, "Conflict", str(e))
        h._send(201, _status_body(201, "Success", "bound", status="Success"))

    def _serve_eviction(self, h, user, namespace, name):
        """POST pods/<name>/eviction — PDB-respecting delete
        (registry/core/pod EvictionREST)."""
        pod = self._find("pods", namespace, name)
        if pod is None:
            raise APIError(404, "NotFound", f"pod {name!r} not found")
        for pdb in self.store.list("poddisruptionbudgets", pod.metadata.namespace):
            sel = pdb.selector
            if sel is not None and sel.matches(pod.metadata.labels or {}) \
                    and pdb.disruptions_allowed <= 0:
                raise APIError(429, "TooManyRequests",
                               f"pdb {pdb.metadata.name} disallows eviction")
        # finalizer-gated like every server-side delete (the reference's
        # eviction goes through the registry Delete and respects them)
        self._delete_or_mark("pods", pod)
        h._send(201, _status_body(201, "Success", "evicted", status="Success"))

    # -- watch -----------------------------------------------------------------

    def _serve_watch(self, h, plural, query, gv=None):
        rv = query.get("resourceVersion", [None])[0]
        since = int(rv) if rv not in (None, "", "0") else None
        timeout = float(query.get("timeoutSeconds", ["30"])[0])
        sel = query.get("labelSelector", [None])[0]
        parsed_sel = None
        if sel:
            from ..api.labels import Selector

            try:
                parsed_sel = Selector.parse(sel)
            except ValueError:
                raise APIError(400, "BadRequest",
                               f"unparseable labelSelector {sel!r}")

        def _matches(o) -> bool:
            return parsed_sel is None or \
                parsed_sel.matches(o.metadata.labels or {})
        # resourceVersion=0: deliver current state as synthetic ADDED events
        # then go live (cacher's GetAllEventsSince for zero version,
        # storage/watch_cache.go) — must snapshot state and open the live
        # watcher under one view to not drop or duplicate events
        initial: List[object] = []
        try:
            if rv == "0":
                with self.store._lock:
                    initial = self.store.list(plural)
                    watcher = self.broadcaster.watch(
                        kind=plural,
                        since_rv=self.store.latest_resource_version)
            else:
                watcher = self.broadcaster.watch(kind=plural, since_rv=since)
        except TooOld as e:
            raise APIError(410, "Expired", str(e))
        try:
            h.send_response(200)
            h.send_header("Content-Type", "application/json")
            h.send_header("Transfer-Encoding", "chunked")
            h.end_headers()
            for obj in initial:
                if not _matches(obj):
                    continue
                line = (json.dumps(
                    {"type": "ADDED",
                     "object": scheme.encode_object(obj, version=gv)})
                    + "\n").encode()
                h.wfile.write(b"%x\r\n%s\r\n" % (len(line), line))
            if initial:
                h.wfile.flush()
            deadline = time.monotonic() + timeout
            while True:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                ev = watcher.next(timeout=min(left, 1.0))
                if ev is None:
                    if watcher.stopped:
                        break
                    continue
                etype = ev.type
                if parsed_sel is not None:
                    # cacher watch filtering incl. TRANSITIONS
                    # (storage/cacher.go watchFilterFunc over prevObject):
                    # entering the selector surfaces as ADDED, leaving
                    # as DELETED, outside-only events are dropped
                    cur_m = _matches(ev.obj)
                    old_m = ev.old is not None and _matches(ev.old)
                    if etype == "MODIFIED":
                        if cur_m and not old_m:
                            etype = "ADDED"
                        elif old_m and not cur_m:
                            etype = "DELETED"
                        elif not cur_m:
                            continue
                    elif not cur_m:
                        continue
                line = (json.dumps(
                    {"type": etype,
                     "object": scheme.encode_object(ev.obj, version=gv)})
                    + "\n").encode()
                h.wfile.write(b"%x\r\n%s\r\n" % (len(line), line))
                h.wfile.flush()
            h.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, socket.error):
            pass
        finally:
            watcher.stop()
            h.close_connection = True

    # -- cross-cutting ---------------------------------------------------------

    def _audit(self, user: Optional[UserInfo], verb, plural, namespace, name):
        if self.audit_sink is None or self.audit_policy == "None":
            return
        self.audit_sink({"ts": time.time(),
                         "user": user.name if user else "",
                         "verb": verb, "resource": plural,
                         "namespace": namespace or "", "name": name or ""})

    def _metrics_text(self) -> str:
        lines = ["# TYPE apiserver_request_count counter"]
        with self._count_lock:
            for key, n in sorted(self.request_count.items()):
                verb, res = key.split(":", 1)
                lines.append(
                    f'apiserver_request_count{{verb="{verb}",resource="{res}"}} {n}')
        for provider in self.metrics_providers:
            lines.append(provider())
        return "\n".join(lines) + "\n"


def _merge_patch(target: dict, patch: dict):
    """RFC 7386 merge patch (the reference default is strategic merge;
    merge patch covers the framework's PATCH uses). When the target key
    is absent or non-dict, a dict-valued patch recurses into a FRESH
    dict so nested null deletion markers are stripped instead of leaking
    into the stored object as literal nulls (RFC 7386 §2)."""
    for k, v in patch.items():
        if v is None:
            target.pop(k, None)
        elif isinstance(v, dict):
            if not isinstance(target.get(k), dict):
                target[k] = {}
            _merge_patch(target[k], v)
        else:
            target[k] = v
