"""Authentication + RBAC authorization.

Reference: token-file authn (apiserver/pkg/authentication/request/
bearertoken + plugin/pkg/auth/authenticator/token/tokenfile), RBAC
authorizer (plugin/pkg/auth/authorizer/rbac/rbac.go RuleAllows).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class UserInfo:
    name: str
    groups: Tuple[str, ...] = ()


ANONYMOUS = UserInfo("system:anonymous", ("system:unauthenticated",))


class TokenAuthenticator:
    """Bearer-token -> user mapping (token-file authenticator analog)."""

    def __init__(self, tokens: Dict[str, UserInfo],
                 allow_anonymous: bool = True):
        self.tokens = tokens
        self.allow_anonymous = allow_anonymous

    def authenticate(self, authorization_header: Optional[str]) -> Optional[UserInfo]:
        """Returns the user, or None to reject (401)."""
        if authorization_header and authorization_header.startswith("Bearer "):
            tok = authorization_header[len("Bearer "):].strip()
            user = self.tokens.get(tok)
            if user is not None:
                return user
            return None  # bad token is always a 401
        return ANONYMOUS if self.allow_anonymous else None


@dataclass
class PolicyRule:
    """One RBAC rule: verbs x resources (reference: rbac/v1 PolicyRule;
    '*' wildcards as in rbac.VerbMatches/ResourceMatches)."""

    verbs: Sequence[str]
    resources: Sequence[str]

    def allows(self, verb: str, resource: str) -> bool:
        return (("*" in self.verbs or verb in self.verbs)
                and ("*" in self.resources or resource in self.resources))


@dataclass
class RoleBinding:
    """Subject (user or group name) -> list of rules. Collapses the
    reference's ClusterRole + ClusterRoleBinding pair."""

    subject: str  # user name or group name
    rules: List[PolicyRule] = field(default_factory=list)


class RBACAuthorizer:
    """visitRulesFor analog: union of rules from bindings matching the
    user's name or any group (rbac.go:74 Authorize)."""

    def __init__(self, bindings: Sequence[RoleBinding]):
        self.bindings = list(bindings)

    def authorize(self, user: UserInfo, verb: str, resource: str) -> bool:
        names = {user.name, *user.groups}
        for b in self.bindings:
            if b.subject in names:
                if any(r.allows(verb, resource) for r in b.rules):
                    return True
        return False


def cluster_admin_bindings(subjects: Sequence[str]) -> List[RoleBinding]:
    return [RoleBinding(s, [PolicyRule(["*"], ["*"])]) for s in subjects]
