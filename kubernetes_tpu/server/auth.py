"""Authentication + RBAC authorization.

Authentication (request chain, apiserver/pkg/authentication/):
  * bearer token file (plugin/pkg/auth/authenticator/token/tokenfile)
  * service-account JWTs (pkg/serviceaccount/jwt.go) — signature plus
    liveness of the SA and its Secret
  * x509 client certs (authentication/request/x509/x509.go:76
    CommonNameUserConversion) — CN=user, O=groups, verified against the
    cluster CA by the TLS handshake itself (server/pki.py
    server_ssl_context); the server hands the verified peer subject to
    the chain. There is no header-borne cert path: a cert only
    authenticates over a connection whose handshake proved possession
    of its key.

Authorization:
  * RBAC over SERVED API objects (plugin/pkg/auth/authorizer/rbac/
    rbac.go:74): Role/ClusterRole/RoleBinding/ClusterRoleBinding are
    watched from the store and evaluated per request with apiGroups,
    resourceNames, nonResourceURLs, and namespaced Role scoping —
    reconfigurable at runtime by writing RBAC objects.
  * static constructor bindings (the pre-round-4 collapsed form) keep
    working for embedded/test servers.
  * node authorizer (plugin/pkg/auth/authorizer/node/node_authorizer.go)
    for system:nodes subjects; write fencing to the node's OWN objects
    is NodeRestriction admission, as in the reference.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class UserInfo:
    name: str
    groups: Tuple[str, ...] = ()


ANONYMOUS = UserInfo("system:anonymous", ("system:unauthenticated",))


class TokenAuthenticator:
    """Bearer-token -> user mapping (token-file authenticator analog)."""

    def __init__(self, tokens: Dict[str, UserInfo],
                 allow_anonymous: bool = True):
        self.tokens = tokens
        self.allow_anonymous = allow_anonymous

    def authenticate(self, authorization_header: Optional[str]) -> Optional[UserInfo]:
        """Returns the user, or None to reject (401)."""
        if authorization_header and authorization_header.startswith("Bearer "):
            tok = authorization_header[len("Bearer "):].strip()
            user = self.tokens.get(tok)
            if user is not None:
                return user
            return None  # bad token is always a 401
        return ANONYMOUS if self.allow_anonymous else None


class AuthenticatorChain:
    """union.New analog: token file -> SA JWT -> TLS peer cert; the
    first authenticator that positively identifies the request wins, any
    presented-but-invalid credential is a 401."""

    def __init__(self, tokens: Optional[Dict[str, UserInfo]] = None,
                 store=None, ca=None, allow_anonymous: bool = True):
        self.tokens = tokens or {}
        self.store = store
        self.ca = ca  # pki.ClusterCA (x509 + SA signing key)
        self.allow_anonymous = allow_anonymous

    def authenticate(self, authorization_header: Optional[str]) -> Optional[UserInfo]:
        """Bearer-only entry point (back compat with TokenAuthenticator)."""
        return self._authenticate(authorization_header, None)

    def authenticate_request(self, headers, peer=None) -> Optional[UserInfo]:
        """peer: (CN, [O...]) read from the VERIFIED TLS peer chain by
        the serving socket (pki.peer_identity) — never from a header."""
        return self._authenticate(headers.get("Authorization"), peer)

    def _authenticate(self, auth_header, peer=None) -> Optional[UserInfo]:
        if auth_header and auth_header.startswith("Bearer "):
            tok = auth_header[len("Bearer "):].strip()
            user = self.tokens.get(tok)
            if user is not None:
                return user
            if self.ca is not None and tok.count(".") == 2:
                from . import serviceaccount as sat

                got = sat.verify(self.ca.sa_signing_key, tok, self.store)
                if got is not None:
                    name, groups, _ns = got
                    return UserInfo(name, ("system:authenticated",
                                           *groups))
            if self.store is not None and tok.count(".") == 1:
                # bootstrap tokens (id.secret) resolve through their
                # kube-system Secret — expiry/deletion revokes live
                # (authenticator/token/bootstrap/bootstrap.go)
                from ..controllers.bootstrap import lookup_token

                sec = lookup_token(self.store, tok)
                if sec is not None:
                    tid = tok.partition(".")[0]
                    return UserInfo(f"system:bootstrap:{tid}",
                                    ("system:bootstrappers",
                                     "system:authenticated"))
            return None  # presented token matched nothing: 401
        if peer is not None:
            cn, orgs = peer
            return UserInfo(cn, ("system:authenticated", *orgs))
        return ANONYMOUS if self.allow_anonymous else None


def _match_nonresource(patterns, path: str) -> bool:
    """NonResourceURLMatches: exact, or trailing-* prefix wildcard."""
    for pat in patterns:
        if pat == "*" or pat == path or (
                pat.endswith("*") and path.startswith(pat[:-1])):
            return True
    return False


def _group_of(resource: str) -> str:
    """API group a plural is served under ('' = core) — needed to
    evaluate RBACPolicyRule.api_groups against a request. Subresource
    attributes ("deployments/scale") resolve through their base."""
    from ..api import scheme

    kind = scheme.kind_for_plural(resource.split("/")[0])
    if kind is None:
        return ""
    gv = scheme.api_version_for(kind)
    return gv.split("/")[0] if "/" in gv else ""


@dataclass
class PolicyRule:
    """One RBAC rule. The static/collapsed form used by embedded
    servers; rbac/v1 semantics (VerbMatches/ResourceMatches/
    ResourceNameMatches/NonResourceURLMatches in rbac/v1/evaluation
    helpers)."""

    verbs: Sequence[str]
    resources: Sequence[str] = ()
    resource_names: Sequence[str] = ()
    non_resource_urls: Sequence[str] = ()

    def allows(self, verb: str, resource: str,
               name: Optional[str] = None) -> bool:
        if "*" not in self.verbs and verb not in self.verbs:
            return False
        if resource.startswith("/"):
            # nonResourceURL request. The collapsed static form also
            # lets a full wildcard resources rule cover paths —
            # cluster_admin_bindings() predates the nonResourceURL field
            # and must keep meaning "everything" (the reference's
            # cluster-admin ClusterRole carries both a resources:* and a
            # nonResourceURLs:* rule)
            return (_match_nonresource(self.non_resource_urls, resource)
                    or "*" in self.resources)
        if "*" not in self.resources and resource not in self.resources:
            return False
        if self.resource_names:
            # resourceNames never match collection requests (rbac.go:
            # a list has no name to match)
            return name is not None and name in self.resource_names
        return True


@dataclass
class RoleBinding:
    """Static subject -> rules binding (collapses the reference's
    ClusterRole + ClusterRoleBinding pair); embedded/test servers."""

    subject: str  # user name or group name
    rules: List[PolicyRule] = field(default_factory=list)


NODE_READ_RESOURCES = frozenset({
    "services", "endpoints", "nodes", "pods", "persistentvolumes",
    "persistentvolumeclaims"})
# get-by-name only: the reference's node authorizer walks its graph to
# allow exactly the secrets/configmaps referenced by pods bound to the
# node (node_authorizer.go authorizeReadNamespacedObject) — no graph
# here, so the fence is: named gets only (no list/watch sweeps), and
# never in kube-system, whose Secrets hold the cluster CA + SA signing
# keys (a kubelet reading those would be a cluster-admin escalation)
NODE_GET_ONLY_RESOURCES = frozenset({
    "secrets", "configmaps",
    # named-get for polling its own rotation CSR's signed certificate
    "certificatesigningrequests"})
# writes are whitelisted as EXACT (resource, subresource) attributes —
# the reference node authorizer never grants pods/exec, pods/attach,
# pods/portforward, pods/log or any proxy subresource to node
# identities (node_authorizer.go enumerates the rules explicitly);
# matching on the base resource would hand every kubelet an exec
# capability on every pod (round-4 advisor finding)
NODE_WRITE_RESOURCES = frozenset({
    "nodes", "nodes/status", "pods", "pods/status", "pods/eviction",
    "events"})


def _node_authorize(user: UserInfo, verb: str, resource: str,
                    namespace: Optional[str],
                    name: Optional[str]) -> bool:
    """node_authorizer.go: kubelets (system:nodes group, system:node:<x>
    name) read the resources kubelets need and write node/pod state.
    Which specific node/pod a kubelet may write is enforced by
    NodeRestriction admission, as in the reference."""
    if "system:nodes" not in user.groups or \
            not user.name.startswith("system:node:"):
        return False
    if verb in ("get", "list", "watch"):
        if resource in NODE_READ_RESOURCES:  # plain resources only —
            # no read subresource (pods/log, nodes/proxy) is granted
            return True
        if resource in NODE_GET_ONLY_RESOURCES:
            return (verb == "get" and name is not None
                    and namespace != "kube-system")
        return False
    if resource == "certificatesigningrequests":
        # certificate rotation (selfnodeclient ClusterRole): CREATE
        # only — update/patch would let a node write its own Approved
        # condition and self-sign arbitrary identities (the approval
        # decision belongs to the approver controller alone)
        return verb == "create"
    return resource in NODE_WRITE_RESOURCES


class RBACAuthorizer:
    """visitRulesFor analog (rbac.go:74 Authorize): union of static
    constructor bindings, the node authorizer, and rules resolved from
    served RBAC API objects when a store is attached."""

    def __init__(self, bindings: Sequence[RoleBinding] = (),
                 store=None, node_authorizer: bool = True):
        self.bindings = list(bindings)
        self.node_authorizer = node_authorizer
        self._store = None
        self._lock = threading.Lock()
        self._dirty = True
        # resolved: [(subjects, rules, namespace-or-None)]
        self._resolved: List[Tuple[list, list, Optional[str]]] = []
        if store is not None:
            self.watch_store(store)

    # -- API-object source ------------------------------------------------------

    def watch_store(self, store):
        """Watch the four RBAC kinds; any change invalidates the
        resolved index (rebuilt lazily on the next authorize)."""
        from ..runtime.store import Event  # noqa: F401 (signature doc)

        self._store = store
        for plural in ("roles", "clusterroles", "rolebindings",
                       "clusterrolebindings"):
            store.watch(plural, self._on_event)
        self._dirty = True

    def _on_event(self, ev):
        self._dirty = True

    def _rebuild(self):
        store = self._store
        resolved: List[Tuple[list, list, Optional[str]]] = []
        cluster_roles = {r.metadata.name: r
                         for r in store.list("clusterroles")}
        roles = {(r.metadata.namespace, r.metadata.name): r
                 for r in store.list("roles")}
        for b in store.list("clusterrolebindings"):
            role = cluster_roles.get(b.role_ref.name)
            if role is not None:
                resolved.append((list(b.subjects), list(role.rules), None))
        for b in store.list("rolebindings"):
            ns = b.metadata.namespace
            if b.role_ref.kind == "ClusterRole":
                role = cluster_roles.get(b.role_ref.name)
            else:
                role = roles.get((ns, b.role_ref.name))
            if role is not None:
                # a RoleBinding grants only within its own namespace
                resolved.append((list(b.subjects), list(role.rules), ns))
        self._resolved = resolved

    @staticmethod
    def _subject_matches(subj, user: UserInfo) -> bool:
        if subj.kind == "User":
            return subj.name == user.name
        if subj.kind == "Group":
            return subj.name in user.groups
        if subj.kind == "ServiceAccount":
            return user.name == \
                f"system:serviceaccount:{subj.namespace}:{subj.name}"
        return False

    @staticmethod
    def _obj_rule_allows(rule, verb, resource, name) -> bool:
        verbs = rule.verbs or []
        if "*" not in verbs and verb not in verbs:
            return False
        if resource.startswith("/"):
            return _match_nonresource(rule.non_resource_urls or [],
                                      resource)
        resources = rule.resources or []
        if "*" not in resources and resource not in resources:
            return False
        # apiGroups scope the rule (rbac.go APIGroupMatches): an empty
        # list matches NOTHING, exactly like the reference — a rule must
        # name its groups ([""] for core). Treating empty as "any" would
        # make a hand-built Role grant strictly more here than the
        # identical object grants in the reference (round-4 advisor
        # finding).
        groups = rule.api_groups or []
        if "*" not in groups and _group_of(resource) not in groups:
            return False
        if rule.resource_names:
            return name is not None and name in rule.resource_names
        return True

    # -- entry point ------------------------------------------------------------

    def authorize(self, user: UserInfo, verb: str, resource: str,
                  namespace: Optional[str] = None,
                  name: Optional[str] = None) -> bool:
        # system:basic-user bootstrap grant: every authenticated subject
        # may ask about its OWN permissions (the review evaluates as the
        # requestor, so this grants no transitive access;
        # plugin/pkg/auth/authorizer/rbac/bootstrappolicy/policy.go
        # "system:basic-user" -> create selfsubjectaccessreviews)
        if resource == "selfsubjectaccessreviews" and verb == "create":
            return True
        if self.node_authorizer and _node_authorize(user, verb, resource,
                                                    namespace, name):
            return True
        names = {user.name, *user.groups}
        for b in self.bindings:
            if b.subject in names:
                if any(r.allows(verb, resource, name) for r in b.rules):
                    return True
        if self._store is not None:
            if self._dirty:
                with self._lock:
                    if self._dirty:
                        # clear BEFORE rebuilding: an event landing
                        # mid-rebuild re-dirties, so the next authorize
                        # rebuilds again instead of serving the stale
                        # snapshot forever
                        self._dirty = False
                        self._rebuild()
            for subjects, rules, bind_ns in self._resolved:
                if bind_ns is not None and namespace != bind_ns:
                    continue
                if not any(self._subject_matches(s, user)
                           for s in subjects):
                    continue
                if any(self._obj_rule_allows(r, verb, resource, name)
                       for r in rules):
                    return True
        return False


def cluster_admin_bindings(subjects: Sequence[str]) -> List[RoleBinding]:
    return [RoleBinding(s, [PolicyRule(["*"], ["*"])]) for s in subjects]
