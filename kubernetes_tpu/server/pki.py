"""Cluster PKI: a self-signed CA, CSR issuance, and cert verification.

Reference: the kubeadm certs phase (cmd/kubeadm/app/phases/certs) creates
a self-signed cluster CA; the CSR signer (pkg/controller/certificates/
signer/signer.go) issues client certs from it; x509 request authn
(staging/src/k8s.io/apiserver/pkg/authentication/request/x509/x509.go:76)
maps a verified client cert to a user via CommonName (user) and
Organization (groups) — CommonNameUserConversion.

EC P-256 keys throughout (small, fast). The CA material lives in a
kube-system Secret so every component — apiserver authn, the CSR
signer, kubeadm join — shares one trust root through the store, and a
durable store carries it across restarts (the reference's equivalent is
the /etc/kubernetes/pki directory).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import List, Optional, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

from ..api import types as api

CA_SECRET_NAMESPACE = "kube-system"
CA_SECRET_NAME = "cluster-ca"

_ONE_DAY = datetime.timedelta(days=1)


def _name(common_name: str, organizations: Tuple[str, ...] = ()) -> x509.Name:
    attrs = [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
    attrs += [x509.NameAttribute(NameOID.ORGANIZATION_NAME, o)
              for o in organizations]
    return x509.Name(attrs)


@dataclass
class ClusterCA:
    """The cluster trust root + the service-account signing secret."""

    ca_cert_pem: str
    ca_key_pem: str
    sa_signing_key: str  # HMAC secret for SA JWTs (jwt.go's key analog)

    @property
    def ca_cert(self) -> x509.Certificate:
        return x509.load_pem_x509_certificate(self.ca_cert_pem.encode())

    def _ca_key(self):
        return serialization.load_pem_private_key(
            self.ca_key_pem.encode(), password=None)

    def sign_csr(self, csr_pem: str, days: int = 365) -> str:
        """signer.go Sign: issue a client cert for a PEM CSR, preserving
        its subject (CN = user, O = groups)."""
        csr = x509.load_pem_x509_csr(csr_pem.encode())
        if not csr.is_signature_valid:
            raise ValueError("CSR signature invalid")
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (x509.CertificateBuilder()
                .subject_name(csr.subject)
                .issuer_name(self.ca_cert.subject)
                .public_key(csr.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - _ONE_DAY)
                .not_valid_after(now + days * _ONE_DAY)
                .add_extension(x509.ExtendedKeyUsage(
                    [x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH]),
                    critical=False)
                .sign(self._ca_key(), hashes.SHA256()))
        return cert.public_bytes(serialization.Encoding.PEM).decode()

    def verify_client_cert(self, cert_pem: str
                           ) -> Optional[Tuple[str, List[str]]]:
        """x509.go:76 CommonNameUserConversion: validate the cert chains
        to this CA and is in its validity window; return (CN, [O...]),
        or None if untrusted/expired."""
        try:
            cert = x509.load_pem_x509_certificate(cert_pem.encode())
            cert.verify_directly_issued_by(self.ca_cert)
        except Exception:
            return None
        now = datetime.datetime.now(datetime.timezone.utc)
        if not (cert.not_valid_before_utc <= now <= cert.not_valid_after_utc):
            return None
        cn = cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
        orgs = cert.subject.get_attributes_for_oid(NameOID.ORGANIZATION_NAME)
        if not cn:
            return None
        return cn[0].value, [o.value for o in orgs]


def new_cluster_ca(name: str = "kubernetes-tpu-ca") -> ClusterCA:
    """kubeadm certs phase: generate the self-signed CA."""
    import secrets

    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    subject = _name(name)
    cert = (x509.CertificateBuilder()
            .subject_name(subject).issuer_name(subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - _ONE_DAY)
            .not_valid_after(now + 3650 * _ONE_DAY)
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    return ClusterCA(
        ca_cert_pem=cert.public_bytes(serialization.Encoding.PEM).decode(),
        ca_key_pem=key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()).decode(),
        sa_signing_key=secrets.token_hex(32))


def make_csr(common_name: str, organizations: Tuple[str, ...] = ()
             ) -> Tuple[str, str]:
    """Client-side key + CSR (kubeadm join's kubelet-client flow).
    Returns (private_key_pem, csr_pem)."""
    key = ec.generate_private_key(ec.SECP256R1())
    csr = (x509.CertificateSigningRequestBuilder()
           .subject_name(_name(common_name, tuple(organizations)))
           .sign(key, hashes.SHA256()))
    return (key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption()).decode(),
            csr.public_bytes(serialization.Encoding.PEM).decode())


def sign_proof(key_pem: str, cert_pem: str) -> str:
    """Proof of key possession for header-borne client certs: an ECDSA
    signature by the cert's private key OVER the cert itself (base64
    DER). TLS proves possession in the handshake; plain HTTP cannot, so
    without this the PEM in X-Client-Cert would be a bearer credential
    anyone who read the signed CSR status could replay."""
    import base64

    key = serialization.load_pem_private_key(key_pem.encode(),
                                             password=None)
    sig = key.sign(cert_pem.encode(), ec.ECDSA(hashes.SHA256()))
    return base64.b64encode(sig).decode()


def verify_proof(cert_pem: str, proof_b64: str) -> bool:
    """Does the proof demonstrate possession of the cert's key?"""
    import base64

    try:
        cert = x509.load_pem_x509_certificate(cert_pem.encode())
        cert.public_key().verify(base64.b64decode(proof_b64),
                                 cert_pem.encode(),
                                 ec.ECDSA(hashes.SHA256()))
        return True
    except Exception:
        return False


def ensure_cluster_ca(store) -> ClusterCA:
    """Load the CA Secret, creating it (and kube-system) on first call —
    every component resolves the same trust root through the store."""
    from ..runtime.store import Conflict

    sec = store.get("secrets", CA_SECRET_NAMESPACE, CA_SECRET_NAME)
    if sec is None:
        ca = new_cluster_ca()
        try:
            store.create("namespaces", api.Namespace(
                metadata=api.ObjectMeta(name=CA_SECRET_NAMESPACE),
                status=api.NamespaceStatus(phase="Active")))
        except Conflict:
            pass
        try:
            store.create("secrets", api.Secret(
                metadata=api.ObjectMeta(name=CA_SECRET_NAME,
                                        namespace=CA_SECRET_NAMESPACE),
                type="kubernetes.io/cluster-ca",
                data={"ca.crt": ca.ca_cert_pem, "ca.key": ca.ca_key_pem,
                      "sa.key": ca.sa_signing_key}))
        except Conflict:
            sec = store.get("secrets", CA_SECRET_NAMESPACE, CA_SECRET_NAME)
        else:
            return ca
    return ClusterCA(ca_cert_pem=sec.data["ca.crt"],
                     ca_key_pem=sec.data["ca.key"],
                     sa_signing_key=sec.data["sa.key"])
