"""Cluster PKI: a self-signed CA, CSR issuance, and cert verification.

Reference: the kubeadm certs phase (cmd/kubeadm/app/phases/certs) creates
a self-signed cluster CA; the CSR signer (pkg/controller/certificates/
signer/signer.go) issues client certs from it; x509 request authn
(staging/src/k8s.io/apiserver/pkg/authentication/request/x509/x509.go:76)
maps a verified client cert to a user via CommonName (user) and
Organization (groups) — CommonNameUserConversion.

EC P-256 keys throughout (small, fast). The CA material lives in a
kube-system Secret so every component — apiserver authn, the CSR
signer, kubeadm join — shares one trust root through the store, and a
durable store carries it across restarts (the reference's equivalent is
the /etc/kubernetes/pki directory).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

from ..api import types as api

CA_SECRET_NAMESPACE = "kube-system"
CA_SECRET_NAME = "cluster-ca"

_ONE_DAY = datetime.timedelta(days=1)


def _name(common_name: str, organizations: Tuple[str, ...] = ()) -> x509.Name:
    attrs = [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
    attrs += [x509.NameAttribute(NameOID.ORGANIZATION_NAME, o)
              for o in organizations]
    return x509.Name(attrs)


@dataclass
class ClusterCA:
    """The cluster trust root + the service-account signing secret."""

    ca_cert_pem: str
    ca_key_pem: str
    sa_signing_key: str  # HMAC secret for SA JWTs (jwt.go's key analog)

    @property
    def ca_cert(self) -> x509.Certificate:
        return x509.load_pem_x509_certificate(self.ca_cert_pem.encode())

    def _ca_key(self):
        return serialization.load_pem_private_key(
            self.ca_key_pem.encode(), password=None)

    def sign_csr(self, csr_pem: str, days: int = 365) -> str:
        """signer.go Sign: issue a client cert for a PEM CSR, preserving
        its subject (CN = user, O = groups)."""
        csr = x509.load_pem_x509_csr(csr_pem.encode())
        if not csr.is_signature_valid:
            raise ValueError("CSR signature invalid")
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (x509.CertificateBuilder()
                .subject_name(csr.subject)
                .issuer_name(self.ca_cert.subject)
                .public_key(csr.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - _ONE_DAY)
                .not_valid_after(now + days * _ONE_DAY)
                .add_extension(x509.ExtendedKeyUsage(
                    [x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH]),
                    critical=False)
                .sign(self._ca_key(), hashes.SHA256()))
        return cert.public_bytes(serialization.Encoding.PEM).decode()

    def verify_client_cert(self, cert_pem: str
                           ) -> Optional[Tuple[str, List[str]]]:
        """x509.go:76 CommonNameUserConversion: validate the cert chains
        to this CA and is in its validity window; return (CN, [O...]),
        or None if untrusted/expired."""
        try:
            cert = x509.load_pem_x509_certificate(cert_pem.encode())
            cert.verify_directly_issued_by(self.ca_cert)
        except Exception:
            return None
        now = datetime.datetime.now(datetime.timezone.utc)
        if not (cert.not_valid_before_utc <= now <= cert.not_valid_after_utc):
            return None
        cn = cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
        orgs = cert.subject.get_attributes_for_oid(NameOID.ORGANIZATION_NAME)
        if not cn:
            return None
        return cn[0].value, [o.value for o in orgs]


def new_cluster_ca(name: str = "kubernetes-tpu-ca") -> ClusterCA:
    """kubeadm certs phase: generate the self-signed CA."""
    import secrets

    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    subject = _name(name)
    cert = (x509.CertificateBuilder()
            .subject_name(subject).issuer_name(subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - _ONE_DAY)
            .not_valid_after(now + 3650 * _ONE_DAY)
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    return ClusterCA(
        ca_cert_pem=cert.public_bytes(serialization.Encoding.PEM).decode(),
        ca_key_pem=key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()).decode(),
        sa_signing_key=secrets.token_hex(32))


def make_csr(common_name: str, organizations: Tuple[str, ...] = ()
             ) -> Tuple[str, str]:
    """Client-side key + CSR (kubeadm join's kubelet-client flow).
    Returns (private_key_pem, csr_pem)."""
    key = ec.generate_private_key(ec.SECP256R1())
    csr = (x509.CertificateSigningRequestBuilder()
           .subject_name(_name(common_name, tuple(organizations)))
           .sign(key, hashes.SHA256()))
    return (key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption()).decode(),
            csr.public_bytes(serialization.Encoding.PEM).decode())


def issue_server_cert(ca: ClusterCA, common_name: str,
                      dns_sans: Sequence[str] = ("localhost",),
                      ip_sans: Sequence[str] = ("127.0.0.1",),
                      days: int = 365) -> Tuple[str, str]:
    """Serving certificate signed by the cluster CA (kubeadm certs
    phase's apiserver.crt / the kubelet serving cert). Returns
    (key_pem, cert_pem)."""
    import ipaddress

    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    san = x509.SubjectAlternativeName(
        [x509.DNSName(d) for d in dns_sans]
        + [x509.IPAddress(ipaddress.ip_address(i)) for i in ip_sans])
    cert = (x509.CertificateBuilder()
            .subject_name(_name(common_name))
            .issuer_name(ca.ca_cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - _ONE_DAY)
            .not_valid_after(now + days * _ONE_DAY)
            .add_extension(san, critical=False)
            .add_extension(x509.ExtendedKeyUsage(
                [x509.oid.ExtendedKeyUsageOID.SERVER_AUTH]),
                critical=False)
            .sign(ca._ca_key(), hashes.SHA256()))
    return (key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption()).decode(),
            cert.public_bytes(serialization.Encoding.PEM).decode())


def _load_cert_chain(ctx, cert_pem: str, key_pem: str) -> None:
    """ssl.SSLContext.load_cert_chain only reads files; stage the PEMs
    in a private tmpdir for the duration of the load."""
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        cert_path = os.path.join(d, "tls.crt")
        key_path = os.path.join(d, "tls.key")
        with open(cert_path, "w") as f:
            f.write(cert_pem)
        fd = os.open(key_path, os.O_WRONLY | os.O_CREAT, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(key_pem)
        ctx.load_cert_chain(cert_path, key_path)


def server_ssl_context(ca_cert_pem: str, cert_pem: str, key_pem: str,
                       require_client_cert: bool = False):
    """TLS serving context trusting the cluster CA for client certs.
    CERT_OPTIONAL by default: bearer-token clients connect without a
    client cert, x509 clients are verified in the handshake (the real
    form of x509.go:76's 'verified peer chain'). The kubelet server
    uses require_client_cert=True — its only legitimate clients are
    cluster components holding CA-issued certs."""
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    _load_cert_chain(ctx, cert_pem, key_pem)
    ctx.load_verify_locations(cadata=ca_cert_pem)
    ctx.verify_mode = (ssl.CERT_REQUIRED if require_client_cert
                       else ssl.CERT_OPTIONAL)
    return ctx


def wrap_http_server(httpd, ctx, handshake_timeout: float = 10.0) -> None:
    """Serve `httpd` (a ThreadingHTTPServer) over TLS with the handshake
    performed in the PER-CONNECTION handler thread, not the accept loop.
    Wrapping the listener naively makes accept() run the blocking
    handshake inside serve_forever — one idle TCP connection (a port
    scan, a TCP health probe) would hang the whole server for every
    client. A handshake that stalls past handshake_timeout or fails is
    closed without touching the accept loop."""
    httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True,
                                   do_handshake_on_connect=False)
    orig_finish = httpd.finish_request

    def finish_request(request, client_address):
        try:
            request.settimeout(handshake_timeout)
            request.do_handshake()
            request.settimeout(None)
        except Exception:
            try:
                request.close()
            except OSError:
                pass
            return
        orig_finish(request, client_address)

    httpd.finish_request = finish_request


def client_ssl_context(ca_cert_pem: str,
                       client_cert_pem: Optional[str] = None,
                       client_key_pem: Optional[str] = None):
    """TLS client context: verify the server against the cluster CA
    bundle (the kubeconfig certificate-authority-data analog); present
    an x509 client credential when given (mTLS)."""
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_verify_locations(cadata=ca_cert_pem)
    ctx.check_hostname = False  # identity = the CA-verified chain; nodes
    # serve on ephemeral host:port pairs the cert's SANs can't enumerate
    if client_cert_pem and client_key_pem:
        _load_cert_chain(ctx, client_cert_pem, client_key_pem)
    return ctx


def peer_identity(ssl_socket) -> Optional[Tuple[str, List[str]]]:
    """(CN, [O...]) of the VERIFIED TLS peer certificate, or None when
    the client sent none. The chain/validity checks already happened in
    the handshake against the context's CA — this only reads the
    subject (CommonNameUserConversion, x509.go:76)."""
    try:
        peer = ssl_socket.getpeercert()
    except (ValueError, AttributeError):
        return None
    if not peer:
        return None
    cn, orgs = None, []
    for rdn in peer.get("subject", ()):
        for key, value in rdn:
            if key == "commonName" and cn is None:
                cn = value
            elif key == "organizationName":
                orgs.append(value)
    if cn is None:
        return None
    return cn, orgs


def ensure_cluster_ca(store) -> ClusterCA:
    """Load the CA Secret, creating it (and kube-system) on first call —
    every component resolves the same trust root through the store."""
    from ..runtime.store import Conflict

    sec = store.get("secrets", CA_SECRET_NAMESPACE, CA_SECRET_NAME)
    if sec is None:
        ca = new_cluster_ca()
        try:
            store.create("namespaces", api.Namespace(
                metadata=api.ObjectMeta(name=CA_SECRET_NAMESPACE),
                status=api.NamespaceStatus(phase="Active")))
        except Conflict:
            pass
        try:
            store.create("secrets", api.Secret(
                metadata=api.ObjectMeta(name=CA_SECRET_NAME,
                                        namespace=CA_SECRET_NAMESPACE),
                type="kubernetes.io/cluster-ca",
                data={"ca.crt": ca.ca_cert_pem, "ca.key": ca.ca_key_pem,
                      "sa.key": ca.sa_signing_key}))
        except Conflict:
            sec = store.get("secrets", CA_SECRET_NAMESPACE, CA_SECRET_NAME)
        else:
            return ca
    return ClusterCA(ca_cert_pem=sec.data["ca.crt"],
                     ca_key_pem=sec.data["ca.key"],
                     sa_signing_key=sec.data["sa.key"])
