"""HA apiserver endpoint reconciler.

Reference: pkg/master/master.go:199-248 + the lease endpoint reconciler
(pkg/master/reconcilers/lease.go): every apiserver replica records its
own address under a refreshed lease in the shared store and rewrites the
"kubernetes" Endpoints object to the set of live replicas; a replica
that dies stops refreshing and is pruned by whichever replica
reconciles next. This is what makes `kubectl get endpoints kubernetes`
track a scale-out control plane.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict

from ..api import types as api
from ..runtime.store import Conflict

LEASE_PREFIX = "apiserver-lease/"
ENDPOINTS_NAME = "kubernetes"


class EndpointReconciler:
    def __init__(self, store, addr: str, port: int, ttl: float = 15.0,
                 clock: Callable[[], float] = time.time):
        self.store = store
        self.addr = addr
        self.port = port
        self.ttl = ttl
        self.clock = clock
        self._stop = threading.Event()
        self._thread = None

    # -- one reconcile pass ----------------------------------------------------

    def reconcile(self):
        """Refresh our lease, prune expired ones, publish live addrs."""
        now = self.clock()
        ep = self.store.get("endpoints", "default", ENDPOINTS_NAME)
        created = ep is None
        if created:
            ep = api.Endpoints(metadata=api.ObjectMeta(
                name=ENDPOINTS_NAME, namespace="default"))
        leases: Dict[str, float] = {}
        for k, v in list(ep.metadata.annotations.items()):
            if k.startswith(LEASE_PREFIX):
                try:
                    leases[k[len(LEASE_PREFIX):]] = float(v)
                except ValueError:
                    pass
        leases[self.addr] = now
        live = sorted(a for a, t in leases.items() if now - t < self.ttl)
        ep.metadata.annotations = {
            **{k: v for k, v in ep.metadata.annotations.items()
               if not k.startswith(LEASE_PREFIX)},
            **{LEASE_PREFIX + a: str(t) for a, t in leases.items()
               if now - t < self.ttl}}
        ep.subsets = [api.EndpointSubset(
            addresses=[api.EndpointAddress(ip=a) for a in live],
            ports=[api.EndpointPort(name="https", port=self.port)])]
        try:
            if created:
                self.store.create("endpoints", ep)
            else:
                self.store.update("endpoints", ep)
        except (Conflict, KeyError):
            pass  # another replica won this round; next tick converges

    def remove(self):
        """Drop our own lease + address on clean shutdown."""
        ep = self.store.get("endpoints", "default", ENDPOINTS_NAME)
        if ep is None:
            return
        ep.metadata.annotations.pop(LEASE_PREFIX + self.addr, None)
        for ss in ep.subsets:
            ss.addresses = [a for a in ss.addresses if a.ip != self.addr]
        try:
            self.store.update("endpoints", ep)
        except (Conflict, KeyError):
            pass

    # -- background loop -------------------------------------------------------

    def start(self) -> "EndpointReconciler":
        self.reconcile()
        period = max(self.ttl / 3.0, 0.5)

        def loop():
            while not self._stop.wait(period):
                self.reconcile()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="endpoint-reconciler")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            # an in-flight reconcile() would re-publish our lease right
            # after remove() pruned it — drain the loop first
            self._thread.join(timeout=5)
        self.remove()
