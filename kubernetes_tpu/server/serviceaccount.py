"""Service-account tokens: mint + verify JWTs.

Reference: pkg/serviceaccount/jwt.go — the token is a JWT whose claims
carry the SA's namespace/name/uid and the backing Secret's name; the
authenticator validates the signature AND that the SA + Secret still
exist (jwt.go Validate), so deleting either revokes the token. The
reference signs RSA/ECDSA; this build signs HS256 with the cluster's
sa_signing_key (pki.ClusterCA) — same claims, same validation contract.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
from typing import List, Optional, Tuple

ISSUER = "kubernetes/serviceaccount"
GROUPS = ("system:serviceaccounts",)


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def username(namespace: str, name: str) -> str:
    return f"system:serviceaccount:{namespace}:{name}"


def mint(key: str, namespace: str, name: str, uid: str,
         secret_name: str) -> str:
    """jwt.go TokenGenerator.GenerateToken: claims bind the token to the
    SA identity and its Secret."""
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    claims = _b64(json.dumps({
        "iss": ISSUER,
        "sub": username(namespace, name),
        "kubernetes.io/serviceaccount/namespace": namespace,
        "kubernetes.io/serviceaccount/service-account.name": name,
        "kubernetes.io/serviceaccount/service-account.uid": uid,
        "kubernetes.io/serviceaccount/secret.name": secret_name,
    }).encode())
    signing_input = f"{header}.{claims}"
    sig = hmac.new(key.encode(), signing_input.encode(),
                   hashlib.sha256).digest()
    return f"{signing_input}.{_b64(sig)}"


def claims_of(token: str) -> Optional[dict]:
    """Unverified claims (for controllers deciding whether a stored
    token still matches its ServiceAccount — NOT for authentication)."""
    parts = token.split(".")
    if len(parts) != 3:
        return None
    try:
        return json.loads(_unb64(parts[1]))
    except Exception:
        return None


def verify(key: str, token: str, store=None
           ) -> Optional[Tuple[str, List[str], str]]:
    """jwt.go Validate: signature, issuer, and — when a store is given —
    that the ServiceAccount (same uid) and Secret still exist. Returns
    (username, groups, namespace) or None."""
    parts = token.split(".")
    if len(parts) != 3:
        return None
    signing_input = f"{parts[0]}.{parts[1]}"
    want = hmac.new(key.encode(), signing_input.encode(),
                    hashlib.sha256).digest()
    try:
        if not hmac.compare_digest(want, _unb64(parts[2])):
            return None
        claims = json.loads(_unb64(parts[1]))
    except Exception:
        return None
    if claims.get("iss") != ISSUER:
        return None
    ns = claims.get("kubernetes.io/serviceaccount/namespace", "")
    name = claims.get(
        "kubernetes.io/serviceaccount/service-account.name", "")
    uid = claims.get("kubernetes.io/serviceaccount/service-account.uid", "")
    secret = claims.get("kubernetes.io/serviceaccount/secret.name", "")
    if not ns or not name:
        return None
    if store is not None:
        sa = store.get("serviceaccounts", ns, name)
        if sa is None or (uid and sa.metadata.uid != uid):
            return None  # SA deleted/recreated: token revoked
        if secret and store.get("secrets", ns, secret) is None:
            return None  # backing Secret deleted: token revoked
    return (username(ns, name),
            list(GROUPS) + [f"system:serviceaccounts:{ns}"], ns)
