"""Admission webhooks: call out to external admission servers.

Reference: plugin/pkg/admission/webhook/{mutating,validating} (the
1.11-era GenericAdmissionWebhook) + apiserver/pkg/admission/plugin/
webhook/request: for each matching webhook in the registered
configurations, POST an AdmissionReview carrying the object; a
validating webhook answers allowed/denied, a mutating webhook may also
return a JSON patch (RFC 6902) the apiserver applies before storage.
failurePolicy decides whether an unreachable webhook fails open
(Ignore) or closed (Fail).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import List, Optional

from ..api import scheme
from ..api import types as api
from .admission import AdmissionError, AdmissionPlugin


def apply_json_patch(doc: dict, patch: List[dict]) -> dict:
    """RFC 6902 subset: add / replace / remove over /-separated paths
    (apimachinery's jsonpatch usage in mutating webhook dispatch)."""
    import copy

    out = copy.deepcopy(doc)
    for op in patch:
        path = [p.replace("~1", "/").replace("~0", "~")
                for p in op["path"].lstrip("/").split("/")]
        parent = out
        for seg in path[:-1]:
            parent = (parent[int(seg)] if isinstance(parent, list)
                      else parent.setdefault(seg, {}))
        leaf = path[-1]
        kind = op["op"]
        if kind not in ("add", "replace", "remove"):
            # never silently half-apply: an unsupported op (test/move/
            # copy) raises so admit() routes it through failurePolicy
            raise ValueError(f"unsupported JSON patch op {kind!r}")
        if isinstance(parent, list):
            idx = len(parent) if leaf == "-" else int(leaf)
            if kind == "add":
                parent.insert(idx, op["value"])
            elif kind == "replace":
                parent[idx] = op["value"]
            else:
                del parent[idx]
        else:
            if kind in ("add", "replace"):
                parent[leaf] = op["value"]
            else:
                parent.pop(leaf, None)
    return out


class _WebhookAdmission(AdmissionPlugin):
    """Shared dispatch; subclasses pick the configuration kind and
    whether patches apply."""

    config_plural = ""
    mutating = False

    def _matching(self, store, op: str, kind: str) -> List[api.Webhook]:
        out = []
        for cfg in store.list(self.config_plural):
            for wh in cfg.webhooks:
                # a rule-less webhook matches nothing (the reference requires
                # non-empty rules); substituting a wildcard here would let a
                # misregistered webhook intercept every operation
                for rule in (wh.rules or ()):
                    ops = [o.lower() for o in rule.operations]
                    if ("*" in ops or op in ops) and \
                            ("*" in rule.resources or kind in rule.resources):
                        out.append(wh)
                        break
        return out

    def _call(self, wh: api.Webhook, review: dict) -> Optional[dict]:
        req = urllib.request.Request(
            wh.url, data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(
                    req, timeout=wh.timeout_seconds) as resp:
                return json.loads(resp.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            if wh.failure_policy == "Fail":
                raise AdmissionError(
                    f"webhook {wh.name!r} unreachable and "
                    f"failurePolicy=Fail: {e}")
            return None  # Ignore: fail open

    def admit(self, op, kind, obj, old, user, store):
        if obj is None and old is None:
            return
        if kind in ("mutatingwebhookconfigurations",
                    "validatingwebhookconfigurations"):
            # never intercept webhook registration itself: a broken
            # wildcard webhook must stay deletable (the reference exempts
            # admissionregistration resources for the same reason)
            return
        hooks = self._matching(store, op, kind)
        if not hooks:
            return
        subject = obj if obj is not None else old
        review = {
            "kind": "AdmissionReview",
            "apiVersion": "admission.k8s.io/v1beta1",
            "request": {
                "uid": subject.metadata.uid,
                "resource": kind,
                "operation": op.upper(),
                "userInfo": {"username": user.name if user else ""},
                "object": (scheme.encode_object(obj)
                           if obj is not None else None),
                "oldObject": (scheme.encode_object(old)
                              if old is not None else None),
            },
        }
        for wh in hooks:
            body = self._call(wh, review)
            if body is None:
                continue
            resp = body.get("response")
            if not isinstance(resp, dict) or "allowed" not in resp:
                # a 200 without a valid AdmissionReview envelope is a
                # BROKEN webhook, not a denial: failurePolicy governs,
                # same as the unreachable case
                if wh.failure_policy == "Fail":
                    raise AdmissionError(
                        f"webhook {wh.name!r} returned an invalid "
                        f"AdmissionReview response")
                continue
            if not resp.get("allowed", False):
                status = resp.get("status")
                msg = (status.get("message") if isinstance(status, dict)
                       else None) or f"denied by {wh.name}"
                raise AdmissionError(msg)
            patch = resp.get("patch")
            if self.mutating and patch and obj is not None:
                try:
                    if isinstance(patch, str):  # base64, per the reference
                        import base64

                        patch = json.loads(base64.b64decode(patch))
                    patched = apply_json_patch(scheme.encode_object(obj),
                                               patch)
                    new_obj = scheme.decode_object(patched)
                except Exception as e:
                    # webhook-controlled input must never 500 the request
                    # path; a malformed patch is a webhook failure under
                    # failurePolicy
                    if wh.failure_policy == "Fail":
                        raise AdmissionError(
                            f"webhook {wh.name!r} returned an unappliable "
                            f"patch: {e}")
                    continue
                # mutate the caller's object in place (admission contract)
                for f in obj.__dataclass_fields__:
                    setattr(obj, f, getattr(new_obj, f))
                review["request"]["object"] = scheme.encode_object(obj)


class MutatingAdmissionWebhook(_WebhookAdmission):
    name = "MutatingAdmissionWebhook"
    config_plural = "mutatingwebhookconfigurations"
    mutating = True


class ValidatingAdmissionWebhook(_WebhookAdmission):
    name = "ValidatingAdmissionWebhook"
    config_plural = "validatingwebhookconfigurations"
    mutating = False