from .vocab import Interner, VocabSet  # noqa: F401
from .node_info import NodeInfo  # noqa: F401
from .cache import SchedulerCache  # noqa: F401
from .scrubber import SnapshotScrubber, ScrubReport, Divergence  # noqa: F401
