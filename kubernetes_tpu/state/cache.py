"""Scheduler cache: authoritative in-memory cluster state.

Behavioral port of the reference's schedulerCache (pkg/scheduler/
schedulercache/cache.go:42, interface.go:62). It aggregates pod/node
events into NodeInfo structs and runs the assumed-pod state machine
(interface.go:35-61 state diagram):

    Assume -> (bind finished) -> expire after TTL unless confirmed
    Assume -> Add (informer confirms) -> normal pod
    Assume -> Forget (bind failed) -> gone

Default TTL 30s with a 1s sweep (reference: factory/factory.go:161,
cache.go:35); here the sweep is invoked by the scheduler loop with an
injectable clock so tests control time.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional, Set

from ..api import types as api
from .node_info import NodeInfo


class _PodState:
    __slots__ = ("pod", "deadline", "binding_finished")

    def __init__(self, pod: api.Pod):
        self.pod = pod
        self.deadline: Optional[float] = None
        self.binding_finished = False


class SchedulerCache:
    def __init__(self, ttl: float = 30.0, clock: Callable[[], float] = time.monotonic):
        self.ttl = ttl
        self.clock = clock
        self.node_infos: Dict[str, NodeInfo] = {}
        self.nodes: Dict[str, api.Node] = {}
        self._pod_states: Dict[str, _PodState] = {}
        self._assumed: Set[str] = set()
        # invoked with the expiring pod whenever cleanup_expired drops an
        # assumed pod — an expiry means a bind confirmation was LOST, so
        # the owner (the scheduler) counts it in
        # cache_assumed_expired_total; None = no accounting
        self.on_expired: Optional[Callable[[api.Pod], None]] = None

    # -- assume / confirm / forget (reference: cache.go AssumePod:88,
    #    FinishBinding:110, ForgetPod:130, AddPod:171) ------------------------

    def assume_pod(self, pod: api.Pod):
        if pod.uid in self._pod_states:
            raise KeyError(f"pod {pod.uid} already in cache")
        self._add_pod_to_node(pod)
        self._pod_states[pod.uid] = _PodState(pod)
        self._assumed.add(pod.uid)

    def finish_binding(self, pod: api.Pod, now: Optional[float] = None):
        if pod.uid in self._assumed:
            st = self._pod_states[pod.uid]
            st.binding_finished = True
            st.deadline = (now if now is not None else self.clock()) + self.ttl

    def forget_pod(self, pod: api.Pod):
        st = self._pod_states.get(pod.uid)
        if st is None:
            return
        if pod.uid in self._assumed:
            self._remove_pod_from_node(st.pod)
            del self._pod_states[pod.uid]
            self._assumed.discard(pod.uid)
        else:
            raise KeyError(f"pod {pod.uid} not assumed; cannot forget")

    def is_assumed(self, pod: api.Pod) -> bool:
        return pod.uid in self._assumed

    def assumed_pods(self) -> List[api.Pod]:
        """The assumed (bound-copy) pods awaiting confirmation — the set
        a leadership-recovery pass must reconcile against API truth."""
        return [self._pod_states[uid].pod for uid in sorted(self._assumed)
                if uid in self._pod_states]

    def add_pod(self, pod: api.Pod):
        """Informer-confirmed add (reference: cache.go:171). Confirms an
        assumed pod or, if the pod expired/was never assumed, inserts it."""
        st = self._pod_states.get(pod.uid)
        if st is not None and pod.uid in self._assumed:
            if st.pod.spec.node_name != pod.spec.node_name:
                # Scheduler's assumption was overridden; move the pod.
                self._remove_pod_from_node(st.pod)
                self._add_pod_to_node(pod)
            self._assumed.discard(pod.uid)
            st.deadline = None
            st.pod = pod
        elif st is None:
            self._add_pod_to_node(pod)
            self._pod_states[pod.uid] = _PodState(pod)
        # else: duplicate add — keep existing confirmed state.

    def update_pod(self, old: api.Pod, new: api.Pod):
        st = self._pod_states.get(old.uid)
        if st is not None and old.uid not in self._assumed:
            self._remove_pod_from_node(st.pod)
            self._add_pod_to_node(new)
            st.pod = new

    def remove_pod(self, pod: api.Pod):
        st = self._pod_states.pop(pod.uid, None)
        if st is not None:
            self._remove_pod_from_node(st.pod)
        self._assumed.discard(pod.uid)

    def cleanup_expired(self, now: Optional[float] = None):
        """Expire assumed pods whose binding finished > TTL ago
        (reference: cache.go:422 cleanupAssumedPods)."""
        now = now if now is not None else self.clock()
        # sorted: expiries release capacity in a deterministic order
        # (set order follows the per-process uid hash seed)
        for uid in sorted(self._assumed):
            st = self._pod_states[uid]
            if st.binding_finished and st.deadline is not None and now >= st.deadline:
                # an expiry is never routine: the bind POST reported
                # success but no informer confirmation arrived within the
                # TTL — a lost watch event or a bind that silently never
                # landed. Dropping it silently (the old behavior) hid
                # exactly the capacity leaks the reconciler exists to
                # resolve.
                logging.getLogger(__name__).warning(
                    "assumed pod %s/%s on %s expired after %.0fs without "
                    "bind confirmation (lost confirmation or lost bind); "
                    "releasing its capacity",
                    st.pod.namespace, st.pod.name, st.pod.spec.node_name,
                    self.ttl)
                self._remove_pod_from_node(st.pod)
                del self._pod_states[uid]
                self._assumed.discard(uid)
                if self.on_expired is not None:
                    self.on_expired(st.pod)

    # -- nodes ---------------------------------------------------------------

    def add_node(self, node: api.Node):
        ni = self.node_infos.get(node.name)
        if ni is None:
            ni = NodeInfo()
            self.node_infos[node.name] = ni
        ni.set_node(node)
        self.nodes[node.name] = node

    def update_node(self, node: api.Node):
        self.add_node(node)

    def remove_node(self, node: api.Node):
        ni = self.node_infos.get(node.name)
        if ni is not None:
            ni.node = None
            if not ni.pods:
                del self.node_infos[node.name]
        self.nodes.pop(node.name, None)

    # -- listing -------------------------------------------------------------

    def list_pods(self, predicate=None) -> List[api.Pod]:
        out = []
        for st in self._pod_states.values():
            if predicate is None or predicate(st.pod):
                out.append(st.pod)
        return out

    def pod_count(self) -> int:
        return len(self._pod_states)

    # -- internals -----------------------------------------------------------

    def _add_pod_to_node(self, pod: api.Pod):
        name = pod.spec.node_name
        ni = self.node_infos.get(name)
        if ni is None:
            ni = NodeInfo()
            self.node_infos[name] = ni
        ni.add_pod(pod)

    def _remove_pod_from_node(self, pod: api.Pod):
        ni = self.node_infos.get(pod.spec.node_name)
        if ni is not None:
            ni.remove_pod(pod)
            if ni.node is None and not ni.pods:
                del self.node_infos[pod.spec.node_name]
