"""Pending-pod wavefront featurization.

Turns a batch of pending pods into the fixed-shape PodBatch encoding
(ops/encoding.py). Featurization is the per-cycle "metadata"
precomputation of the reference (pkg/scheduler/algorithm/predicates/
metadata.go:111 GetMetadata) fused with its equivalence cache
(pkg/scheduler/core/equivalence_cache.go:240 getEquivalenceClassInfo):
pods created by the same controller share an identical spec, so their
feature rows are computed once and cached by equivalence class. The
cache is invalidated when the interning vocabularies grow (a previously
unknown selector operand may have gained an id).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import labels as lbl
from ..api import types as api
from ..ops import encoding as enc
from ..utils import faultpoints
from .snapshot import Snapshot, _parse_label_num
from .vocab import VocabSet, bucket_size

# A "group selector" for spreading: AND of requirements over pod labels.
GroupSelectorsFn = Callable[[api.Pod], List[lbl.Selector]]


def equivalence_class(pod: api.Pod) -> Optional[str]:
    """Feature-row cache key. The reference's equivalence cache keys by
    controller ref alone (equivalence_cache.go:240), betting that siblings
    share spec; we add a cheap spec fingerprint so a same-owner pod with a
    divergent spec (template update mid-rollout) can never silently reuse
    stale features."""
    for ref in pod.metadata.owner_references:
        if ref.controller:
            sig = hash(repr((pod.namespace,
                             tuple(sorted(pod.metadata.labels.items())),
                             pod.spec)))
            return f"{ref.uid}/{sig:x}"
    return None


@dataclass
class _PodRow:
    """Cached per-pod feature columns (everything except host_idx, which
    depends on the node index map)."""

    data: Dict[str, np.ndarray]
    node_name: str
    vocab_version: tuple


class FeaturizeError(Exception):
    pass


class PodFeaturizeError(FeaturizeError):
    """One pod's spec crashed the featurizer — or featurized into
    non-finite planes (a NaN/inf resource quantity would poison the
    device scan's usage carry and shift every later pod's placement).
    Typed and UID-carrying so the scheduler's poison-isolation plane
    (sched/scheduler.py) convicts the culprit DIRECTLY, without wave
    bisection: the batched Filter+Score pass collapses 1.11's free
    per-pod error isolation, and this error is what restores exact
    attribution for spec-level faults."""

    def __init__(self, pod, cause: Exception):
        self.uid = getattr(pod, "uid", "")
        self.pod_name = (pod.full_name() if hasattr(pod, "full_name")
                         else str(pod))
        super().__init__(
            f"pod {self.pod_name} (uid {self.uid}) poisons featurization: "
            f"{type(cause).__name__}: {cause}")


def poison_pod_fault(uid: str, kind: str = "nan"):
    """corrupt-mode fn poisoning exactly ONE pod UID — the
    lost_device_fault (sched/breaker.py) analog for *work* instead of
    devices. Two seams consume it:

      featurize.poison  payload (pod, row-dict), fired AFTER the
                        featurizer's finite validation — kind="nan"
                        writes NaN into the victim's req columns
                        (models post-validation in-flight corruption:
                        slips past the featurizer, MUST be caught by
                        the kernel's numeric-integrity sentinel);
                        kind="crash" raises PodFeaturizeError (direct
                        attribution, no bisection needed).
      wave.poison       payload (pods, PodBatch), fired before BOTH the
                        device dispatch and every numpy-twin pass over
                        the same pods — kind="crash" raises whenever
                        the victim rides in the batch, so the fault
                        follows the DATA across backends: the twin
                        replay crashes too, classification lands on
                        input-fault, and wave bisection isolates the
                        victim; kind="nan" corrupts the victim's
                        host-side PodBatch row pre-upload (sentinel
                        path).

    Everything without the victim proceeds untouched, so one activation
    models exactly one poison pod:

        faultpoints.activate("wave.poison", "corrupt",
                             fn=poison_pod_fault(pod.uid, "crash"))
    """

    def fn(payload):
        if payload is None:
            return
        first = payload[0] if isinstance(payload, tuple) else None
        if first is not None and not isinstance(first, (list, tuple)):
            pod, d = payload  # featurize seam
            if getattr(pod, "uid", None) != uid:
                return
            if kind == "crash":
                raise PodFeaturizeError(
                    pod, RuntimeError("injected poison spec"))
            d["req"] = np.full_like(d["req"], np.nan)
            return
        pods, pb = payload  # wave seam
        for i, p in enumerate(pods):
            if getattr(p, "uid", None) == uid:
                if kind == "crash":
                    raise RuntimeError(
                        f"injected poison work riding pod uid {uid!r}")
                # host-side batch, pre-upload: numpy in place
                pb.req[i] = np.nan
                return

    return fn


class PodFeaturizer:
    def __init__(self, snapshot: Snapshot, group_selectors: Optional[GroupSelectorsFn] = None):
        self.snap = snapshot
        self.vocabs = snapshot.vocabs
        self.group_selectors = group_selectors or (lambda pod: [])
        self._cache: Dict[str, _PodRow] = {}

    # -- selector program compilation ----------------------------------------

    def _compile_reqs(
        self, reqs: Sequence[lbl.Requirement], keys, AE: int, AV: int,
        node_space: bool,
    ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
        """Compile an AND-list of requirements to (key[AE], op[AE],
        vals[AE,AV], num[AE]). Returns None if it doesn't fit caps (caller
        grows and retries).

        Keys and values are INTERNED, not looked up: a freshly interned id
        matches nothing until some entity carries it — identical semantics
        to an unknown id — and, unlike lookup, a program compiled early in
        a batch stays correct when a later pod in the same batch interns
        the same string (the stale -1 operand hazard)."""
        if len(reqs) > AE:
            return None
        key = np.zeros((AE,), np.int32)
        op = np.full((AE,), enc.OP_PAD, np.int32)
        vals = np.full((AE, AV), -1, np.int32)
        num = np.full((AE,), np.nan, np.float32)
        v = self.vocabs
        for i, r in enumerate(reqs):
            if r.key == api.NODE_FIELD_NAME and node_space:
                # matchFields metadata.name -> node-index membership
                if r.op not in (lbl.IN,):
                    # NotIn over node names: rewrite as NODE_NAME_IN inverted is
                    # not supported yet; treat conservatively as always-false.
                    op[i] = enc.OP_FALSE
                    continue
                if len(r.values) > AV:
                    return None
                op[i] = enc.OP_NODE_NAME_IN
                for j, val in enumerate(r.values):
                    vals[i, j] = self.snap.node_index.get(val, -1)
                continue
            kid = keys.intern(r.key)
            if node_space:
                if kid >= self.snap.caps.K:
                    self.snap._grow(K=kid + 1)
            elif kid >= self.snap.caps.KP:
                self.snap._grow(KP=kid + 1)
            key[i] = kid
            op[i] = enc.op_id(r.op)
            if r.op in (lbl.IN, lbl.NOT_IN):
                if len(r.values) > AV:
                    return None
                for j, val in enumerate(r.values):
                    vals[i, j] = v.label_values.intern(val)
            elif r.op in (lbl.GT, lbl.LT):
                num[i] = _parse_label_num(r.values[0]) if r.values else math.nan
        return key, op, vals, num

    # -- featurize one pod ----------------------------------------------------

    def _featurize_pod(self, pod: api.Pod) -> Dict[str, np.ndarray]:
        c = self.snap.caps
        v = self.vocabs
        d: Dict[str, np.ndarray] = {}
        # resources
        req_map = api.get_resource_request(pod)
        from .node_info import Resource

        d["req"] = self.snap._res_vec(Resource.from_map(req_map))
        nz_cpu, nz_mem = api.get_nonzero_requests(pod)
        d["nonzero"] = np.array([nz_cpu, nz_mem], np.float32)
        d["best_effort"] = np.bool_(api.is_best_effort(pod))
        # zero-request fast flag is implicit: req all zeros
        # nodeSelector equality pairs
        ns = pod.spec.node_selector or {}
        if len(ns) > c.NS:
            self.snap._grow(NS=len(ns))
            c = self.snap.caps
        ns_key = np.zeros((c.NS,), np.int32)
        ns_val = np.full((c.NS,), -1, np.int32)
        for i, (k, val) in enumerate(sorted(ns.items())):
            kid = v.label_keys.lookup(k)
            ns_key[i] = kid if kid > 0 else -2  # -2: unknown key, never matches
            ns_val[i] = v.label_values.lookup(val)
        d["ns_key"], d["ns_val"] = ns_key, ns_val
        # required node affinity
        aff = pod.spec.affinity
        na = aff.node_affinity if aff else None
        terms = list(na.required.node_selector_terms) if (na and na.required is not None) else []
        d["has_aff"] = np.bool_(na is not None and na.required is not None)
        while True:
            c = self.snap.caps
            at_valid = np.zeros((c.AT,), bool)
            at_key = np.zeros((c.AT, c.AE), np.int32)
            at_op = np.full((c.AT, c.AE), enc.OP_PAD, np.int32)
            at_vals = np.full((c.AT, c.AE, c.AV), -1, np.int32)
            at_num = np.full((c.AT, c.AE), np.nan, np.float32)
            if len(terms) > c.AT:
                self.snap._grow(AT=len(terms))
                continue
            ok = True
            for ti, term in enumerate(terms):
                reqs = list(term.match_expressions) + list(term.match_fields)
                if not reqs:
                    continue  # empty term matches nothing -> leave invalid
                prog = self._compile_reqs(reqs, v.label_keys, c.AE, c.AV, node_space=True)
                if prog is None:
                    self.snap._grow(AE=len(reqs),
                                    AV=max((len(r.values) for r in reqs), default=0))
                    ok = False
                    break
                at_valid[ti] = True
                at_key[ti], at_op[ti], at_vals[ti], at_num[ti] = prog
            if ok:
                break
        d["at_valid"], d["at_key"], d["at_op"], d["at_vals"], d["at_num"] = (
            at_valid, at_key, at_op, at_vals, at_num)
        # preferred node affinity
        pref = list(na.preferred) if na else []
        pref = [t for t in pref if t.weight != 0]
        while True:
            c = self.snap.caps
            if len(pref) > c.PT:
                self.snap._grow(PT=len(pref))
                continue
            pt_weight = np.zeros((c.PT,), np.float32)
            pt_key = np.zeros((c.PT, c.AE), np.int32)
            pt_op = np.full((c.PT, c.AE), enc.OP_PAD, np.int32)
            pt_vals = np.full((c.PT, c.AE, c.AV), -1, np.int32)
            pt_num = np.full((c.PT, c.AE), np.nan, np.float32)
            ok = True
            for ti, term in enumerate(pref):
                reqs = list(term.preference.match_expressions) + list(term.preference.match_fields)
                prog = self._compile_reqs(reqs, v.label_keys, c.AE, c.AV, node_space=True)
                if prog is None:
                    self.snap._grow(AE=len(reqs),
                                    AV=max((len(r.values) for r in reqs), default=0))
                    ok = False
                    break
                pt_weight[ti] = term.weight
                pt_key[ti], pt_op[ti], pt_vals[ti], pt_num[ti] = prog
            if ok:
                break
        d["pt_weight"], d["pt_key"], d["pt_op"], d["pt_vals"], d["pt_num"] = (
            pt_weight, pt_key, pt_op, pt_vals, pt_num)
        # tolerations
        tols = pod.spec.tolerations
        if len(tols) > self.snap.caps.TL:
            self.snap._grow(TL=len(tols))
        c = self.snap.caps
        tol_key = np.zeros((c.TL,), np.int32)
        tol_val = np.full((c.TL,), -1, np.int32)
        tol_op = np.full((c.TL,), enc.TOL_PAD, np.int32)
        tol_effect = np.zeros((c.TL,), np.int32)
        for i, t in enumerate(tols):
            tol_key[i] = v.taint_keys.lookup(t.key) if t.key else 0  # 0 = all keys
            if t.key and tol_key[i] < 0:
                tol_key[i] = -2  # unknown key: tolerates nothing present
            tol_val[i] = v.taint_values.lookup(t.value)
            tol_op[i] = enc.TOL_EXISTS if t.operator == api.TOLERATION_OP_EXISTS else enc.TOL_EQUAL
            tol_effect[i] = enc.EFFECT_IDS.get(t.effect, 0)
        d["tol_key"], d["tol_val"], d["tol_op"], d["tol_effect"] = (
            tol_key, tol_val, tol_op, tol_effect)
        # host ports
        cports = api.get_container_ports(pod)
        if len(cports) > self.snap.caps.PQ:
            self.snap._grow(PQ=len(cports))
        c = self.snap.caps
        ports = np.zeros((c.PQ,), np.int32)
        for i, p in enumerate(cports):
            pid = v.lookup_port(p.protocol, p.host_port)
            ports[i] = pid if pid > 0 else 0  # unknown port id: no node uses it
        d["ports"] = ports
        # spreading selectors (over pod-label space)
        d["ns_id"] = np.int32(v.namespaces.intern(pod.namespace))
        sels = self.group_selectors(pod)
        while True:
            c = self.snap.caps
            if len(sels) > c.SG:
                self.snap._grow(SG=len(sels))
                continue
            sg_valid = np.zeros((c.SG,), bool)
            sg_key = np.zeros((c.SG, c.SE), np.int32)
            sg_op = np.full((c.SG, c.SE), enc.OP_PAD, np.int32)
            sg_vals = np.full((c.SG, c.SE, c.SV), -1, np.int32)
            sg_num = np.full((c.SG, c.SE), np.nan, np.float32)
            ok = True
            for si, sel in enumerate(sels):
                prog = self._compile_reqs(sel.requirements, v.pod_label_keys,
                                          c.SE, c.SV, node_space=False)
                if prog is None:
                    self.snap._grow(SE=len(sel.requirements),
                                    SV=max((len(r.values) for r in sel.requirements), default=0))
                    ok = False
                    break
                sg_valid[si] = True
                sg_key[si], sg_op[si], sg_vals[si], sg_num[si] = prog
            if ok:
                break
        d["sg_valid"], d["sg_key"], d["sg_op"], d["sg_vals"], d["sg_num"] = (
            sg_valid, sg_key, sg_op, sg_vals, sg_num)
        # topologySpreadConstraints (forward-port; ops/topology.py).
        # Selector programs run over the existing-pod label space, like
        # inter-pod-affinity terms; a nil selector matches nothing
        # (labels.Nothing, same convention as _compile_combined).
        cons = [t for t in pod.spec.topology_spread_constraints
                if t.topology_key]
        while True:
            c = self.snap.caps
            if len(cons) > c.TS:
                self.snap._grow(TS=len(cons))
                continue
            ts_valid = np.zeros((c.TS,), bool)
            ts_hard = np.zeros((c.TS,), bool)
            ts_skew = np.zeros((c.TS,), np.float32)
            ts_tk = np.zeros((c.TS,), np.int32)
            ts_key = np.zeros((c.TS, c.TE), np.int32)
            ts_op = np.full((c.TS, c.TE), enc.OP_PAD, np.int32)
            ts_vals = np.full((c.TS, c.TE, c.TV), -1, np.int32)
            ok = True
            for ti, con in enumerate(cons):
                if con.label_selector is None:
                    prog = "nothing"
                else:
                    reqs = con.label_selector.to_selector().requirements
                    prog = self._compile_reqs(reqs, v.pod_label_keys,
                                              c.TE, c.TV, node_space=False)
                    if prog is None:
                        self.snap._grow(
                            TE=len(reqs),
                            TV=max((len(r.values) for r in reqs), default=0))
                        ok = False
                        break
                ts_valid[ti] = True
                ts_hard[ti] = con.when_unsatisfiable != api.SCHEDULE_ANYWAY
                ts_skew[ti] = max(1, int(con.max_skew))
                ts_tk[ti] = self.snap.label_key_col(con.topology_key)
                if prog == "nothing":
                    ts_op[ti, 0] = enc.OP_FALSE
                else:
                    ts_key[ti], ts_op[ti], ts_vals[ti], _ = prog
            if ok:
                break
        d["ts_valid"], d["ts_hard"], d["ts_skew"], d["ts_tk"] = (
            ts_valid, ts_hard, ts_skew, ts_tk)
        d["ts_key"], d["ts_op"], d["ts_vals"] = ts_key, ts_op, ts_vals
        # inter-pod affinity
        self._featurize_interpod(pod, d)
        # misc
        d["owned"] = np.bool_(any(
            ref.controller and ref.kind in ("ReplicationController", "ReplicaSet")
            for ref in pod.metadata.owner_references))
        imgs = [img for ctr in pod.spec.containers for img in ([getattr(ctr, "image", "")] if getattr(ctr, "image", "") else [])]
        c = self.snap.caps
        img_id = np.zeros((c.PI,), np.int32)
        for i, name in enumerate(imgs[: c.PI]):
            img_id[i] = v.images.lookup(name)
        d["img_id"] = img_id
        d["prio"] = np.int32(api.pod_priority(pod))
        return d

    def _featurize_pod_guarded(self, pod: api.Pod) -> Dict[str, np.ndarray]:
        """_featurize_pod hardened for poison isolation: any crash is
        re-raised as a typed, UID-carrying PodFeaturizeError, and rows
        whose resource columns came out non-finite (a 'NaN'-quantity
        spec parses without error) are rejected HERE — before they can
        reach a device program and poison the whole wave's usage carry.
        The featurize.poison chaos seam fires AFTER the validation:
        corrupt-mode injection models post-validation corruption, which
        only the kernel's numeric-integrity sentinel can catch."""
        try:
            d = self._featurize_pod(pod)
        except PodFeaturizeError:
            raise
        except (MemoryError, OSError, TimeoutError):
            # environmental, not spec-caused: convicting the pod that
            # HAPPENED to be featurizing when memory ran out would
            # quarantine an innocent — propagate raw, like before
            raise
        except Exception as e:
            raise PodFeaturizeError(pod, e) from e
        if not (np.isfinite(d["req"]).all()
                and np.isfinite(d["nonzero"]).all()):
            raise PodFeaturizeError(
                pod, ValueError("non-finite resource request"))
        try:
            faultpoints.fire("featurize.poison", payload=(pod, d))
        except PodFeaturizeError:
            raise
        except Exception as e:
            raise PodFeaturizeError(pod, e) from e
        return d

    # -- inter-pod affinity ----------------------------------------------------

    @staticmethod
    def needs_host_path(pod: api.Pod) -> bool:
        """True when the pod's required pod-(anti)affinity terms span more
        than one distinct topology key. The device kernel's single-anchor
        encoding (ops/affinity.py) collapses all required terms to one
        shared topology key — the reference semantics
        (predicates.go anyPodsMatchingTopologyTerms: one target node must
        satisfy ALL terms' topologies) need a composite domain otherwise,
        so such pods take the exact host path (plugins/golden.py)."""
        aff = pod.spec.affinity
        if aff is None:
            return False
        for group in (aff.pod_affinity, aff.pod_anti_affinity):
            if group is None:
                continue
            tks = {t.topology_key for t in group.required}
            if len(tks) > 1:
                return True
        return False

    def golden_reason(self, pod: api.Pod) -> str:
        """Why a pod bypasses the batched kernels (device AND numpy
        twin) for the exact per-pod golden path: 'multi_tk' — required
        (anti)affinity spanning multiple topology keys, the shared
        encoding limit. 'affinity' is retained for direct callers that
        classify pods the twin-era degraded path no longer routes
        golden (the inter-pod affinity plane is twinned —
        ops/hostwave.py incoming_statics_host — so the count should
        stay zero in degraded rounds). The label set of
        scheduler_degraded_golden_pods_total{reason=...}."""
        return "multi_tk" if self.needs_host_path(pod) else "affinity"

    def _ns_set(self, pod: api.Pod, terms) -> List[int]:
        """Intersection of the terms' namespace sets (each term: explicit
        list, or the pod's own namespace) as interned ids."""
        v = self.vocabs
        sets_ = []
        for t in terms:
            names = set(t.namespaces) if t.namespaces else {pod.namespace}
            sets_.append(names)
        inter = set.intersection(*sets_) if sets_ else set()
        # inner sorted: intern() MINTS ids in iteration order, so
        # interning in set order would assign namespace ids by the hash
        # seed — vocab contents must be a pure function of input order
        return sorted(v.namespaces.intern(n) for n in sorted(inter))

    def _compile_combined(self, terms, IE: int, IV: int):
        """All required terms' selectors concatenated into one AND program
        (metadata-path semantics: podMatchesAffinityTermProperties matches
        ALL properties). Returns None if caps too small; 'nothing' if any
        selector is nil."""
        reqs = []
        for t in terms:
            if t.label_selector is None:
                return "nothing"
            reqs.extend(t.label_selector.to_selector().requirements)
        if len(reqs) > IE:
            return None
        return self._compile_reqs(reqs, self.vocabs.pod_label_keys, IE, IV,
                                  node_space=False)

    def _featurize_interpod(self, pod: api.Pod, d: Dict[str, np.ndarray]):
        v = self.vocabs
        c = self.snap.caps
        # the pod's own labels in pod-label key space (matched against
        # existing pods' term selectors and wave-internal programs)
        for key in pod.metadata.labels or {}:
            kid = v.pod_label_keys.intern(key)
            if kid >= self.snap.caps.KP:
                self.snap._grow(KP=kid + 1)
        c = self.snap.caps
        pl = np.zeros((c.KP,), np.int32)
        for key, val in (pod.metadata.labels or {}).items():
            pl[v.pod_label_keys.intern(key)] = v.label_values.intern(val)
        d["pl_val"] = pl

        aff = pod.spec.affinity
        pa_terms = []  # (signed weight, term)
        for side, sign in ((aff.pod_affinity if aff else None, 1.0),
                           (aff.pod_anti_affinity if aff else None, -1.0)):
            if side is not None:
                pa_terms.extend((sign * wt.weight, wt.pod_affinity_term)
                                for wt in side.preferred if wt.weight)
        for req_name, side in (("ra", aff.pod_affinity if aff else None),
                               ("rn", aff.pod_anti_affinity if aff else None)):
            terms = list(side.required) if side is not None else []
            d[f"{req_name}_has"] = np.bool_(bool(terms))
            while True:
                c = self.snap.caps
                prog = self._compile_combined(terms, c.IE, c.IV)
                if prog is None:
                    nreq = sum(len(t.label_selector.to_selector().requirements)
                               for t in terms if t.label_selector is not None)
                    nval = max((len(r.values)
                                for t in terms if t.label_selector is not None
                                for r in t.label_selector.to_selector().requirements),
                               default=0)
                    self.snap._grow(IE=max(nreq, c.IE + 1), IV=nval)
                    continue
                break
            c = self.snap.caps
            if prog == "nothing":
                key = np.zeros((c.IE,), np.int32)
                op = np.full((c.IE,), enc.OP_PAD, np.int32)
                op[0] = enc.OP_FALSE
                vals = np.full((c.IE, c.IV), -1, np.int32)
                num = np.full((c.IE,), np.nan, np.float32)
                prog = (key, op, vals, num)
            d[f"{req_name}_key"], d[f"{req_name}_op"], d[f"{req_name}_vals"], _ = prog
            ns_ids = self._ns_set(pod, terms)
            if len(ns_ids) > c.TNS:
                self.snap._grow(TNS=len(ns_ids))
                c = self.snap.caps
            ns_row = np.zeros((c.TNS,), np.int32)
            ns_row[: len(ns_ids)] = ns_ids
            d[f"{req_name}_ns"] = ns_row
            # shared topology key (single-tk fast path; multi-tk pods were
            # routed host-side by needs_host_path)
            tk = terms[0].topology_key if terms else ""
            d[f"{req_name}_tk"] = np.int32(self.snap.label_key_col(tk) if tk else 0)
        # bootstrap rule input: does the pod match its own affinity props?
        ra_terms = list(aff.pod_affinity.required) if (aff and aff.pod_affinity) else []
        self_match = bool(ra_terms)
        for t in ra_terms:
            names = set(t.namespaces) if t.namespaces else {pod.namespace}
            if pod.namespace not in names or t.label_selector is None or \
                    not t.label_selector.matches(pod.metadata.labels):
                self_match = False
                break
        d["ra_self"] = np.bool_(self_match)
        # preferred terms (priority)
        if len(pa_terms) > self.snap.caps.PA:
            self.snap._grow(PA=len(pa_terms))
        c = self.snap.caps
        pa_w = np.zeros((c.PA,), np.float32)
        pa_tk = np.zeros((c.PA,), np.int32)
        pa_ns = np.zeros((c.PA, c.TNS), np.int32)
        pa_key = np.zeros((c.PA, c.TE), np.int32)
        pa_op = np.full((c.PA, c.TE), enc.OP_PAD, np.int32)
        pa_vals = np.full((c.PA, c.TE, c.TV), -1, np.int32)
        for i, (w, term) in enumerate(pa_terms):
            while True:
                c = self.snap.caps
                if term.label_selector is None:
                    prog = "nothing"
                else:
                    reqs = term.label_selector.to_selector().requirements
                    prog = self._compile_reqs(reqs, v.pod_label_keys, c.TE, c.TV,
                                              node_space=False)
                    if prog is None:
                        self.snap._grow(TE=len(reqs),
                                        TV=max((len(r.values) for r in reqs), default=0))
                        # caps grew: restart the whole preferred-term loop with
                        # freshly sized arrays
                        return self._featurize_interpod(pod, d)
                break
            pa_w[i] = w
            pa_tk[i] = self.snap.label_key_col(term.topology_key) if term.topology_key else 0
            ns_ids = self._ns_set(pod, [term])
            if len(ns_ids) > c.TNS:
                self.snap._grow(TNS=len(ns_ids))
                return self._featurize_interpod(pod, d)
            pa_ns[i, : len(ns_ids)] = ns_ids
            if prog == "nothing":
                pa_op[i, 0] = enc.OP_FALSE
            else:
                pa_key[i], pa_op[i], pa_vals[i], _ = prog
        d["pa_w"], d["pa_tk"], d["pa_ns"] = pa_w, pa_tk, pa_ns
        d["pa_key"], d["pa_op"], d["pa_vals"] = pa_key, pa_op, pa_vals

    # -- batch ----------------------------------------------------------------

    def featurize(self, pods: Sequence[api.Pod]) -> enc.PodBatch:
        c0 = self.snap.caps
        P = bucket_size(max(len(pods), 1), c0.P)
        if P > c0.P:
            self.snap.caps.P = P
        ver = self.vocabs.version()
        rows: List[Dict[str, np.ndarray]] = []
        for pod in pods:
            sig = equivalence_class(pod)
            cached = self._cache.get(sig) if sig else None
            if cached is not None and cached.vocab_version == ver and self._caps_match(cached.data):
                d = cached.data
            else:
                d = self._featurize_pod_guarded(pod)
                ver = self.vocabs.version()  # may have grown during featurize
                if sig:
                    self._cache[sig] = _PodRow(d, pod.spec.node_name, ver)
            rows.append(d)
        # capacities may have grown while featurizing later pods: recompute
        # any row that no longer matches current caps
        for i, (pod, d) in enumerate(zip(pods, rows)):
            if not self._caps_match(d):
                rows[i] = self._featurize_pod_guarded(pod)
                sig = equivalence_class(pod)
                if sig:
                    self._cache[sig] = _PodRow(rows[i], pod.spec.node_name, self.vocabs.version())
        c = self.snap.caps
        P = bucket_size(max(len(pods), 1), c.P)

        def stack(name, shape, dtype, fill=0):
            out = np.full((P,) + shape, fill, dtype)
            for i, d in enumerate(rows):
                out[i] = d[name]
            return out

        host_idx = np.full((P,), -1, np.int32)
        for i, pod in enumerate(pods):
            if pod.spec.node_name:
                # -2: pinned to a node we don't know -> matches NO node
                # (reference PodFitsHost fails everywhere, predicates.go:825);
                # -1 means "no nodeName constraint".
                host_idx[i] = self.snap.node_index.get(pod.spec.node_name, -2)
        batch = enc.PodBatch(
            req=stack("req", (c.R,), np.float32),
            nonzero=stack("nonzero", (2,), np.float32),
            best_effort=stack("best_effort", (), bool),
            host_idx=host_idx,
            ns_key=stack("ns_key", (c.NS,), np.int32),
            ns_val=stack("ns_val", (c.NS,), np.int32, -1),
            has_aff=stack("has_aff", (), bool),
            at_valid=stack("at_valid", (c.AT,), bool),
            at_key=stack("at_key", (c.AT, c.AE), np.int32),
            at_op=stack("at_op", (c.AT, c.AE), np.int32, enc.OP_PAD),
            at_vals=stack("at_vals", (c.AT, c.AE, c.AV), np.int32, -1),
            at_num=stack("at_num", (c.AT, c.AE), np.float32, np.nan),
            pt_weight=stack("pt_weight", (c.PT,), np.float32),
            pt_key=stack("pt_key", (c.PT, c.AE), np.int32),
            pt_op=stack("pt_op", (c.PT, c.AE), np.int32, enc.OP_PAD),
            pt_vals=stack("pt_vals", (c.PT, c.AE, c.AV), np.int32, -1),
            pt_num=stack("pt_num", (c.PT, c.AE), np.float32, np.nan),
            tol_key=stack("tol_key", (c.TL,), np.int32),
            tol_val=stack("tol_val", (c.TL,), np.int32, -1),
            tol_op=stack("tol_op", (c.TL,), np.int32, enc.TOL_PAD),
            tol_effect=stack("tol_effect", (c.TL,), np.int32),
            ports=stack("ports", (c.PQ,), np.int32),
            ns_id=stack("ns_id", (), np.int32),
            sg_valid=stack("sg_valid", (c.SG,), bool),
            sg_key=stack("sg_key", (c.SG, c.SE), np.int32),
            sg_op=stack("sg_op", (c.SG, c.SE), np.int32, enc.OP_PAD),
            sg_vals=stack("sg_vals", (c.SG, c.SE, c.SV), np.int32, -1),
            sg_num=stack("sg_num", (c.SG, c.SE), np.float32, np.nan),
            pl_val=stack("pl_val", (c.KP,), np.int32),
            ra_has=stack("ra_has", (), bool),
            ra_key=stack("ra_key", (c.IE,), np.int32),
            ra_op=stack("ra_op", (c.IE,), np.int32, enc.OP_PAD),
            ra_vals=stack("ra_vals", (c.IE, c.IV), np.int32, -1),
            ra_ns=stack("ra_ns", (c.TNS,), np.int32),
            ra_tk=stack("ra_tk", (), np.int32),
            ra_self=stack("ra_self", (), bool),
            rn_has=stack("rn_has", (), bool),
            rn_key=stack("rn_key", (c.IE,), np.int32),
            rn_op=stack("rn_op", (c.IE,), np.int32, enc.OP_PAD),
            rn_vals=stack("rn_vals", (c.IE, c.IV), np.int32, -1),
            rn_ns=stack("rn_ns", (c.TNS,), np.int32),
            rn_tk=stack("rn_tk", (), np.int32),
            pa_w=stack("pa_w", (c.PA,), np.float32),
            pa_tk=stack("pa_tk", (c.PA,), np.int32),
            pa_ns=stack("pa_ns", (c.PA, c.TNS), np.int32),
            pa_key=stack("pa_key", (c.PA, c.TE), np.int32),
            pa_op=stack("pa_op", (c.PA, c.TE), np.int32, enc.OP_PAD),
            pa_vals=stack("pa_vals", (c.PA, c.TE, c.TV), np.int32, -1),
            owned=stack("owned", (), bool),
            img_id=stack("img_id", (c.PI,), np.int32),
            prio=stack("prio", (), np.int32),
            valid=np.arange(P) < len(pods),
            ts_valid=stack("ts_valid", (c.TS,), bool),
            ts_hard=stack("ts_hard", (c.TS,), bool),
            ts_skew=stack("ts_skew", (c.TS,), np.float32),
            ts_tk=stack("ts_tk", (c.TS,), np.int32),
            ts_key=stack("ts_key", (c.TS, c.TE), np.int32),
            ts_op=stack("ts_op", (c.TS, c.TE), np.int32, enc.OP_PAD),
            ts_vals=stack("ts_vals", (c.TS, c.TE, c.TV), np.int32, -1),
            **self._dedup_tables(rows, P),
        )
        return batch

    def _dedup_tables(self, rows, P: int) -> Dict[str, np.ndarray]:
        """Intern the wave's required/preferred pod-affinity programs into
        unique tables (PodBatch.iu_*/pu_* + uid indices). Pods stamped
        from one controller template share programs, so the device side
        evaluates U unique programs against the M existing pods instead
        of P — the difference between O(P*M) and O(U*M) in
        ops/affinity.py incoming_statics. Row 0 of each table is a
        reserved never-matches program (OP_FALSE, tk 0)."""
        c = self.snap.caps
        ra_uid = np.zeros(P, np.int32)
        rn_uid = np.zeros(P, np.int32)
        pa_uid = np.zeros((P, c.PA), np.int32)
        iu_rows: List[tuple] = []
        iu_index: Dict[bytes, int] = {}
        pu_rows: List[tuple] = []
        pu_index: Dict[bytes, int] = {}

        def intern(index, rows_list, parts) -> int:
            key = b"|".join(p.tobytes() for p in parts)
            j = index.get(key)
            if j is None:
                j = len(rows_list) + 1  # +1: row 0 reserved
                index[key] = j
                rows_list.append(parts)
            return j

        for i, d in enumerate(rows):
            if d["ra_has"]:
                ra_uid[i] = intern(iu_index, iu_rows, (
                    d["ra_key"], d["ra_op"], d["ra_vals"], d["ra_ns"],
                    d["ra_tk"]))
            if d["rn_has"]:
                rn_uid[i] = intern(iu_index, iu_rows, (
                    d["rn_key"], d["rn_op"], d["rn_vals"], d["rn_ns"],
                    d["rn_tk"]))
            for t in range(c.PA):
                if d["pa_w"][t] != 0:
                    pa_uid[i, t] = intern(pu_index, pu_rows, (
                        d["pa_key"][t], d["pa_op"][t], d["pa_vals"][t],
                        d["pa_ns"][t], d["pa_tk"][t]))
        if len(iu_rows) + 1 > c.UI:
            self.snap.caps.UI = bucket_size(len(iu_rows) + 1, c.UI)
        if len(pu_rows) + 1 > c.UP:
            self.snap.caps.UP = bucket_size(len(pu_rows) + 1, c.UP)
        c = self.snap.caps

        def table(rows_list, n, e_dim, v_dim):
            key = np.zeros((n, e_dim), np.int32)
            op = np.full((n, e_dim), enc.OP_PAD, np.int32)
            op[:, 0] = enc.OP_FALSE  # reserved/pad rows match nothing
            vals = np.full((n, e_dim, v_dim), -1, np.int32)
            ns = np.zeros((n, c.TNS), np.int32)
            tk = np.zeros((n,), np.int32)
            for j, (k_, o_, v_, n_, t_) in enumerate(rows_list, start=1):
                key[j], op[j], vals[j], ns[j], tk[j] = k_, o_, v_, n_, t_
            return key, op, vals, ns, tk

        iu_key, iu_op, iu_vals, iu_ns, iu_tk = table(
            iu_rows, c.UI, c.IE, c.IV)
        pu_key, pu_op, pu_vals, pu_ns, pu_tk = table(
            pu_rows, c.UP, c.TE, c.TV)
        return dict(ra_uid=ra_uid, rn_uid=rn_uid, pa_uid=pa_uid,
                    iu_key=iu_key, iu_op=iu_op, iu_vals=iu_vals,
                    iu_ns=iu_ns, iu_tk=iu_tk, pu_key=pu_key, pu_op=pu_op,
                    pu_vals=pu_vals, pu_ns=pu_ns, pu_tk=pu_tk)

    def _caps_match(self, d: Dict[str, np.ndarray]) -> bool:
        c = self.snap.caps
        return (
            d["req"].shape == (c.R,)
            and d["ns_key"].shape == (c.NS,)
            and d["at_key"].shape == (c.AT, c.AE)
            and d["at_vals"].shape == (c.AT, c.AE, c.AV)
            and d["pt_key"].shape == (c.PT, c.AE)
            and d["tol_key"].shape == (c.TL,)
            and d["ports"].shape == (c.PQ,)
            and d["sg_key"].shape == (c.SG, c.SE)
            and d["sg_vals"].shape == (c.SG, c.SE, c.SV)
            and d["pl_val"].shape == (c.KP,)
            and d["ra_key"].shape == (c.IE,)
            and d["ra_vals"].shape == (c.IE, c.IV)
            and d["ra_ns"].shape == (c.TNS,)
            and d["pa_key"].shape == (c.PA, c.TE)
            and d["pa_vals"].shape == (c.PA, c.TE, c.TV)
            and d["pa_ns"].shape == (c.PA, c.TNS)
            and d["ts_key"].shape == (c.TS, c.TE)
            and d["ts_vals"].shape == (c.TS, c.TE, c.TV)
        )
