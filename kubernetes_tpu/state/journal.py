"""Durable bind-intent journal: the disconnected-mode write-ahead log.

While the store path is DISCONNECTED (sched/storehealth.py), the
scheduler keeps assuming pods against its cache but cannot POST binds.
Each spooled bind is first appended here — an fsync'd JSONL record per
intent — so a process crash mid-outage loses no placement decisions:
startup and recover_leadership() replay the unresolved intents and
re-verify each against API truth before the first wave.

The file format borrows deliberately from two proven neighbors:

  * size-cap + rotation from the round ledger (utils/tracing.py
    _write_ledger_line): when the current segment would exceed
    max_bytes, it is os.replace'd to `<path>.1` and a fresh segment
    begins. Replay streams `<path>.1` then `<path>`, so one rotation
    never loses unresolved intents; the cap must simply dwarf the
    spool watermark (it does, by orders of magnitude).
  * torn-line tolerance from the autopilot dataset reader
    (autopilot/dataset.py load_records): a crash can tear the final
    line mid-write; replay counts and skips undecodable lines instead
    of poisoning recovery, and opening for append first terminates a
    torn tail with a newline so new records stay parseable.

Two record kinds, one line each:

  {"v":1,"k":"intent","seq":N,"uid":...,"ns":...,"name":...,
   "node":...,"ts":...}
  {"v":1,"k":"resolved","seq":N,"outcome":"confirmed"|"orphaned"|
   "gone"}

An intent with no matching resolved record is unresolved — exactly the
set replay() returns, in seq (arrival) order. `journal.append` is a
registered fault point: raise models a full disk / IO error at the
worst moment, drop models a write the OS acknowledged but never
persisted (the record is silently not written).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from ..utils import faultpoints

JOURNAL_MAX_BYTES = 16 << 20  # default segment cap; -1 in config means this

CONFIRMED = "confirmed"  # truth shows the bind landed (or the drain POST won)
ORPHANED = "orphaned"    # truth shows no binding -> the pod was requeued
GONE = "gone"            # pod deleted from truth -> nothing to recover


class BindJournal:
    def __init__(self, path: str, max_bytes: int = JOURNAL_MAX_BYTES,
                 clock: Callable[[], float] = time.time):
        self.path = path
        self.max_bytes = JOURNAL_MAX_BYTES if max_bytes < 0 else max_bytes
        self.clock = clock
        self.appends = 0
        self.rotations = 0
        self.skipped_lines = 0  # torn/undecodable lines seen by last scan
        self._lock = threading.Lock()
        self._bytes: Optional[int] = None  # lazy, like the round ledger
        self._seq = self._next_seq()

    # -- appending -------------------------------------------------------------

    def append_intent(self, pod, node_name: str) -> int:
        """Durably record one bind intent; returns its seq. Raises on IO
        failure (the caller decides whether an unjournaled bind may
        still spool in memory)."""
        with self._lock:
            seq = self._seq
            self._seq += 1
            rec = {"v": 1, "k": "intent", "seq": seq, "uid": pod.uid,
                   "ns": pod.namespace, "name": pod.name,
                   "node": node_name, "ts": round(self.clock(), 3)}
            self._append_locked(rec)
            return seq

    def resolve(self, seq: int, outcome: str) -> None:
        """Mark an intent resolved (confirmed/orphaned/gone). Best-effort
        by design: a lost resolved record only means the next replay
        re-verifies an already-settled intent against truth, which is
        idempotent."""
        with self._lock:
            try:
                self._append_locked(
                    {"v": 1, "k": "resolved", "seq": seq, "outcome": outcome})
            except Exception:
                pass

    def _append_locked(self, rec: dict) -> None:
        if faultpoints.fire("journal.append", payload=rec):
            return  # drop mode: the write the OS lied about
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        data = line.encode()
        if self._bytes is None:
            try:
                self._bytes = os.path.getsize(self.path)
            except OSError:
                self._bytes = 0
        if (self.max_bytes > 0 and self._bytes > 0
                and self._bytes + len(data) > self.max_bytes):
            os.replace(self.path, self.path + ".1")
            self.rotations += 1
            self._bytes = 0
        self._repair_torn_tail()
        with open(self.path, "ab") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        self._bytes += len(data)
        self.appends += 1

    def _repair_torn_tail(self) -> None:
        """If a crash tore the final line mid-write, terminate it so the
        next append starts a fresh line (the torn line itself is then a
        single skippable record, not a corruption of two)."""
        try:
            with open(self.path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                if f.tell() == 0:
                    return
                f.seek(-1, os.SEEK_END)
                if f.read(1) != b"\n":
                    f.write(b"\n")
                    if self._bytes is not None:
                        self._bytes += 1
        except FileNotFoundError:
            pass

    # -- replay ----------------------------------------------------------------

    def _segments(self) -> List[str]:
        return [p for p in (self.path + ".1", self.path)
                if os.path.exists(p)]

    def unresolved(self) -> List[dict]:
        """The intents with no resolved record, in seq (arrival) order —
        the spool a crashed process left behind."""
        intents, resolved = self._scan()
        return [intents[s] for s in sorted(intents) if s not in resolved]

    def _scan(self):
        intents: Dict[int, dict] = {}
        resolved = set()
        skipped = 0
        for seg in self._segments():
            with open(seg, "rb") as f:
                for raw in f:
                    try:
                        rec = json.loads(raw)
                        kind, seq = rec["k"], int(rec["seq"])
                    except Exception:
                        skipped += 1  # torn or corrupt line: never fatal
                        continue
                    if kind == "intent":
                        intents[seq] = rec
                    elif kind == "resolved":
                        resolved.add(seq)
        self.skipped_lines = skipped
        return intents, resolved

    def _next_seq(self) -> int:
        intents, resolved = self._scan()
        top = max(list(intents) + list(resolved) + [-1]) if (
            intents or resolved) else -1
        return top + 1

    def stats(self) -> dict:
        return {"path": self.path, "appends": self.appends,
                "rotations": self.rotations,
                "skipped_lines": self.skipped_lines,
                "unresolved": len(self.unresolved())}
