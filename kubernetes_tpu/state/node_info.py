"""Host-side exact per-node accounting.

Analog of the reference's NodeInfo (pkg/scheduler/schedulercache/
node_info.go:40-78): the denormalized int64 aggregate every predicate
and priority reads. In this framework it plays two roles:
  1. the exact (int64) source of truth that featurization reads when
     building the HBM tensor snapshot, and
  2. the final-commit verifier — the device kernel's picks are re-checked
     against NodeInfo before binding, so float32 device arithmetic can
     never place a pod that does not exactly fit (SURVEY.md §7).

The `generation` counter (reference: node_info.go:89 nextGeneration) is
the dirty bit driving incremental tensor updates.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Set, Tuple

from ..api import resources as res
from ..api import types as api

_generation = itertools.count(1)


def next_generation() -> int:
    return next(_generation)


class Resource:
    """int64 resource vector (reference: node_info.go:131 Resource)."""

    __slots__ = ("milli_cpu", "memory", "ephemeral_storage", "allowed_pod_number", "scalars")

    def __init__(self, milli_cpu=0, memory=0, ephemeral_storage=0, allowed_pod_number=0, scalars=None):
        self.milli_cpu = milli_cpu
        self.memory = memory
        self.ephemeral_storage = ephemeral_storage
        self.allowed_pod_number = allowed_pod_number
        self.scalars: Dict[str, int] = dict(scalars or {})

    @staticmethod
    def from_map(m: Dict[str, int]) -> "Resource":
        r = Resource()
        for name, q in m.items():
            if name == res.CPU:
                r.milli_cpu = q
            elif name == res.MEMORY:
                r.memory = q
            elif name == res.EPHEMERAL_STORAGE:
                r.ephemeral_storage = q
            elif name == res.PODS:
                r.allowed_pod_number = q
            else:
                r.scalars[name] = q
        return r

    def add_map(self, m: Dict[str, int], sign: int = 1):
        for name, q in m.items():
            if name == res.CPU:
                self.milli_cpu += sign * q
            elif name == res.MEMORY:
                self.memory += sign * q
            elif name == res.EPHEMERAL_STORAGE:
                self.ephemeral_storage += sign * q
            elif name == res.PODS:
                pass  # pod count tracked by len(pods)
            else:
                self.scalars[name] = self.scalars.get(name, 0) + sign * q

    def clone(self) -> "Resource":
        return Resource(self.milli_cpu, self.memory, self.ephemeral_storage,
                        self.allowed_pod_number, dict(self.scalars))


class NodeInfo:
    """Aggregated node state (reference: node_info.go:40)."""

    def __init__(self, node: Optional[api.Node] = None):
        self.node: Optional[api.Node] = None
        self.pods: List[api.Pod] = []
        self.pods_with_affinity: List[api.Pod] = []
        self.requested = Resource()
        self.nonzero_milli_cpu = 0
        self.nonzero_memory = 0
        self.allocatable = Resource()
        self.taints: List[api.Taint] = []
        self.memory_pressure = False
        self.disk_pressure = False
        self.pid_pressure = False
        self.used_ports: Set[Tuple[str, str, int]] = set()  # (proto, hostIP, port)
        self.image_sizes: Dict[str, int] = {}
        self.generation = next_generation()
        if node is not None:
            self.set_node(node)

    # -- node ----------------------------------------------------------------

    def set_node(self, node: api.Node):
        """Reference: node_info.go:551 SetNode."""
        self.node = node
        self.allocatable = Resource.from_map(node.status.allocatable)
        self.taints = list(node.spec.taints)
        self.memory_pressure = self._cond(node, api.NODE_MEMORY_PRESSURE) == api.COND_TRUE
        self.disk_pressure = self._cond(node, api.NODE_DISK_PRESSURE) == api.COND_TRUE
        self.pid_pressure = self._cond(node, api.NODE_PID_PRESSURE) == api.COND_TRUE
        self.image_sizes = {
            name: img.size_bytes for img in node.status.images for name in img.names
        }
        self.generation = next_generation()

    @staticmethod
    def _cond(node: api.Node, cond_type: str) -> str:
        for c in node.status.conditions:
            if c.type == cond_type:
                return c.status
        return ""

    # -- pods ----------------------------------------------------------------

    def add_pod(self, pod: api.Pod):
        """Reference: node_info.go:431 AddPod."""
        req = api.get_resource_request(pod)
        self.requested.add_map(req, +1)
        nz_cpu, nz_mem = api.get_nonzero_requests(pod)
        self.nonzero_milli_cpu += nz_cpu
        self.nonzero_memory += nz_mem
        self.pods.append(pod)
        if _has_pod_affinity(pod):
            self.pods_with_affinity.append(pod)
        for p in api.get_container_ports(pod):
            self.used_ports.add((p.protocol, p.host_ip or "0.0.0.0", p.host_port))
        self.generation = next_generation()

    def remove_pod(self, pod: api.Pod) -> bool:
        """Reference: node_info.go:456 RemovePod. Returns False if absent."""
        for i, p in enumerate(self.pods):
            if p.uid == pod.uid:
                del self.pods[i]
                break
        else:
            return False
        self.pods_with_affinity = [p for p in self.pods_with_affinity if p.uid != pod.uid]
        req = api.get_resource_request(pod)
        self.requested.add_map(req, -1)
        nz_cpu, nz_mem = api.get_nonzero_requests(pod)
        self.nonzero_milli_cpu -= nz_cpu
        self.nonzero_memory -= nz_mem
        # Rebuild ports (another pod may still hold the same (proto,ip,port)).
        self.used_ports = {
            (cp.protocol, cp.host_ip or "0.0.0.0", cp.host_port)
            for q in self.pods
            for cp in api.get_container_ports(q)
        }
        self.generation = next_generation()
        return True

    # -- exact feasibility recheck (commit-time guard) ------------------------

    def fits_exactly(self, pod: api.Pod) -> bool:
        """Exact int64 re-verification of PodFitsResources + PodFitsHostPorts
        for one (pod, node) pair (reference: predicates.go:688, :991). Used
        to guard device float32 picks at commit time."""
        if self.node is None:
            return False
        if len(self.pods) + 1 > self.allocatable.allowed_pod_number:
            return False
        req = api.get_resource_request(pod)
        r = Resource.from_map(req)
        if r.milli_cpu + self.requested.milli_cpu > self.allocatable.milli_cpu:
            return False
        if r.memory + self.requested.memory > self.allocatable.memory:
            return False
        if r.ephemeral_storage + self.requested.ephemeral_storage > self.allocatable.ephemeral_storage:
            return False
        for name, q in r.scalars.items():
            if q + self.requested.scalars.get(name, 0) > self.allocatable.scalars.get(name, 0):
                return False
        for cp in api.get_container_ports(pod):
            if _ports_conflict(self.used_ports, (cp.protocol, cp.host_ip or "0.0.0.0", cp.host_port)):
                return False
        return True

    def clone(self) -> "NodeInfo":
        ni = NodeInfo()
        ni.node = self.node
        ni.pods = list(self.pods)
        ni.pods_with_affinity = list(self.pods_with_affinity)
        ni.requested = self.requested.clone()
        ni.nonzero_milli_cpu = self.nonzero_milli_cpu
        ni.nonzero_memory = self.nonzero_memory
        ni.allocatable = self.allocatable.clone()
        ni.taints = list(self.taints)
        ni.memory_pressure = self.memory_pressure
        ni.disk_pressure = self.disk_pressure
        ni.pid_pressure = self.pid_pressure
        ni.used_ports = set(self.used_ports)
        ni.image_sizes = dict(self.image_sizes)
        ni.generation = self.generation
        return ni


def _has_pod_affinity(pod: api.Pod) -> bool:
    a = pod.spec.affinity
    return bool(a and (a.pod_affinity or a.pod_anti_affinity))


def _ports_conflict(used: Set[Tuple[str, str, int]], want: Tuple[str, str, int]) -> bool:
    """hostIP wildcard-aware conflict (reference: pkg/scheduler/util and
    predicates.go:991 PodFitsHostPorts): 0.0.0.0 conflicts with any IP on
    the same proto/port; a specific IP conflicts with the same IP or the
    wildcard."""
    proto, ip, port = want
    for (uproto, uip, uport) in used:
        if uproto != proto or uport != port:
            continue
        if ip == "0.0.0.0" or uip == "0.0.0.0" or uip == ip:
            return True
    return False
