"""Self-healing snapshot scrubber: audit the HBM mirror against host truth.

Analog of the 1.11 reference's cache comparer
(pkg/scheduler/factory/cache_comparer.go: SIGUSR2 dumps a diff between
the scheduler cache and apiserver truth). Here the stakes are higher
than a log line: the batched feasibility kernel computes over the dense
`Snapshot` tensors, so ONE silently-divergent node row — a missed
incremental update, a bit of f32 state corrupted by a faulting device
path — poisons every subsequent wave for every pod. The scrubber
therefore goes beyond the reference's compare-and-log:

  1. GOLDEN ROWS — every host-cache NodeInfo is re-featurized through
     the same `Snapshot.set_node` / `refresh_node_resources` encoding
     into a scratch snapshot that shares the live vocabularies (so
     interned ids line up), giving byte-comparable golden rows.
  2. COMPARE — resources (requested/nonzero/pod_count/ports), topology
     (allocatable, labels, taints, conditions, zone, images, avoid),
     and the existing-pod matrix (placement, validity, per-pod request
     rows, priority, liveness) are diffed per node; ghost rows (nodes or
     pods the host cache no longer knows) are flagged too.
  3. REPAIR — divergent node rows are rewritten in place via
     `set_node` (which refreshes resources as well); divergent pod rows
     are re-added (their bind-echo signature is dropped first so
     `add_pod` cannot skip the rewrite); ghosts are removed. Repairs
     mark the dirty groups, so the next wave uploads corrected tensors.

Triggers match cache_comparer.go: a signal (SIGUSR2 by default, via
`install_signal`) and an optional periodic cadence, both drained by the
scheduler's housekeeping step under the scheduler lock. Emits the
`snapshot_scrub_*` metric series.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

from ..api import types as api
from ..utils import faultpoints
from .node_info import NodeInfo, Resource
from .snapshot import SNAPSHOT_DIMS, Snapshot
from .vocab import VocabSet


@dataclass
class Divergence:
    """One divergent row: which node (or pod uid) and which field group."""

    node: str
    fields: List[str]
    repaired: bool = False


@dataclass
class ScrubReport:
    nodes_checked: int = 0
    pods_checked: int = 0
    divergences: List[Divergence] = field(default_factory=list)
    repaired: int = 0
    duration: float = 0.0

    @property
    def clean(self) -> bool:
        return not self.divergences

    def summary(self) -> str:
        if self.clean:
            return (f"scrub clean: {self.nodes_checked} nodes, "
                    f"{self.pods_checked} pods")
        what = "; ".join(f"{d.node}: {','.join(d.fields)}"
                         for d in self.divergences)
        return (f"scrub found {len(self.divergences)} divergent rows "
                f"({self.repaired} repaired): {what}")


# node-row field groups compared 1:1 between golden and live arrays
_RESOURCE_FIELDS = ("requested", "nonzero", "pod_count")
_TOPOLOGY_FIELDS = ("alloc", "allowed_pods", "labels", "label_nums",
                    "taint_key", "taint_val", "taint_effect", "cond",
                    "zone_id", "rack_id", "superpod_id", "accel_gen",
                    "avoid")


def _rows_equal(a, b, fill=0) -> bool:
    """Compare two rows, padding the shorter to the longer's shape with
    `fill` — the scratch snapshot may have grown a cap (a label key the
    live snapshot never interned is itself a divergence, surfaced by the
    padded compare) — NaN-tolerant for the label_nums plane."""
    a = np.atleast_1d(np.asarray(a))
    b = np.atleast_1d(np.asarray(b))
    if a.shape != b.shape:
        shape = tuple(max(x, y) for x, y in zip(a.shape, b.shape))

        def pad(arr):
            out = np.full(shape, fill, arr.dtype)
            out[tuple(slice(0, s) for s in arr.shape)] = arr
            return out

        a, b = pad(a), pad(b)
    if a.dtype.kind == "f" or b.dtype.kind == "f":
        return bool(np.array_equal(a.astype(np.float64),
                                   b.astype(np.float64), equal_nan=True))
    return bool(np.array_equal(a, b))


class SnapshotScrubber:
    def __init__(self, cache, snapshot: Snapshot, metrics=None,
                 clock: Callable[[], float] = time.monotonic,
                 period: Optional[float] = None,
                 lock: Optional[threading.RLock] = None,
                 compact_period: Optional[float] = None):
        self.cache = cache
        self.snapshot = snapshot
        self.metrics = metrics
        self.clock = clock
        self.period = period  # None/0 disables the cadence trigger
        self.compact_period = compact_period  # None/0 disables cadence
        self._lock = lock or threading.RLock()
        self._requested = False
        self._last_run = clock()
        self._last_compact = clock()
        self.last_report: Optional[ScrubReport] = None
        self.last_compaction: Optional[dict] = None

    # -- triggers -------------------------------------------------------------

    def request(self) -> None:
        """Flag a scrub for the next housekeeping pass. Signal-safe: no
        locks, no allocation — the handler context allows nothing more."""
        self._requested = True

    def install_signal(self, signum=None) -> bool:
        """Install a SIGUSR2 handler that requests a scrub, mirroring
        cache_comparer.go's trigger. Returns False where handlers can't
        be installed (non-main thread, platforms without SIGUSR2)."""
        import signal as _signal

        if signum is None:
            signum = getattr(_signal, "SIGUSR2", None)
            if signum is None:
                return False
        try:
            _signal.signal(signum, lambda *_: self.request())
            return True
        except ValueError:
            return False

    def due(self) -> bool:
        if self._requested:
            return True
        return bool(self.period) and \
            self.clock() - self._last_run >= self.period

    def maybe_scrub(self) -> Optional[ScrubReport]:
        """Run a scrub if a signal requested one or the cadence elapsed.
        Called from the scheduler's housekeeping step; a no-op costs two
        comparisons."""
        if not self.due():
            return None
        return self.scrub()

    # -- the scrub cycle ------------------------------------------------------

    def scrub(self, repair: bool = True) -> ScrubReport:
        start = self.clock()
        # the scrubber is an OBSERVER: its golden-row build and repair
        # writes traverse the instrumented snapshot paths, so active
        # faults (e.g. an unbounded snapshot.write corrupt) must not
        # apply to them — they would corrupt the golden rows the same
        # way and re-corrupt every row the moment it is repaired
        with self._lock, faultpoints.suppressed():
            report = self._scrub_locked(repair)
        self._requested = False
        self._last_run = self.clock()
        report.duration = self.clock() - start
        self.last_report = report
        if self.metrics is not None:
            self.metrics.snapshot_scrub_runs.inc()
            self.metrics.snapshot_scrub_divergences.inc(
                len(report.divergences))
            self.metrics.snapshot_scrub_repairs.inc(report.repaired)
            self.metrics.snapshot_scrub_duration.observe(report.duration)
        return report

    def _golden(self) -> Snapshot:
        """Scratch snapshot re-featurized from the host cache. Shares
        the live vocabularies (interning is idempotent, so ids line up
        and already-known strings cause no growth) but copies the caps —
        scratch growth must never resize the live snapshot's notion of
        its own arrays."""
        live = self.snapshot
        scratch = Snapshot(vocabs=live.vocabs,
                           caps=dataclasses.replace(live.caps))
        for name, ni in self.cache.node_infos.items():
            if ni.node is not None:
                scratch.set_node(ni)
        return scratch

    def _batch_suspects(self, golden: Snapshot, live: Snapshot):
        """Vectorized prefilter over the node-row planes (the host-twin
        batched-diff discipline, ops/hostwave.py): every field group is
        compared golden-vs-live for ALL aligned rows in a handful of
        whole-array ops, and only rows flagged here pay the exact
        per-row, per-field Python compare — at 5000 nodes that compare
        was the scrub's wall clock. Ports compare as sorted rows and
        images as lexicographically sorted (id, size) pairs (complex
        sort), so multiset equality is preserved exactly. Returns the
        suspect-name set, or None when a cap mismatch makes whole-plane
        compares unsound (scratch growth — itself a divergence signal —
        falls back to exact row compares for every node)."""
        names: List[str] = []
        gi: List[int] = []
        li: List[int] = []
        for name, ni in self.cache.node_infos.items():
            if ni.node is None:
                continue
            lidx = live.node_index.get(name)
            if lidx is None or not live.valid[lidx]:
                continue  # missing rows take the repair path regardless
            names.append(name)
            gi.append(golden.node_index[name])
            li.append(lidx)
        if not names:
            return set()
        g = np.asarray(gi)
        l = np.asarray(li)
        suspect = np.zeros(len(names), bool)
        for f in _RESOURCE_FIELDS + _TOPOLOGY_FIELDS:
            a = getattr(golden, f)
            b = getattr(live, f)
            if a.shape[1:] != b.shape[1:]:
                return None
            ra = np.atleast_2d(a[g].reshape(len(names), -1))
            rb = np.atleast_2d(b[l].reshape(len(names), -1))
            if ra.dtype.kind == "f" or rb.dtype.kind == "f":
                ra64 = ra.astype(np.float64)
                rb64 = rb.astype(np.float64)
                eq = (ra64 == rb64) | (np.isnan(ra64) & np.isnan(rb64))
            else:
                eq = ra == rb
            suspect |= ~eq.all(axis=1)
        if (golden.ports.shape[1] != live.ports.shape[1]
                or golden.img_id.shape[1] != live.img_id.shape[1]):
            return None
        suspect |= ~(np.sort(golden.ports[g], axis=1)
                     == np.sort(live.ports[l], axis=1)).all(axis=1)
        genc = (golden.img_id[g].astype(np.float64)
                + 1j * golden.img_size[g].astype(np.float64))
        lenc = (live.img_id[l].astype(np.float64)
                + 1j * live.img_size[l].astype(np.float64))
        suspect |= ~(np.sort(genc, axis=1) == np.sort(lenc, axis=1)).all(axis=1)
        return {n for n, s in zip(names, suspect) if s}

    def _scrub_locked(self, repair: bool) -> ScrubReport:
        live = self.snapshot
        report = ScrubReport()
        golden = self._golden()
        suspects = self._batch_suspects(golden, live)
        host_uids = set()
        for name, ni in self.cache.node_infos.items():
            if ni.node is None:
                continue
            report.nodes_checked += 1
            gidx = golden.node_index[name]
            lidx = live.node_index.get(name)
            if lidx is None or not live.valid[lidx]:
                d = Divergence(name, ["missing-node"])
                report.divergences.append(d)
                if repair:
                    live.set_node(ni)
                    d.repaired = True
                    report.repaired += 1
                lidx = live.node_index.get(name)
                if lidx is None:
                    # audit-only run: still record the node's pods as
                    # host truth so the ghost pass can't misflag them
                    for pod in ni.pods:
                        host_uids.add(pod.uid)
                    continue
                # fall through: the freshly written row needs no compare
                report.pods_checked += self._check_pods(
                    ni, lidx, host_uids, report, repair)
                continue
            bad: List[str] = []
            if suspects is None or name in suspects:
                # flagged by the vectorized prefilter (or the prefilter
                # was unsound): exact per-field compare names the
                # divergent groups for the report
                for f in _RESOURCE_FIELDS + _TOPOLOGY_FIELDS:
                    fill = np.nan if f == "label_nums" else 0
                    if not _rows_equal(getattr(golden, f)[gidx],
                                       getattr(live, f)[lidx], fill=fill):
                        bad.append(f)
                # ports and images are written from set/dict iteration;
                # two equal sets can iterate differently, so compare as
                # multisets
                if sorted(golden.ports[gidx].tolist()) != \
                        sorted(live.ports[lidx].tolist()):
                    bad.append("ports")
                if sorted(zip(golden.img_id[gidx].tolist(),
                              golden.img_size[gidx].tolist())) != \
                        sorted(zip(live.img_id[lidx].tolist(),
                                   live.img_size[lidx].tolist())):
                    bad.append("images")
            if bad:
                d = Divergence(name, bad)
                report.divergences.append(d)
                if repair:
                    # set_node rewrites topology AND (via its internal
                    # refresh_node_resources) the resource aggregates
                    live.set_node(ni)
                    d.repaired = True
                    report.repaired += 1
            report.pods_checked += self._check_pods(
                ni, lidx, host_uids, report, repair)
        self._check_ghosts(host_uids, report, repair)
        return report

    def _check_pods(self, ni: NodeInfo, lidx: int, host_uids: set,
                    report: ScrubReport, repair: bool) -> int:
        """Audit the pod-matrix rows of one node's pods: placement index,
        validity/liveness, and the per-pod request row the device-side
        preemption what-if subtracts (a stale ep_req row silently skews
        victim accounting)."""
        live = self.snapshot
        checked = 0
        for pod in ni.pods:
            host_uids.add(pod.uid)
            checked += 1
            bad: List[str] = []
            slot = live.pod_slot.get(pod.uid)
            if slot is None or not live.ep_valid[slot]:
                bad.append("pod-row-missing")
            else:
                if int(live.ep_node[slot]) != lidx:
                    bad.append("pod-node")
                want_alive = pod.metadata.deletion_timestamp is None
                if bool(live.ep_alive[slot]) != want_alive:
                    bad.append("pod-alive")
                want_req = live._res_vec(
                    Resource.from_map(api.get_resource_request(pod)))
                if not _rows_equal(live.ep_req[slot], want_req):
                    bad.append("pod-req")
                if int(live.ep_prio[slot]) != api.pod_priority(pod):
                    bad.append("pod-prio")
            if bad:
                d = Divergence(f"{ni.node.name}/{pod.uid}", bad)
                report.divergences.append(d)
                if repair:
                    # drop the bind-echo signature first or add_pod's
                    # skip path would leave the corrupt row in place
                    live._pod_sig.pop(pod.uid, None)
                    live.add_pod(pod)
                    d.repaired = True
                    report.repaired += 1
        return checked

    def _check_ghosts(self, host_uids: set, report: ScrubReport,
                      repair: bool) -> None:
        live = self.snapshot
        # ghost pod rows: valid in the matrix, unknown to the host cache
        # (staged pending rows are ep_valid=False and never flagged)
        for uid, slot in list(live.pod_slot.items()):
            if live.ep_valid[slot] and uid not in host_uids:
                d = Divergence(uid, ["ghost-pod"])
                report.divergences.append(d)
                if repair:
                    live.remove_pod_by_uid(uid)
                    d.repaired = True
                    report.repaired += 1
        # ghost node rows: valid in the tensors, gone from the host cache
        for name in list(live.node_index):
            idx = live.node_index[name]
            if not live.valid[idx]:
                continue
            ni = self.cache.node_infos.get(name)
            if ni is None or ni.node is None:
                d = Divergence(name, ["ghost-node"])
                report.divergences.append(d)
                if repair:
                    live.remove_node(name)
                    d.repaired = True
                    report.repaired += 1

    # -- full rebuild ---------------------------------------------------------

    def rebuild(self) -> None:
        """Forced from-scratch rewrite of every live row from host truth
        — the device-path circuit breaker's recovery action: a faulting
        device path may have left the mirror (or its device-side cache)
        in an arbitrary state, so on re-admission nothing incremental is
        trusted. Staged (ep_valid=False) pending rows are preserved; the
        bind-echo signatures are dropped so every subsequent add_pod
        rewrites in full."""
        live = self.snapshot
        with self._lock, faultpoints.suppressed():
            live._pod_sig.clear()
            for name, ni in self.cache.node_infos.items():
                if ni.node is None:
                    continue
                live.set_node(ni)
                for pod in ni.pods:
                    live.add_pod(pod)
            report = ScrubReport()
            host_uids = {p.uid for ni in self.cache.node_infos.values()
                         for p in ni.pods}
            self._check_ghosts(host_uids, report, repair=True)
            live.dirty_resources = live.dirty_topology = True
            live.dirty_pods = True
            live._device_cache.clear()

    # -- compaction (vocab mark-and-sweep + row/bucket shrink) ----------------

    def compact_due(self) -> bool:
        """Governor demand, or the cadence elapsed with something to
        reclaim (row removals since the last compaction — churn is the
        only way vocab garbage accrues)."""
        live = self.snapshot
        if live.compaction_requested:
            return True
        return bool(self.compact_period) and \
            live.removals_since_compact > 0 and \
            self.clock() - self._last_compact >= self.compact_period

    def maybe_compact(self) -> Optional[dict]:
        """Run a compaction if the governor demanded one or the cadence
        elapsed. Called from the scheduler's housekeeping step."""
        if not self.compact_due():
            return None
        if self.snapshot.compaction_requested:
            # governor demand: reclaiming HBM outranks jit-cache
            # stability, so any smaller bucket is taken
            return self.compact(trigger="governor", force=True)
        return self.compact(trigger="cadence")

    def compact(self, trigger: str = "cadence",
                force: bool = False) -> Optional[dict]:
        """Vocab mark-and-sweep + row compaction: rebuild a scratch
        snapshot from host truth against a FRESH VocabSet (only strings
        live objects still reference survive), then adopt it into the
        live snapshot in place (Snapshot._compact — array swap, vocab
        adopt, generation bump, full re-upload). Returns a summary
        dict, or None when deferred (staged rows outstanding: device
        kernels hold staged row indices mid-round, so the request is
        parked for the next housekeeping pass)."""
        live = self.snapshot
        with self._lock:
            # the chaos seam fires BEFORE entering suppressed() — a
            # raise/latency-mode fault must be able to hit the
            # housekeeping path like any other subsystem
            faultpoints.fire("snapshot.compact", payload=(live, trigger))
            if live.has_staged_rows():
                live.compaction_requested = True
                return None
            start = self.clock()
            with faultpoints.suppressed():
                before = live.vocabs.sizes()
                before_hbm = live.projected_hbm_bytes()
                scratch = self._compact_scratch()
                shrunk = live._compact(scratch, force=force)
            summary = {
                "trigger": trigger,
                "shrunk": shrunk,
                "vocabs_before": before,
                "vocabs_after": live.vocabs.sizes(),
                "hbm_before": before_hbm,
                "hbm_after": live.projected_hbm_bytes(),
                "duration": self.clock() - start,
            }
        self._last_compact = self.clock()
        self.last_compaction = summary
        if self.metrics is not None:
            self.metrics.snapshot_compactions_total.labels(
                trigger=trigger).inc()
        return summary

    def _compact_scratch(self) -> Snapshot:
        """Scratch snapshot re-featurized from the host cache against a
        fresh VocabSet, with every snapshot-owned Caps dim reset to its
        floor so the rebuild discovers the minimal buckets. Node rows
        keep the live snapshot's relative index order and pod rows the
        live slot order: row order feeds every argmax tie-break, so
        preserving it is what makes placements bit-equal across the
        compaction."""
        live = self.snapshot
        caps = dataclasses.replace(live.caps)
        floors = type(live.caps)()
        for d in SNAPSHOT_DIMS:
            setattr(caps, d, getattr(floors, d))
        scratch = Snapshot(vocabs=VocabSet(), caps=caps)
        placed = set()
        for idx, name in enumerate(live.node_names):
            if live.node_index.get(name) != idx:
                continue  # freed row whose name was never overwritten
            ni = self.cache.node_infos.get(name)
            if ni is not None and ni.node is not None:
                scratch.set_node(ni)
                placed.add(name)
        for name, ni in self.cache.node_infos.items():
            # host truth the live snapshot never saw (possible only
            # between an event and its apply; harmless to include)
            if name not in placed and ni.node is not None:
                scratch.set_node(ni)
        pods_by_uid = {}
        for _name, ni in self.cache.node_infos.items():
            for pod in ni.pods:
                pods_by_uid[pod.uid] = pod
        added = set()
        for uid, _slot in sorted(live.pod_slot.items(),
                                 key=lambda kv: kv[1]):
            pod = pods_by_uid.get(uid)
            if pod is not None:
                scratch.add_pod(pod)
                added.add(uid)
        for uid, pod in pods_by_uid.items():
            if uid not in added:
                scratch.add_pod(pod)
        return scratch
