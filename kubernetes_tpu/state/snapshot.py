"""Host->HBM cluster snapshot.

Maintains the dense NodeTensors / PodMatrix arrays (ops/encoding.py) as
numpy buffers, updated incrementally from scheduler events, and uploads
dirty groups to the device per scheduling cycle. This replaces the
reference's per-cycle `UpdateNodeNameToInfoMap` snapshot point
(pkg/scheduler/core/generic_scheduler.go:124) — instead of copying a Go
map, we keep the device mirror warm and re-upload only what changed.

Dirtiness is tracked in three groups with very different change rates:
  * resources  (requested/nonzero/pod_count)      — every bind
  * topology   (labels/taints/conds/ports/images) — node lifecycle only
  * pods       (the existing-pod matrix)          — every bind

and, within each group, per ROW: a bind/evict/heartbeat re-uploads only
the touched node/pod/term rows (gathered host rows + an index vector,
applied with ONE jitted scatter per dirty group), so steady-state
upload bytes scale with the churn, not the cluster. A whole-group flag
(set by the scrubber, growth, or cache invalidation) or a dirty
fraction past DELTA_MAX_FRACTION falls back to the full upload. With a mesh (to_device(mesh=...)) the node groups are committed
to the "nodes"-axis NamedSharding and the pod/term groups replicated —
parallel/mesh.py group_shardings — so the wave kernels run under GSPMD
partitioning with no program change.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import types as api
from ..ops import encoding as enc
from ..utils import faultpoints
from .node_info import NodeInfo
from .vocab import Interner, VocabSet, bucket_size


def _parse_label_num(v: str) -> float:
    try:
        return float(int(v))
    except (ValueError, TypeError):
        return math.nan


# Delta-upload tuning: the dirty-row count buckets to a power of two
# (>= DELTA_MIN_ROWS, padded with duplicate writes of the first row) so
# the per-group scatter program compiles O(log N) variants, not one per
# distinct churn size; a bucketed fraction past DELTA_MAX_FRACTION
# falls back to the whole-group upload (at that point the row
# bookkeeping buys nothing).
DELTA_MAX_FRACTION = 0.5
DELTA_MIN_ROWS = 16

# Caps dims the snapshot itself grows — and a compaction may shrink.
# Every other Caps dim (P, UI, the pod-batch dims...) belongs to the
# featurizer/wave plane and is never touched by _compact.
SNAPSHOT_DIMS = ("N", "Z", "K", "KP", "R", "T", "PP", "NI", "M", "E",
                 "TE", "TV", "TNS", "LV")

# every numpy plane _grow pads and _compact adopts, in _grow order
SNAPSHOT_ARRAYS = (
    "alloc", "requested", "nonzero", "pod_count", "allowed_pods",
    "labels", "label_nums", "taint_key", "taint_val", "taint_effect",
    "cond", "ports", "zone_id", "rack_id", "superpod_id", "accel_gen",
    "img_id", "img_size", "avoid", "valid",
    "ep_labels", "ep_ns", "ep_node", "ep_valid", "ep_alive", "ep_req",
    "ep_prio",
    "t_kind", "t_owner", "t_node", "t_tk", "t_weight", "t_ns", "t_key",
    "t_op", "t_vals", "t_valid")

_ROW_UPDATE = None


def _row_update():
    """Lazily-jitted batched row scatter: one program application per
    (group shapes, row-count bucket) writes the gathered host rows into
    every array of a cached device group at the given indices. The host
    row slices + the index vector are the ONLY host->device transfer.
    Pad entries duplicate the first row's (index, content) pair, so
    duplicate-index scatter order can't matter — every duplicate writes
    identical bytes."""
    global _ROW_UPDATE
    if _ROW_UPDATE is None:
        import jax

        @jax.jit
        def upd(devs, updates, idx):
            return tuple(d.at[idx].set(u) for d, u in zip(devs, updates))

        _ROW_UPDATE = upd
    return _ROW_UPDATE


class Snapshot:
    """Mutable numpy mirror + device cache."""

    def __init__(self, vocabs: Optional[VocabSet] = None, caps: Optional[enc.Caps] = None):
        self.vocabs = vocabs or VocabSet()
        self.caps = caps or enc.Caps()
        self.node_index: Dict[str, int] = {}
        self.node_names: List[str] = []
        self._free_nodes: List[int] = []
        self.extended = self.vocabs.resources  # extended resource -> column - RES_FIXED + 1
        self._alloc_nodes()
        # existing-pod matrix
        self.pod_slot: Dict[str, int] = {}
        self._free_slots: List[int] = []
        self._next_slot = 0
        self._alloc_pods()
        # inter-pod affinity term table
        self.term_rows: Dict[str, List[int]] = {}  # pod uid -> row indices
        # uid -> (node_idx, alive, labels) of the last written row; lets
        # add_pod skip the bind-confirmation echo (see add_pod)
        self._pod_sig: Dict[str, tuple] = {}
        self._free_terms: List[int] = []
        self._next_term = 0
        self._alloc_terms()
        # whole-group dirty flags: True forces a full re-upload of the
        # group (set by growth, the scrubber's repairs, and external
        # invalidation). Fine-grained churn goes through _mark_rows
        # instead, so a steady-state bind re-uploads only touched rows.
        self.dirty_resources = True
        self.dirty_topology = True
        self.dirty_pods = True
        # per-group dirty ROW indices ("res"/"topo" over N, "pods" over
        # M, "terms" over E) — the delta-upload input
        self._dirty_rows: Dict[str, set] = {
            "res": set(), "topo": set(), "pods": set(), "terms": set()}
        self._device_cache: Dict[str, object] = {}
        # device telemetry: cumulative host->HBM upload bytes and the
        # byte size of each resident group — the scheduler exports these
        # as snapshot_upload_bytes_total / snapshot_hbm_bytes
        self.upload_bytes_total = 0
        self._group_bytes: Dict[str, int] = {}
        # sharding bookkeeping for honest HBM accounting: which cached
        # groups are node-sharded, the mesh's device list, and how many
        # node shards it splits them into (1/None = unsharded)
        self._group_sharded: Dict[str, bool] = {}
        self._mesh_devices: List[str] = []
        self._node_shards = 1
        # HBM budget governor: 0 = unlimited. A _grow that pushes the
        # projected footprint past the budget sets compaction_requested
        # (the growth itself proceeds — the rows must land somewhere)
        # and the scheduler's housekeeping compacts before the next
        # round commits the bigger footprint for good.
        self.hbm_budget_bytes = 0
        self.compaction_requested = False
        # node/pod row removals since the last compaction — the cadence
        # trigger's "is there anything to reclaim" signal
        self.removals_since_compact = 0

    def _mark_rows(self, group: str, *rows: int) -> None:
        self._dirty_rows[group].update(rows)

    def _account_upload(self, group: str, arrays) -> None:
        nbytes = sum(int(a.nbytes) for a in arrays)
        self.upload_bytes_total += nbytes
        self._group_bytes[group] = nbytes

    def hbm_bytes(self) -> int:
        """TRUE byte footprint of the device-resident mirror summed over
        every device: node-sharded groups count once (the shards tile the
        array), replicated groups once PER device. Unsharded, this is
        exactly the cached groups' host sizes, as before."""
        ndev = max(len(self._mesh_devices), 1)
        if ndev == 1:
            return sum(self._group_bytes.values())
        total = 0
        for g, b in self._group_bytes.items():
            if self._group_sharded.get(g):
                # sharded over "nodes", replicated across any "wave" axis
                total += b * (ndev // self._node_shards)
            else:
                total += b * ndev
        return total

    def hbm_bytes_per_device(self) -> Dict[str, int]:
        """Per-device HBM footprint under mesh sharding ({} when
        unsharded): each device holds 1/node_shards of every node group
        plus a full replica of the pod/term groups."""
        if len(self._mesh_devices) <= 1:
            return {}
        per = 0
        for g, b in self._group_bytes.items():
            per += b // self._node_shards if self._group_sharded.get(g) else b
        return {d: per for d in self._mesh_devices}

    def projected_hbm_bytes(self) -> int:
        """What the device mirror will occupy after the next full
        upload, computed from the HOST arrays under the same sharding
        accounting as hbm_bytes() — the governor's check input.
        hbm_bytes() lags until an upload actually lands; a budget check
        against it would admit one over-budget round first."""
        ndev = max(len(self._mesh_devices), 1)
        total = 0
        for g in ("res", "topo", "pods", "terms"):
            b = sum(int(a.nbytes) for a in self._group_host(g))
            if ndev > 1:
                b = (b * (ndev // self._node_shards)
                     if self._group_sharded.get(g) else b * ndev)
            total += b
        return total

    def hbm_headroom_bytes(self) -> Optional[int]:
        """Budget minus projected footprint (negative = over budget),
        None when no budget is configured."""
        if not self.hbm_budget_bytes:
            return None
        return self.hbm_budget_bytes - self.projected_hbm_bytes()

    # ---- allocation / growth ----------------------------------------------

    def _alloc_nodes(self):
        c = self.caps
        self.alloc = np.zeros((c.N, c.R), np.float32)
        self.requested = np.zeros((c.N, c.R), np.float32)
        self.nonzero = np.zeros((c.N, 2), np.float32)
        self.pod_count = np.zeros((c.N,), np.int32)
        self.allowed_pods = np.zeros((c.N,), np.int32)
        self.labels = np.zeros((c.N, c.K), np.int32)
        self.label_nums = np.full((c.N, c.K), np.nan, np.float32)
        self.taint_key = np.zeros((c.N, c.T), np.int32)
        self.taint_val = np.zeros((c.N, c.T), np.int32)
        self.taint_effect = np.zeros((c.N, c.T), np.int32)
        self.cond = np.zeros((c.N, enc.N_COND), bool)
        self.ports = np.zeros((c.N, c.PP), np.int32)
        self.zone_id = np.zeros((c.N,), np.int32)
        # topology + heterogeneity columns (ops/topology.py): rack and
        # superpod ids live in the shared zone vocabulary (hierarchical
        # keys, see api.get_rack_key), so they are bounded by caps.Z
        self.rack_id = np.zeros((c.N,), np.int32)
        self.superpod_id = np.zeros((c.N,), np.int32)
        self.accel_gen = np.zeros((c.N,), np.int32)
        self.img_id = np.zeros((c.N, c.NI), np.int32)
        self.img_size = np.zeros((c.N, c.NI), np.float32)
        self.avoid = np.zeros((c.N,), bool)
        self.valid = np.zeros((c.N,), bool)

    def _alloc_pods(self):
        c = self.caps
        self.ep_labels = np.zeros((c.M, c.KP), np.int32)
        self.ep_ns = np.zeros((c.M,), np.int32)
        self.ep_node = np.zeros((c.M,), np.int32)
        self.ep_valid = np.zeros((c.M,), bool)
        self.ep_alive = np.zeros((c.M,), bool)
        # per-pod resource requests + priority: the device-side
        # preemption what-if subtracts victim rows from node usage
        # (ops/preempt.py; reference selectVictimsOnNode removes pods
        # from the cloned NodeInfo, generic_scheduler.go:898)
        self.ep_req = np.zeros((c.M, c.R), np.float32)
        self.ep_prio = np.zeros((c.M,), np.int32)

    def _alloc_terms(self):
        c = self.caps
        self.t_kind = np.zeros((c.E,), np.int32)
        self.t_owner = np.zeros((c.E,), np.int32)
        self.t_node = np.zeros((c.E,), np.int32)
        self.t_tk = np.zeros((c.E,), np.int32)
        self.t_weight = np.zeros((c.E,), np.float32)
        self.t_ns = np.zeros((c.E, c.TNS), np.int32)
        self.t_key = np.zeros((c.E, c.TE), np.int32)
        self.t_op = np.full((c.E, c.TE), enc.OP_PAD, np.int32)
        self.t_vals = np.full((c.E, c.TE, c.TV), -1, np.int32)
        self.t_valid = np.zeros((c.E,), bool)

    def _grow(self, **dims):
        """Grow capacity dims, preserving data. Triggers jit retrace."""
        c = self.caps
        for k, v in dims.items():
            setattr(c, k, bucket_size(v, getattr(c, k)))

        def pad(a, shape, fill=0):
            out = np.full(shape, fill, a.dtype)
            sl = tuple(slice(0, s) for s in a.shape)
            out[sl] = a
            return out

        self.alloc = pad(self.alloc, (c.N, c.R))
        self.requested = pad(self.requested, (c.N, c.R))
        self.nonzero = pad(self.nonzero, (c.N, 2))
        self.pod_count = pad(self.pod_count, (c.N,))
        self.allowed_pods = pad(self.allowed_pods, (c.N,))
        self.labels = pad(self.labels, (c.N, c.K))
        self.label_nums = pad(self.label_nums, (c.N, c.K), np.nan)
        self.taint_key = pad(self.taint_key, (c.N, c.T))
        self.taint_val = pad(self.taint_val, (c.N, c.T))
        self.taint_effect = pad(self.taint_effect, (c.N, c.T))
        self.cond = pad(self.cond, (c.N, enc.N_COND))
        self.ports = pad(self.ports, (c.N, c.PP))
        self.zone_id = pad(self.zone_id, (c.N,))
        self.rack_id = pad(self.rack_id, (c.N,))
        self.superpod_id = pad(self.superpod_id, (c.N,))
        self.accel_gen = pad(self.accel_gen, (c.N,))
        self.img_id = pad(self.img_id, (c.N, c.NI))
        self.img_size = pad(self.img_size, (c.N, c.NI))
        self.avoid = pad(self.avoid, (c.N,))
        self.valid = pad(self.valid, (c.N,))
        self.ep_labels = pad(self.ep_labels, (c.M, c.KP))
        self.ep_ns = pad(self.ep_ns, (c.M,))
        self.ep_node = pad(self.ep_node, (c.M,))
        self.ep_valid = pad(self.ep_valid, (c.M,))
        self.ep_alive = pad(self.ep_alive, (c.M,))
        self.ep_req = pad(self.ep_req, (c.M, c.R))
        self.ep_prio = pad(self.ep_prio, (c.M,))
        self.t_kind = pad(self.t_kind, (c.E,))
        self.t_owner = pad(self.t_owner, (c.E,))
        self.t_node = pad(self.t_node, (c.E,))
        self.t_tk = pad(self.t_tk, (c.E,))
        self.t_weight = pad(self.t_weight, (c.E,))
        self.t_ns = pad(self.t_ns, (c.E, c.TNS))
        self.t_key = pad(self.t_key, (c.E, c.TE))
        self.t_op = pad(self.t_op, (c.E, c.TE), enc.OP_PAD)
        self.t_vals = pad(self.t_vals, (c.E, c.TE, c.TV), -1)
        self.t_valid = pad(self.t_valid, (c.E,))
        # realloc: every dirty row range is void (the cached device
        # arrays have the old shapes) — whole-group flags take over
        self.dirty_resources = self.dirty_topology = self.dirty_pods = True
        for rows in self._dirty_rows.values():
            rows.clear()
        # HBM budget governor: over-budget growth demands a compaction
        # instead of letting the next upload hit XLA's allocator
        if self.hbm_budget_bytes and \
                self.projected_hbm_bytes() > self.hbm_budget_bytes:
            self.compaction_requested = True

    def has_staged_rows(self) -> bool:
        """True while any pipeline-staged pod row is outstanding. A
        compaction renumbers every row index, but the device kernels
        hold staged pm_rows/term_rows by INDEX mid-round — compacting
        under them would scatter placements into the wrong rows, so
        callers must defer (or unstage first)."""
        return any(sig[0] == "staged" for sig in self._pod_sig.values())

    def _compact(self, scratch: "Snapshot", force: bool = False
                 ) -> Dict[str, Tuple[int, int]]:
        """Adopt a freshly-rebuilt scratch snapshot in place — the
        inverse of _grow. The scratch (built by the scrubber's
        golden-row machinery against a FRESH VocabSet) holds the same
        live rows densely renumbered with freshly-assigned vocab ids;
        this commit step swaps its arrays, registries, and vocabularies
        into the live snapshot.

        Shrink hysteresis: a dim only shrinks when its rebuilt bucket
        is at most HALF the current one — at least one power-of-two
        step of slack beyond the grow threshold, so a grow right after
        a cadence compaction can't thrash the jit cache. force=True
        (governor/OOM demand) takes any smaller bucket: reclaiming HBM
        outranks a retrace. Dims that don't shrink are re-grown on the
        scratch to the live bucket first, keeping shapes_key stable.

        Returns {dim: (old, new)} for every dim that shrank. Vocab
        identity is preserved (adopt_all rewrites contents in place)
        and the generation bump invalidates every featurizer cache."""
        assert not self.has_staged_rows(), \
            "compaction with staged rows outstanding"
        regrow: Dict[str, int] = {}
        shrunk: Dict[str, Tuple[int, int]] = {}
        for d in SNAPSHOT_DIMS:
            cur = getattr(self.caps, d)
            tgt = getattr(scratch.caps, d)
            if tgt >= cur:
                continue
            if (tgt < cur) if force else (tgt * 2 <= cur):
                shrunk[d] = (cur, tgt)
            else:
                regrow[d] = cur
        if regrow:
            scratch._grow(**regrow)
        self.vocabs.adopt_all(scratch.vocabs)
        for d in SNAPSHOT_DIMS:
            setattr(self.caps, d, getattr(scratch.caps, d))
        for name in SNAPSHOT_ARRAYS:
            setattr(self, name, getattr(scratch, name))
        self.node_index = dict(scratch.node_index)
        self.node_names = list(scratch.node_names)
        self._free_nodes = list(scratch._free_nodes)
        self.pod_slot = dict(scratch.pod_slot)
        self._free_slots = list(scratch._free_slots)
        self._next_slot = scratch._next_slot
        self.term_rows = {uid: list(rows)
                          for uid, rows in scratch.term_rows.items()}
        self._free_terms = list(scratch._free_terms)
        self._next_term = scratch._next_term
        self._pod_sig = dict(scratch._pod_sig)
        # everything the device holds is now stale: full re-upload
        self.dirty_resources = self.dirty_topology = self.dirty_pods = True
        for rows in self._dirty_rows.values():
            rows.clear()
        self._device_cache.clear()
        self._group_bytes.clear()
        self.compaction_requested = False
        self.removals_since_compact = 0
        return shrunk

    # ---- resource columns ---------------------------------------------------

    def _res_col(self, name: str) -> int:
        col = enc.RES_FIXED - 1 + self.extended.intern(name)
        if col >= self.caps.R:
            self._grow(R=col + 1)
        return col

    def _res_vec(self, r) -> np.ndarray:
        """node_info.Resource -> f32 row of width caps.R."""
        cols = [(self._res_col(name), q) for name, q in r.scalars.items()]
        out = np.zeros((self.caps.R,), np.float32)  # after growth from _res_col
        out[enc.RES_CPU] = r.milli_cpu
        out[enc.RES_MEM] = r.memory
        out[enc.RES_EPH] = r.ephemeral_storage
        for col, q in cols:
            out[col] = q
        return out

    # ---- node events --------------------------------------------------------

    def ensure_node(self, name: str) -> int:
        idx = self.node_index.get(name)
        if idx is None:
            if self._free_nodes:
                idx = self._free_nodes.pop()
                self.node_names[idx] = name
            else:
                idx = len(self.node_names)
                if idx >= self.caps.N:
                    self._grow(N=idx + 1)
                self.node_names.append(name)
            self.node_index[name] = idx
        return idx

    def set_node(self, ni: NodeInfo):
        """Refresh a node's topology + allocatable row from its NodeInfo."""
        node = ni.node
        assert node is not None
        idx = self.ensure_node(node.name)
        v = self.vocabs
        # labels
        lbls = node.metadata.labels or {}
        for key in lbls:
            kid = v.label_keys.intern(key)
            if kid >= self.caps.K:
                self._grow(K=kid + 1)
        self.labels[idx, :] = 0
        self.label_nums[idx, :] = np.nan
        for key, val in lbls.items():
            kid = v.label_keys.intern(key)
            self.labels[idx, kid] = v.label_values.intern(val)
            self.label_nums[idx, kid] = _parse_label_num(val)
        # taints
        if len(ni.taints) > self.caps.T:
            self._grow(T=len(ni.taints))
        self.taint_key[idx, :] = 0
        self.taint_val[idx, :] = 0
        self.taint_effect[idx, :] = 0
        for i, t in enumerate(ni.taints):
            self.taint_key[idx, i] = v.taint_keys.intern(t.key)
            self.taint_val[idx, i] = v.taint_values.intern(t.value)
            self.taint_effect[idx, i] = enc.EFFECT_IDS[t.effect]
        # conditions
        # Reference iterates only *present* conditions (predicates.go:1591):
        # a node that hasn't reported Ready at all is NOT rejected.
        cond = NodeInfo._cond
        ready = cond(node, api.NODE_READY)
        self.cond[idx, enc.COND_NOT_READY] = ready not in ("", api.COND_TRUE)
        self.cond[idx, enc.COND_OUT_OF_DISK] = (
            cond(node, api.NODE_OUT_OF_DISK) not in ("", api.COND_FALSE)
        )
        self.cond[idx, enc.COND_NET_UNAVAIL] = (
            cond(node, api.NODE_NETWORK_UNAVAILABLE) not in ("", api.COND_FALSE)
        )
        self.cond[idx, enc.COND_UNSCHEDULABLE] = node.spec.unschedulable
        self.cond[idx, enc.COND_MEM_PRESSURE] = ni.memory_pressure
        self.cond[idx, enc.COND_DISK_PRESSURE] = ni.disk_pressure
        self.cond[idx, enc.COND_PID_PRESSURE] = ni.pid_pressure
        # allocatable
        self.alloc[idx, :] = self._res_vec(ni.allocatable)
        self.allowed_pods[idx] = ni.allocatable.allowed_pod_number
        # zone
        zk = api.get_zone_key(node)
        zid = v.zones.intern(zk) if zk else 0
        if zid >= self.caps.Z:
            self._grow(Z=zid + 1)
        self.zone_id[idx] = zid
        # rack / superpod: interned into the SAME zone vocabulary with
        # hierarchical keys ("sp:<v>", "sp:<v>/rk:<r>"), so both ids stay
        # under caps.Z and every topology segment-sum reuses num_zones as
        # its segment count — no new static kernel args
        spk = api.get_superpod_key(node)
        spid = v.zones.intern(spk) if spk else 0
        rk = api.get_rack_key(node)
        rid = v.zones.intern(rk) if rk else 0
        top = max(spid, rid)
        if top >= self.caps.Z:
            self._grow(Z=top + 1)
        self.superpod_id[idx] = spid
        self.rack_id[idx] = rid
        self.accel_gen[idx] = api.get_accel_gen(node)
        # images
        imgs = list(ni.image_sizes.items())
        if len(imgs) > self.caps.NI:
            imgs = imgs[: self.caps.NI]  # overflow images simply don't score
        self.img_id[idx, :] = 0
        self.img_size[idx, :] = 0.0
        for i, (name_, sz) in enumerate(imgs):
            self.img_id[idx, i] = v.images.intern(name_)
            self.img_size[idx, i] = sz
        # prefer-avoid annotation (simplified: presence only; see ops/scores.py)
        self.avoid[idx] = "scheduler.alpha.kubernetes.io/preferAvoidPods" in (
            node.metadata.annotations or {}
        )
        self.valid[idx] = True
        self.refresh_node_resources(ni)
        self._mark_rows("topo", idx)

    def remove_node(self, name: str):
        idx = self.node_index.pop(name, None)
        if idx is not None:
            # sweep hook: the row is freed but every label/zone/rack/
            # image string this node interned stays in the vocabularies
            # until a compaction rebuilds them — count the garbage so
            # the housekeeping cadence knows a sweep has something to
            # reclaim (the append-only vocab leak, ISSUE 20)
            self.removals_since_compact += 1
            self.valid[idx] = False
            self._free_nodes.append(idx)
            # Drop this node's rows from the pod matrix so a future node
            # reusing the index doesn't inherit ghost pods in spreading.
            stale = (self.ep_node == idx) & self.ep_valid
            if stale.any():
                self.ep_valid[stale] = False
                self.ep_alive[stale] = False
                self._mark_rows("pods", *np.flatnonzero(stale).tolist())
                for uid, slot in list(self.pod_slot.items()):
                    if stale[slot]:
                        del self.pod_slot[uid]
                        # sig must die with the row: a node flap that
                        # reuses this node index would otherwise make
                        # add_pod's echo-skip treat the re-delivered pod
                        # as already written and drop it forever
                        self._pod_sig.pop(uid, None)
                        self._free_slots.append(slot)
                        self._clear_pod_terms(uid)
            self._mark_rows("topo", idx)

    def refresh_node_resources(self, ni: NodeInfo):
        """Fast path run on every (un)bind: just the resource aggregates."""
        if ni.node is None:
            return
        idx = self.node_index.get(ni.node.name)
        if idx is None:
            return
        self.requested[idx, :] = self._res_vec(ni.requested)
        self.nonzero[idx, 0] = ni.nonzero_milli_cpu
        self.nonzero[idx, 1] = ni.nonzero_memory
        self.pod_count[idx] = len(ni.pods)
        # used host ports
        up = list(ni.used_ports)
        if len(up) > self.caps.PP:
            self._grow(PP=len(up))
        self.ports[idx, :] = 0
        for i, (proto, _ip, port) in enumerate(up):
            self.ports[idx, i] = self.vocabs.port_id(proto, port)
        self._mark_rows("res", idx)
        # chaos seam: fires AFTER the row write so a `corrupt`-mode
        # fault leaves a silently-divergent row for the scrubber to
        # catch; one dict check when no faults are armed
        faultpoints.fire("snapshot.write", payload=(self, idx))

    # ---- existing-pod matrix ------------------------------------------------

    def _alloc_slot(self, uid: str) -> int:
        slot = self.pod_slot.get(uid)
        if slot is None:
            if self._free_slots:
                slot = self._free_slots.pop()
            else:
                slot = self._next_slot
                self._next_slot += 1
                if slot >= self.caps.M:
                    self._grow(M=slot + 1)
            self.pod_slot[uid] = slot
        return slot

    def _write_pod_row(self, pod: api.Pod, slot: int, node_idx: int,
                       active: bool):
        v = self.vocabs
        for key in pod.metadata.labels or {}:
            kid = v.pod_label_keys.intern(key)
            if kid >= self.caps.KP:
                self._grow(KP=kid + 1)
        self.ep_labels[slot, :] = 0
        for key, val in (pod.metadata.labels or {}).items():
            self.ep_labels[slot, v.pod_label_keys.intern(key)] = v.label_values.intern(val)
        self.ep_ns[slot] = v.namespaces.intern(pod.namespace)
        self.ep_node[slot] = node_idx
        self.ep_valid[slot] = active
        from .node_info import Resource

        self.ep_req[slot, :] = self._res_vec(
            Resource.from_map(api.get_resource_request(pod)))
        self.ep_prio[slot] = api.pod_priority(pod)
        self.ep_alive[slot] = (active
                               and pod.metadata.deletion_timestamp is None)

    def _row_sig(self, pod: api.Pod, node_idx):
        """Row-content signature for bind-echo/staged-row detection.
        node_idx is an int placement or the sentinel "staged"; both the
        staging and commit sites MUST build sigs through this helper or
        the staged fast path silently stops matching."""
        return (node_idx, pod.metadata.deletion_timestamp is None,
                tuple(sorted((pod.metadata.labels or {}).items())))

    def add_pod(self, pod: api.Pod):
        """Add/refresh a scheduled pod's row in the PodMatrix."""
        node_idx = self.node_index.get(pod.spec.node_name)
        if node_idx is None:
            return
        # bind-confirmation echo: the informer re-delivers the pod the
        # commit just wrote. Labels and placement unchanged -> the row
        # (and term rows — pod affinity is spec-immutable in the API) is
        # already exact; skipping avoids rewriting every row twice per
        # bind and re-marking the device mirror dirty
        sig = self._row_sig(pod, node_idx)
        prev = self._pod_sig.get(pod.uid)
        if prev == sig:
            return
        if prev == self._row_sig(pod, "staged"):
            # pipeline-staged row being activated at commit: labels and
            # term programs were already written at stage time (affinity
            # is spec-immutable), only placement/validity change — skip
            # re-interning labels and recompiling term selectors
            slot = self.pod_slot[pod.uid]
            self.ep_node[slot] = node_idx
            self.ep_valid[slot] = True
            self.ep_alive[slot] = sig[1]
            self._mark_rows("pods", slot)
            for row in self.term_rows.get(pod.uid, ()):
                self.t_node[row] = node_idx
                self.t_valid[row] = True
                self._mark_rows("terms", row)
            self._pod_sig[pod.uid] = sig
            return
        slot = self._alloc_slot(pod.uid)
        self._write_pod_row(pod, slot, node_idx, active=True)
        self._set_pod_terms(pod, slot, node_idx)
        self._pod_sig[pod.uid] = sig
        self._mark_rows("pods", slot)

    def stage_pending(self, pods) -> Tuple[np.ndarray, np.ndarray]:
        """Pre-stage pending pods into the PodMatrix/TermTable with
        valid=False rows: labels, namespaces, and term programs are
        written now so the device-resident pipeline
        (ops/kernel.py schedule_wave_resident) can flip validity and set
        node indices on device as placements happen — no host roundtrip
        between waves. Returns (pm_rows i32 [n], term_rows i32 [n, TPP],
        -1 pads). Slots stay registered to the pod uid: the post-fetch
        host commit's add_pod() reuses them; unstage() frees rows of
        pods that didn't place."""
        n = len(pods)
        pm_rows = np.full(max(n, 1), -1, np.int32)
        per_pod_terms: List[List[int]] = []
        for i, pod in enumerate(pods):
            slot = self._alloc_slot(pod.uid)
            # staged alive=True: anti-affinity of later waves must see it
            # once placed (the device only flips valid/node)
            self._write_pod_row(pod, slot, node_idx=0, active=False)
            self.ep_alive[slot] = pod.metadata.deletion_timestamp is None
            self._mark_rows("pods", slot)
            pm_rows[i] = slot
            self._set_pod_terms(pod, slot, node_idx=0, active=False)
            per_pod_terms.append(list(self.term_rows.get(pod.uid, ())))
            # mark the row as staged so the commit-time add_pod can take
            # the fast activate path instead of rewriting it
            self._pod_sig[pod.uid] = self._row_sig(pod, "staged")
        tpp = max([len(t) for t in per_pod_terms] + [1])
        term_rows = np.full((max(n, 1), tpp), -1, np.int32)
        for i, rows in enumerate(per_pod_terms):
            term_rows[i, :len(rows)] = rows
        return pm_rows, term_rows

    def unstage(self, pod: api.Pod):
        """Free the staged rows of a pod the pipeline did not place."""
        self.remove_pod(pod)

    def remove_pod(self, pod: api.Pod):
        self.remove_pod_by_uid(pod.uid)

    def remove_pod_by_uid(self, uid: str):
        """Row removal keyed by uid alone — the scrubber drops ghost
        rows whose pod object the host cache no longer holds."""
        slot = self.pod_slot.pop(uid, None)
        self._pod_sig.pop(uid, None)
        if slot is not None:
            self.removals_since_compact += 1
            self.ep_valid[slot] = False
            self.ep_alive[slot] = False
            self._free_slots.append(slot)
            self._clear_pod_terms(uid)
            self._mark_rows("pods", slot)

    # ---- inter-pod affinity term table --------------------------------------

    def label_key_col(self, key: str) -> int:
        """Intern a node-label key (e.g. an affinity topologyKey), growing
        the label matrix so the column is addressable."""
        kid = self.vocabs.label_keys.intern(key)
        if kid >= self.caps.K:
            self._grow(K=kid + 1)
        return kid

    def compile_term_selector(self, selector) -> Optional[List[Tuple[int, int, List[int]]]]:
        """LabelSelector -> [(key, op, vals)] over pod-label space, interning.
        None selector matches nothing (LabelSelectorAsSelector(nil) ->
        labels.Nothing(), apimachinery meta/v1/helpers.go)."""
        if selector is None:
            return None
        v = self.vocabs
        out: List[Tuple[int, int, List[int]]] = []
        for r in selector.to_selector().requirements:
            kid = v.pod_label_keys.intern(r.key)
            if kid >= self.caps.KP:
                self._grow(KP=kid + 1)
            vals = [v.label_values.intern(val) for val in r.values]
            out.append((kid, enc.op_id(r.op), vals))
        return out

    def _iter_pod_terms(self, pod: api.Pod):
        """(kind, weight, PodAffinityTerm) for every term the pod carries."""
        aff = pod.spec.affinity
        if aff is None:
            return
        if aff.pod_affinity is not None:
            for t in aff.pod_affinity.required:
                yield enc.TERM_REQ_AFF, 1.0, t
            for wt in aff.pod_affinity.preferred:
                yield enc.TERM_PREF_AFF, float(wt.weight), wt.pod_affinity_term
        if aff.pod_anti_affinity is not None:
            for t in aff.pod_anti_affinity.required:
                yield enc.TERM_REQ_ANTI, 1.0, t
            for wt in aff.pod_anti_affinity.preferred:
                yield enc.TERM_PREF_ANTI, float(wt.weight), wt.pod_affinity_term

    def _set_pod_terms(self, pod: api.Pod, slot: int, node_idx: int,
                       active: bool = True):
        self._clear_pod_terms(pod.uid)
        terms = list(self._iter_pod_terms(pod))
        if not terms:
            return
        v = self.vocabs
        rows: List[int] = []
        for kind, weight, term in terms:
            prog = self.compile_term_selector(term.label_selector)
            ns_ids = ([v.namespaces.intern(n) for n in term.namespaces]
                      if term.namespaces else [v.namespaces.intern(pod.namespace)])
            if len(ns_ids) > self.caps.TNS:
                self._grow(TNS=len(ns_ids))
            if prog is not None:
                if len(prog) > self.caps.TE:
                    self._grow(TE=len(prog))
                if any(len(vals) > self.caps.TV for _, _, vals in prog):
                    self._grow(TV=max(len(vals) for _, _, vals in prog))
            if self._free_terms:
                row = self._free_terms.pop()
            else:
                row = self._next_term
                self._next_term += 1
                if row >= self.caps.E:
                    self._grow(E=row + 1)
            c = self.caps
            self.t_kind[row] = kind
            self.t_owner[row] = slot
            self.t_node[row] = node_idx
            # empty topologyKey: only legal for preferred anti-affinity in the
            # reference (validation); a 0 id never matches any topology.
            self.t_tk[row] = self.label_key_col(term.topology_key) if term.topology_key else 0
            self.t_weight[row] = weight
            self.t_ns[row, :] = 0
            self.t_ns[row, : len(ns_ids)] = ns_ids
            self.t_key[row, :] = 0
            self.t_op[row, :] = enc.OP_PAD
            self.t_vals[row, :, :] = -1
            if prog is None:
                self.t_op[row, 0] = enc.OP_FALSE  # nil selector matches nothing
            else:
                for i, (kid, op, vals) in enumerate(prog):
                    self.t_key[row, i] = kid
                    self.t_op[row, i] = op
                    self.t_vals[row, i, : len(vals)] = vals
            self.t_valid[row] = active
            self._mark_rows("terms", row)
            rows.append(row)
        self.term_rows[pod.uid] = rows

    def _clear_pod_terms(self, uid: str):
        for row in self.term_rows.pop(uid, ()):
            self.t_valid[row] = False
            self.t_kind[row] = enc.TERM_PAD
            self.t_op[row, :] = enc.OP_PAD
            self._free_terms.append(row)
            self._mark_rows("terms", row)

    @property
    def has_affinity_terms(self) -> bool:
        return bool(self.term_rows)

    @property
    def num_label_values(self) -> int:
        """Bucketed label-value vocab size — the segment count for
        topology-domain anchoring in ops/affinity.py."""
        if self.vocabs.label_values.size > self.caps.LV:
            self.caps.LV = bucket_size(self.vocabs.label_values.size, self.caps.LV)
        return self.caps.LV

    # ---- device views -------------------------------------------------------

    def node_tensors(self) -> enc.NodeTensors:
        return enc.NodeTensors(
            alloc=self.alloc, requested=self.requested, nonzero=self.nonzero,
            pod_count=self.pod_count, allowed_pods=self.allowed_pods,
            labels=self.labels, label_nums=self.label_nums,
            taint_key=self.taint_key, taint_val=self.taint_val,
            taint_effect=self.taint_effect, cond=self.cond, ports=self.ports,
            zone_id=self.zone_id, rack_id=self.rack_id,
            superpod_id=self.superpod_id, accel_gen=self.accel_gen,
            img_id=self.img_id, img_size=self.img_size,
            avoid=self.avoid, valid=self.valid,
        )

    def pod_matrix(self) -> enc.PodMatrix:
        return enc.PodMatrix(
            labels=self.ep_labels, ns=self.ep_ns, node=self.ep_node,
            valid=self.ep_valid, alive=self.ep_alive, req=self.ep_req,
            prio=self.ep_prio,
        )

    def host_tensors(self) -> Tuple[enc.NodeTensors, enc.PodMatrix, enc.TermTable]:
        """Host-side views for the vectorized numpy twin (ops/hostwave.py):
        the SAME numpy planes the device upload reads, zero-copy — no
        upload, no clone-per-node. Callers must treat them as read-only;
        the twin copies its usage carries."""
        return self.node_tensors(), self.pod_matrix(), self.term_table()

    def term_table(self) -> enc.TermTable:
        return enc.TermTable(
            kind=self.t_kind, owner=self.t_owner, node=self.t_node,
            tk=self.t_tk, weight=self.t_weight, ns=self.t_ns,
            key=self.t_key, op=self.t_op, vals=self.t_vals, valid=self.t_valid,
        )

    def _group_host(self, key: str) -> tuple:
        """The host arrays of one device group, in cache-tuple order
        (every array's axis 0 is the group's row domain: N, M, or E)."""
        if key == "res":
            return (self.requested, self.nonzero, self.pod_count, self.ports)
        if key == "topo":
            return (self.alloc, self.allowed_pods, self.labels,
                    self.label_nums, self.taint_key, self.taint_val,
                    self.taint_effect, self.cond, self.zone_id, self.rack_id,
                    self.superpod_id, self.accel_gen, self.img_id,
                    self.img_size, self.avoid, self.valid)
        if key == "pods":
            return (self.ep_labels, self.ep_ns, self.ep_node, self.ep_valid,
                    self.ep_alive, self.ep_req, self.ep_prio)
        return (self.t_kind, self.t_owner, self.t_node, self.t_tk,
                self.t_weight, self.t_ns, self.t_key, self.t_op,
                self.t_vals, self.t_valid)

    @staticmethod
    def _delta_rows(rows: set, total: int):
        """Dirty row indices -> a power-of-two-bucketed i32 index vector
        (pads duplicate the first index), or None when the bucketed
        fraction makes a full upload cheaper. Index-based scatter —
        not contiguous ranges — because real churn is scattered: a
        trickle round's binds land on spread-scored nodes all over the
        cluster."""
        k = len(rows)
        kb = min(max(DELTA_MIN_ROWS, 1 << (k - 1).bit_length()), total)
        if kb > DELTA_MAX_FRACTION * total:
            return None
        srt = sorted(rows)
        idx = np.full((kb,), srt[0], np.int32)
        idx[:k] = srt
        return idx

    def _sync_group(self, jax, key: str, target, full_dirty: bool) -> None:
        """Bring one cached device group up to date: nothing when clean,
        a gathered-row delta scatter when the churn is sparse, the whole
        group otherwise. `target` is a device or NamedSharding (None =
        default device)."""
        cache = self._device_cache
        host = self._group_host(key)
        rows = self._dirty_rows[key]
        if key in cache and not full_dirty:
            if not rows:
                return
            idx = self._delta_rows(rows, host[0].shape[0])
            if idx is not None:
                updates = tuple(np.ascontiguousarray(a[idx]) for a in host)
                devs = _row_update()(tuple(cache[key]), updates, idx)
                self.upload_bytes_total += (
                    sum(int(u.nbytes) for u in updates) + int(idx.nbytes))
                # re-commit to the group's target: the scatter output
                # follows the operand sharding in practice, but pinning
                # it keeps a compiler-chosen layout out of the kernels'
                # jit keys (a no-op transfer when already there)
                cache[key] = (jax.device_put(devs, target)
                              if target is not None else devs)
                rows.clear()
                return
        self._account_upload(key, host)
        cache[key] = jax.device_put(host, target)
        rows.clear()

    def to_device(self, device=None, mesh=None
                  ) -> Tuple[enc.NodeTensors, enc.PodMatrix, enc.TermTable]:
        """Upload dirty groups (whole, or just the touched row ranges);
        reuse cached device arrays otherwise.

        mesh: optional jax.sharding.Mesh — mesh-aware mode commits the
        node-tensor groups to the "nodes"-axis NamedSharding and the
        pod/term groups replicated (parallel/mesh.py group_shardings).
        Callers gate on nodes_divide(mesh, caps.N); switching between
        mesh and single-device modes invalidates the cache."""
        import jax

        cache = self._device_cache
        shapes_key = (self.caps.N, self.caps.K, self.caps.KP, self.caps.R,
                      self.caps.T, self.caps.PP, self.caps.NI, self.caps.M,
                      self.caps.E, self.caps.TE, self.caps.TV, self.caps.TNS)
        if cache.get("shapes") != shapes_key or cache.get("mesh") is not mesh:
            cache.clear()
            self._group_bytes.clear()
            cache["shapes"] = shapes_key
            cache["mesh"] = mesh
            self.dirty_resources = self.dirty_topology = self.dirty_pods = True
            for rows in self._dirty_rows.values():
                rows.clear()
        if mesh is not None:
            from ..parallel.mesh import group_shardings

            node_sh, repl_sh = group_shardings(mesh)
            targets = {"res": node_sh, "topo": node_sh,
                       "pods": repl_sh, "terms": repl_sh}
            self._mesh_devices = [str(d) for d in mesh.devices.flat]
            self._node_shards = int(mesh.shape["nodes"])
            self._group_sharded = {"res": True, "topo": True}
        else:
            targets = dict.fromkeys(("res", "topo", "pods", "terms"), device)
            self._mesh_devices = []
            self._node_shards = 1
            self._group_sharded = {}
        self._sync_group(jax, "res", targets["res"], self.dirty_resources)
        self._sync_group(jax, "topo", targets["topo"], self.dirty_topology)
        self._sync_group(jax, "pods", targets["pods"], self.dirty_pods)
        self._sync_group(jax, "terms", targets["terms"], self.dirty_pods)
        self.dirty_resources = self.dirty_topology = self.dirty_pods = False
        requested, nonzero, pod_count, ports = cache["res"]
        (alloc, allowed_pods, labels, label_nums, taint_key, taint_val,
         taint_effect, cond, zone_id, rack_id, superpod_id, accel_gen,
         img_id, img_size, avoid, valid) = cache["topo"]
        (ep_labels, ep_ns, ep_node, ep_valid, ep_alive, ep_req,
         ep_prio) = cache["pods"]
        (t_kind, t_owner, t_node, t_tk, t_weight, t_ns, t_key, t_op, t_vals,
         t_valid) = cache["terms"]
        nt = enc.NodeTensors(
            alloc=alloc, requested=requested, nonzero=nonzero,
            pod_count=pod_count, allowed_pods=allowed_pods, labels=labels,
            label_nums=label_nums, taint_key=taint_key, taint_val=taint_val,
            taint_effect=taint_effect, cond=cond, ports=ports, zone_id=zone_id,
            rack_id=rack_id, superpod_id=superpod_id, accel_gen=accel_gen,
            img_id=img_id, img_size=img_size, avoid=avoid, valid=valid,
        )
        pm = enc.PodMatrix(labels=ep_labels, ns=ep_ns, node=ep_node,
                           valid=ep_valid, alive=ep_alive, req=ep_req,
                           prio=ep_prio)
        tt = enc.TermTable(kind=t_kind, owner=t_owner, node=t_node, tk=t_tk,
                           weight=t_weight, ns=t_ns, key=t_key, op=t_op,
                           vals=t_vals, valid=t_valid)
        return nt, pm, tt
