"""String interning vocabularies.

The tensor snapshot (state/snapshot.py) encodes label keys/values, taint
triples, ports, namespaces and spreading groups as dense integer ids.
Interners are append-only so ids are stable for the lifetime of a
scheduler process; tensor shapes derived from vocab sizes are bucketed
to powers of two to keep XLA jit cache hits high (SURVEY.md §7 hard
part (e): recompilation pressure).

Append-only is also a leak under node churn: every hostname/label/image
a departed node ever contributed stays interned forever. Compaction
(state/scrubber.py compact) rebuilds the vocabularies from live objects
via `Interner.adopt` — in place, preserving object identity, because
interners are shared by reference across the snapshot, the featurizer,
and the nodelifecycle controller. `VocabSet.generation` counts those
rebuilds and is folded into `version()` so featurizer caches can never
confuse pre- and post-compaction id spaces even when sizes coincide.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional


def bucket_size(n: int, minimum: int = 8) -> int:
    """Round up to a power of two (>= minimum) so jit shapes stay stable."""
    b = minimum
    while b < n:
        b *= 2
    return b


class Interner:
    """Append-only string -> id map. Id 0 is reserved for "absent"/pad."""

    __slots__ = ("_ids", "_strings")

    def __init__(self):
        self._ids: Dict[str, int] = {}
        self._strings: List[str] = ["\x00<pad>"]

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._strings)
            self._ids[s] = i
            self._strings.append(s)
        return i

    def lookup(self, s: str) -> int:
        """Id of s, or -1 if never interned. -1 never matches any stored id,
        which encodes "this selector value matches nothing here yet"."""
        return self._ids.get(s, -1)

    def string(self, i: int) -> str:
        return self._strings[i]

    def __len__(self) -> int:
        return len(self._strings)

    @property
    def size(self) -> int:
        return len(self._strings)

    def strings(self) -> List[str]:
        """Live strings in id order, pad excluded — the mark set a
        compaction rebuilds from."""
        return self._strings[1:]

    def adopt(self, other: "Interner") -> None:
        """Replace contents with `other`'s, IN PLACE. The object identity
        must survive: interners are shared by reference (snapshot.extended
        aliases vocabs.resources, nodelifecycle shares zones), so a
        compaction can never swap in a new Interner object."""
        self._ids = dict(other._ids)
        self._strings = list(other._strings)


class VocabSet:
    """All vocabularies used by the tensor encoding."""

    # attribute names of every interner, in declaration order — the
    # closed label set of the snapshot_vocab_size{vocab} gauge and the
    # iteration order of sizes()/adopt_all()
    NAMES = ("label_keys", "label_values", "taint_keys", "taint_values",
             "resources", "ports", "namespaces", "zones", "images",
             "pod_label_keys")

    def __init__(self):
        self.label_keys = Interner()
        self.label_values = Interner()  # global value vocab (shared across keys)
        self.taint_keys = Interner()
        self.taint_values = Interner()
        self.resources = Interner()  # extended resource names (snapshot columns)
        self.ports = Interner()  # "proto/port" strings
        self.namespaces = Interner()
        self.zones = Interner()  # GetZoneKey strings
        self.images = Interner()  # container image names
        self.pod_label_keys = Interner()  # pod-label key space (ep matrix)
        # bumped by every compaction adopt_all(); part of version() so a
        # post-compaction vocab whose sizes happen to match the
        # pre-compaction sizes still invalidates featurizer caches
        self.generation = 0

    def version(self) -> tuple:
        """Sizes of the vocabs selector compilation reads; featurizer caches
        are invalidated when this changes (a -1 'unknown value' lookup may
        have become valid). Includes the compaction generation: a rebuild
        REASSIGNS ids, so sizes alone cannot prove cached rows valid."""
        return (
            self.generation,
            self.label_keys.size,
            self.label_values.size,
            self.taint_keys.size,
            self.taint_values.size,
            self.pod_label_keys.size,
        )

    def sizes(self) -> Dict[str, int]:
        """Per-vocab sizes keyed by attribute name (metrics export and
        the soak harness's plateau gates)."""
        return {name: getattr(self, name).size for name in self.NAMES}

    def adopt_all(self, other: "VocabSet") -> None:
        """Adopt every interner's contents from `other` in place (object
        identities preserved — see Interner.adopt) and bump the
        generation. The compaction commit step."""
        for name in self.NAMES:
            getattr(self, name).adopt(getattr(other, name))
        self.generation += 1

    def intern_label(self, key: str, value: str) -> tuple:
        return self.label_keys.intern(key), self.label_values.intern(value)

    def port_id(self, protocol: str, port: int) -> int:
        return self.ports.intern(f"{protocol}/{port}")

    def lookup_port(self, protocol: str, port: int) -> int:
        return self.ports.lookup(f"{protocol}/{port}")
