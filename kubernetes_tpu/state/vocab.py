"""String interning vocabularies.

The tensor snapshot (state/snapshot.py) encodes label keys/values, taint
triples, ports, namespaces and spreading groups as dense integer ids.
Interners are append-only so ids are stable for the lifetime of a
scheduler process; tensor shapes derived from vocab sizes are bucketed
to powers of two to keep XLA jit cache hits high (SURVEY.md §7 hard
part (e): recompilation pressure).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional


def bucket_size(n: int, minimum: int = 8) -> int:
    """Round up to a power of two (>= minimum) so jit shapes stay stable."""
    b = minimum
    while b < n:
        b *= 2
    return b


class Interner:
    """Append-only string -> id map. Id 0 is reserved for "absent"/pad."""

    __slots__ = ("_ids", "_strings")

    def __init__(self):
        self._ids: Dict[str, int] = {}
        self._strings: List[str] = ["\x00<pad>"]

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._strings)
            self._ids[s] = i
            self._strings.append(s)
        return i

    def lookup(self, s: str) -> int:
        """Id of s, or -1 if never interned. -1 never matches any stored id,
        which encodes "this selector value matches nothing here yet"."""
        return self._ids.get(s, -1)

    def string(self, i: int) -> str:
        return self._strings[i]

    def __len__(self) -> int:
        return len(self._strings)

    @property
    def size(self) -> int:
        return len(self._strings)


class VocabSet:
    """All vocabularies used by the tensor encoding."""

    def __init__(self):
        self.label_keys = Interner()
        self.label_values = Interner()  # global value vocab (shared across keys)
        self.taint_keys = Interner()
        self.taint_values = Interner()
        self.resources = Interner()  # extended resource names (snapshot columns)
        self.ports = Interner()  # "proto/port" strings
        self.namespaces = Interner()
        self.zones = Interner()  # GetZoneKey strings
        self.images = Interner()  # container image names
        self.pod_label_keys = Interner()  # pod-label key space (ep matrix)

    def version(self) -> tuple:
        """Sizes of the vocabs selector compilation reads; featurizer caches
        are invalidated when this changes (a -1 'unknown value' lookup may
        have become valid)."""
        return (
            self.label_keys.size,
            self.label_values.size,
            self.taint_keys.size,
            self.taint_values.size,
            self.pod_label_keys.size,
        )

    def intern_label(self, key: str, value: str) -> tuple:
        return self.label_keys.intern(key), self.label_values.intern(value)

    def port_id(self, protocol: str, port: int) -> int:
        return self.ports.intern(f"{protocol}/{port}")

    def lookup_port(self, protocol: str, port: int) -> int:
        return self.ports.lookup(f"{protocol}/{port}")
