from .trace import Trace  # noqa: F401
from .metrics import (Metrics, Histogram, Counter, Gauge,  # noqa: F401
                      LabeledCounter, LabeledGauge, bounded_label)
from .backoff import PodBackoff  # noqa: F401
from .feature_gates import FeatureGates, DEFAULT_FEATURES  # noqa: F401
from . import faultpoints  # noqa: F401
from . import tracing  # noqa: F401
