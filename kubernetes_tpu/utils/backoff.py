"""Per-pod exponential backoff (reference: pkg/scheduler/util/
backoff_utils.go:97-112 — 1s initial, doubling, 60s max, entries GC'd
after 2*maxDuration of idleness)."""

from __future__ import annotations

import threading
import time
from typing import Dict, Tuple


class _Entry:
    __slots__ = ("duration", "last_update")

    def __init__(self, duration: float, now: float):
        self.duration = duration
        self.last_update = now


class PodBackoff:
    def __init__(self, initial: float = 1.0, maximum: float = 60.0,
                 clock=time.monotonic):
        self.initial = initial
        self.maximum = maximum
        self.clock = clock
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.Lock()

    def get(self, pod_id: str) -> float:
        """Peek the current duration WITHOUT inflating it. Observing a
        pod's backoff (metrics, debug endpoints, a would-this-wait
        check) must not double it — the old single `get_backoff` entry
        point bumped on every read, so two observers could push a pod
        from 1s to 4s without a single failure."""
        with self._lock:
            e = self._entries.get(pod_id)
            return e.duration if e is not None else self.initial

    def bump(self, pod_id: str) -> float:
        """Record a failure: return the current duration and double it
        for next time (reference getBackoffTime + BackoffPod)."""
        now = self.clock()
        with self._lock:
            e = self._entries.get(pod_id)
            if e is None:
                e = _Entry(self.initial, now)
                self._entries[pod_id] = e
            d = e.duration
            e.duration = min(e.duration * 2, self.maximum)
            e.last_update = now
            return d

    def try_wait(self, pod_id: str) -> float:
        return self.bump(pod_id)

    def clear(self, pod_id: str):
        with self._lock:
            self._entries.pop(pod_id, None)

    def gc(self):
        """Drop entries idle for > 2*maximum (reference Gc())."""
        now = self.clock()
        with self._lock:
            for k in list(self._entries):
                if now - self._entries[k].last_update > 2 * self.maximum:
                    del self._entries[k]
