"""Exponential backoff, shared by every retry ladder in the tree.

PodBackoff is the per-pod map (reference: pkg/scheduler/util/
backoff_utils.go:97-112 — 1s initial, doubling, 60s max, entries GC'd
after 2*maxDuration of idleness). JitteredLadder is the single-stream
variant used by the reflector's relist loop, the bind reconciler's
retry loop, and the store-path breaker's probe cooldown: each bump
yields `delay * (0.5 + jitter())` (full-jitter over [0.5x, 1.5x), so
concurrent ladders never synchronize) and doubles the base toward the
cap. Before this module owned it, the same three lines lived
copy-pasted in client/reflector.py and sched/reconciler.py and a
third unjittered copy in the autoscaler's duration doubling — one
shape, one place.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Tuple


def jittered(delay: float,
             jitter: Callable[[], float] = random.random) -> float:
    """Full-jitter: a uniform draw over [0.5x, 1.5x) of `delay`."""
    return delay * (0.5 + jitter())


def exp_step(delay: float, maximum: float) -> float:
    """One rung up the doubling ladder, capped at `maximum`."""
    return min(delay * 2.0, maximum)


class JitteredLadder:
    """A single jittered-exponential retry ladder.

    bump() returns the jittered wait for THIS failure and doubles the
    base (capped) for the next one; reset() drops back to the initial
    rung after a clean cycle. `delay` is the un-jittered base — tests
    assert ladder position against it without fighting the jitter.
    """

    __slots__ = ("initial", "maximum", "jitter", "delay")

    def __init__(self, initial: float, maximum: float,
                 jitter: Callable[[], float] = random.random):
        self.initial = initial
        self.maximum = maximum
        self.jitter = jitter
        self.delay = initial

    def bump(self) -> float:
        d = jittered(self.delay, self.jitter)
        self.delay = exp_step(self.delay, self.maximum)
        return d

    def reset(self) -> None:
        self.delay = self.initial


class _Entry:
    __slots__ = ("duration", "last_update")

    def __init__(self, duration: float, now: float):
        self.duration = duration
        self.last_update = now


class PodBackoff:
    def __init__(self, initial: float = 1.0, maximum: float = 60.0,
                 clock=time.monotonic):
        self.initial = initial
        self.maximum = maximum
        self.clock = clock
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.Lock()

    def get(self, pod_id: str) -> float:
        """Peek the current duration WITHOUT inflating it. Observing a
        pod's backoff (metrics, debug endpoints, a would-this-wait
        check) must not double it — the old single `get_backoff` entry
        point bumped on every read, so two observers could push a pod
        from 1s to 4s without a single failure."""
        with self._lock:
            e = self._entries.get(pod_id)
            return e.duration if e is not None else self.initial

    def bump(self, pod_id: str) -> float:
        """Record a failure: return the current duration and double it
        for next time (reference getBackoffTime + BackoffPod)."""
        now = self.clock()
        with self._lock:
            e = self._entries.get(pod_id)
            if e is None:
                e = _Entry(self.initial, now)
                self._entries[pod_id] = e
            d = e.duration
            e.duration = exp_step(e.duration, self.maximum)
            e.last_update = now
            return d

    def try_wait(self, pod_id: str) -> float:
        return self.bump(pod_id)

    def clear(self, pod_id: str):
        with self._lock:
            self._entries.pop(pod_id, None)

    def gc(self):
        """Drop entries idle for > 2*maximum (reference Gc())."""
        now = self.clock()
        with self._lock:
            for k in list(self._entries):
                if now - self._entries[k].last_update > 2 * self.maximum:
                    del self._entries[k]
