"""Named fault points for deterministic chaos testing.

The reference exercises failure paths structurally (test/e2e/chaosmonkey
kills whole components); this framework additionally has *internal*
surfaces that can fail independently of any process — the device kernel
call (XLA error, kernel OOM), the bind POST, watch delivery, and the
incremental snapshot writes that keep the HBM mirror honest. Each of
those is wired with a named fault point (the etcd `gofail`
pattern): chaos tests activate a point by name and the production code
path fails exactly there, deterministically.

Wired points (grep for `faultpoints.fire`):

  kernel.wave      ops/kernel.py schedule_wave entry (per-wave program)
  kernel.round     ops/kernel.py schedule_round entry (device-resident round)
  kernel.gang      ops/gang.py schedule_gang entry (joint-assignment)
  kernel.hang      ops/kernel.py record_dispatch, INSIDE the guarded
                   dispatch (on the watchdog's worker thread when one
                   is armed) — `latency` models a wedged XLA dispatch
                   that silently never returns: with cfg.wave_deadline_s
                   set the watchdog abandons it, the breaker trips via
                   record_hang, and the round salvages through the
                   hostwave twin
  device.lost      ops/kernel.py record_dispatch, inside the guarded
                   dispatch (next to kernel.hang), AND sched/scheduler.py
                   _probe_device (the quarantined-device recovery probe).
                   Payload: the active mesh device-name tuple at the
                   dispatch seam, the probed device's name (str) at the
                   probe. Arm per-device with `corrupt` mode and
                   sched.breaker.lost_device_fault(str(dev)) — raises
                   DeviceLost(dev) only while the victim is in the
                   payload, so a reformed mesh stops failing and only
                   the victim's probes fail; a plain `raise` models an
                   unattributed device loss (the bisection path)
  device.oom       ops/kernel.py record_dispatch, inside the guarded
                   dispatch (next to device.lost; payload: the active
                   mesh device-name tuple). The capacity-fault seam: a
                   `raise` (or `corrupt` with sched.breaker.oom_fault()
                   raising ResourceExhausted) models an HBM
                   RESOURCE_EXHAUSTED — the scheduler must classify it
                   as a capacity fault (compact, halve the wave, host
                   twin), NEVER convict a device or reform the mesh
  snapshot.compact state/scrubber.py compact entry, BEFORE the
                   fault-suppressed rebuild (payload: (snapshot,
                   trigger)) — a `raise` fails the housekeeping
                   compaction; `latency` models a slow sweep holding
                   the scheduler lock
  mesh.reform      sched/scheduler.py _maybe_reform, BEFORE the new mesh
                   is built — a `raise` fails the reform so the failure
                   falls through to the whole-path breaker (host-twin
                   rung); hits() counts reforms for chaos asserts
  queue.shed       sched/queue.py _should_shed_locked — `drop` forces
                   the shed decision for every sheddable
                   (sub-threshold-priority, non-gang) pod regardless of
                   the watermark: the storm chaos rig for shedding
                   tests that don't want to build a real 5x backlog
  bind.post        sched/scheduler.py _bind_and_finish, before each POST
                   attempt (the bind reconciler retries through it)
  watch.deliver    runtime/store.py _notify, before fan-out
  snapshot.write   state/snapshot.py refresh_node_resources, AFTER the
                   row write (payload: (snapshot, node_idx) — the
                   `corrupt` mode's target)
  rest.request     client/rest.py request_bytes + watch entry — every
                   control-plane round trip (payload: (method, path);
                   `drop` models the request never reaching the wire)
  reflector.relist client/reflector.py run, before each list+watch
                   cycle (exercises the jittered relist backoff)
  lease.renew      client/leaderelection.py _try_acquire_or_renew entry
                   (a `raise` fails renewals -> leadership loss after
                   renew_deadline; `latency` eats the renew budget)
  autoscaler.simulate  ops/simulate.py simulate_placements /
                   simulate_refit entry — the autoscaler's on-device
                   what-if passes (a `raise` models a faulting device
                   path: the pass is skipped, no resize happens)
  cloud.resize     cloud/provider.py FakeCloud increase_size /
                   delete_nodes, BEFORE any mutation (payload: (op,
                   group, arg)) — a `raise` models a rejected cloud API
                   call; group target/instances stay untouched and the
                   autoscaler backs the group off
  heartbeat.deliver  kubelet/kubelet.py heartbeat entry (payload: node
                   name) — `drop` models the node status update never
                   reaching the apiserver (a partitioned node); the
                   nodelifecycle controller then sees a stale heartbeat
  nodelifecycle.evict  controllers/nodelifecycle.py, AFTER the zone
                   rate limiter admitted an eviction but BEFORE the pod
                   delete (payload: (pod key, node)) — `drop` models a
                   lost eviction call: the entry stays queued and
                   retries next pass; `raise` fails the monitor pass
  nodelifecycle.tally  ops/zonehealth.py device-path entry — a `raise`
                   forces the per-zone health reduction onto the exact
                   host fallback (and feeds the circuit breaker when
                   one is wired)
  featurize.poison state/featurize.py _featurize_pod_guarded, AFTER the
                   per-pod finite validation (payload: (pod, row-dict)).
                   Arm `corrupt` with state.featurize.poison_pod_fault
                   (uid, kind): kind="crash" raises PodFeaturizeError
                   for exactly that pod (direct poison attribution);
                   kind="nan" silently NaNs the victim's req row —
                   post-validation corruption only the kernel's
                   numeric-integrity sentinel catches
  wave.poison      sched/scheduler.py, before EVERY batched pass over a
                   pod list — the device round/wave/gang dispatches,
                   the degraded host-twin waves, AND the input-fault
                   attribution replay (payload: (pods, PodBatch)). With
                   poison_pod_fault(uid, "crash") the fault follows the
                   DATA across backends: device fails, the twin replay
                   fails identically, the failure classifies as an
                   input fault (breaker untouched, mesh untouched) and
                   wave bisection isolates the victim in log2(wave)
                   rounds; "nan" corrupts the victim's batch row
                   pre-upload (sentinel path, one-round conviction)
  queue.quarantine sched/queue.py quarantine entry (payload: pod) —
                   `drop` refuses the quarantine (a lost conviction:
                   the scheduler falls back to a plain backoff park, so
                   chaos can probe that poison handling degrades to
                   pre-PR-15 behavior instead of wedging)
  autopilot.train  autopilot/trainer.py Trainer.fit entry (payload: the
                   LedgerDataset) — a `raise` fails a training job
                   cleanly before any candidate is emitted; `latency`
                   models a slow fit on a big ledger
  autopilot.promote  autopilot/controller.py _promote, BEFORE the
                   role=live write (payload: candidate name) — a
                   `raise` aborts the pipeline at the most dangerous
                   instant; the chaos assert is that nothing was
                   promoted, the gating flag is dropped, and the
                   outcome ledgered as `aborted`
  store.outage     the store-path outage seam, fired once per
                   control-plane round trip the scheduler depends on:
                   sched/scheduler.py _bind_attempt (before each bind
                   POST; payload ("bind", uid)) and _pod_truth (before
                   each truth GET; payload ("get", uid)),
                   client/reflector.py Reflector._list (payload
                   ("list", plural)) and RemoteStore._guard (payload:
                   the op string). A duration-armed `raise` severs the
                   whole store path: the store breaker
                   (sched/storehealth.py) trips to DISCONNECTED, binds
                   spool into the intent journal, and the post-heal
                   drain must leave placements bit-identical to an
                   outage-free run
  journal.append   state/journal.py _append_locked, BEFORE the write
                   (payload: the record dict) — `raise` models a full
                   disk / IO error at the worst moment (the intent
                   then spools in memory only), `drop` models a write
                   the OS acknowledged but never persisted (the
                   crash-restart replay must tolerate the hole)

Modes:

  raise    raise FaultInjected (or a caller-supplied exception factory)
  latency  time.sleep(arg seconds), then continue
  drop     fire() returns True — the call site skips the guarded action
           (models a lost watch event / lost incremental update)
  corrupt  invoke the fault's fn(payload) — or the default snapshot-row
           corruption (alloc[idx, CPU] += 4 cores, a silently wrong
           capacity the scrubber must catch) — then continue

Inactive cost: `fire()` is one module-global dict check (`if not
_active: return False`) — nothing on the tier-1 / bench hot paths pays
for the harness. Activation is programmatic (activate / injected
context manager) or via the environment:

  KTPU_FAULTPOINTS="kernel.wave=raise,bind.post=latency:0.05:3"
                    name=mode[:arg[:times]]  (comma-separated)

Environment specs are validated by parse(): an unknown point name, an
unknown mode, or a malformed arg/times field raises ValueError naming
the offending token at activation — a chaos run with a typoed spec
must fail loudly, not silently run fault-free. The point-name check is
against registered_points(), the docstring registry above, which a
drift-guard test keeps exactly equal to the fire() call sites in the
tree (both directions).
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional


class FaultInjected(RuntimeError):
    """The error raised by a `raise`-mode fault point."""

    def __init__(self, point: str):
        super().__init__(f"fault injected at {point!r}")
        self.point = point


class _Fault:
    __slots__ = ("name", "mode", "arg", "times", "fn", "exc", "hits")

    def __init__(self, name: str, mode: str, arg: float = 0.0,
                 times: Optional[int] = None,
                 fn: Optional[Callable] = None,
                 exc: Optional[Callable[[], BaseException]] = None):
        if mode not in ("raise", "latency", "drop", "corrupt"):
            raise ValueError(f"unknown fault mode {mode!r}")
        self.name = name
        self.mode = mode
        self.arg = arg
        self.times = times  # None = unlimited
        self.fn = fn
        self.exc = exc
        self.hits = 0


_active: Dict[str, _Fault] = {}
_hits: Dict[str, int] = {}  # survives deactivate, for post-hoc asserts
_lock = threading.Lock()
_suppress = threading.local()  # per-thread: observers opt out of chaos


def _default_corrupt(payload) -> None:
    """The canonical silent-divergence corruption: inflate a snapshot
    node row's allocatable CPU by 4 cores. Allocatable is a topology
    field — no bind-path refresh overwrites it, so the corruption
    persists until a node event or a scrub, exactly the hazard the
    snapshot scrubber exists to catch."""
    try:
        snap, idx = payload
        snap.alloc[idx, 0] += 4000.0  # RES_CPU column, milli-cpu
    except (TypeError, ValueError, AttributeError, IndexError):
        pass  # payload isn't a (snapshot, idx) pair: nothing to corrupt


def fire(name: str, payload=None) -> bool:
    """Hot-path hook. Returns True when a `drop`-mode fault is active
    (the caller must skip the guarded action); False otherwise. With no
    active faults this is a single dict check."""
    if not _active:
        return False
    if getattr(_suppress, "on", False):
        return False
    f = _active.get(name)
    if f is None:
        return False
    with _lock:
        if f.times is not None:
            if f.times <= 0:
                return False
            f.times -= 1
        f.hits += 1
        _hits[name] = _hits.get(name, 0) + 1
    if f.mode == "latency":
        time.sleep(f.arg)
        return False
    if f.mode == "drop":
        return True
    if f.mode == "corrupt":
        (f.fn or _default_corrupt)(payload)
        return False
    raise (f.exc() if f.exc is not None else FaultInjected(name))


def is_armed(name: str, mode: Optional[str] = None) -> bool:
    """Non-consuming probe: is the point armed (optionally in `mode`)
    with budget remaining? Unlike fire(), this neither counts a hit
    nor decrements `times` — for call sites that only need to know
    whether chaos is active (the queue's watermark-release suppression
    must not eat the per-pod shed budget of a times-bounded fault)."""
    if not _active:
        return False
    if getattr(_suppress, "on", False):
        return False
    f = _active.get(name)
    if f is None or (mode is not None and f.mode != mode):
        return False
    return f.times is None or f.times > 0


def activate(name: str, mode: str = "raise", arg: float = 0.0,
             times: Optional[int] = None, fn: Optional[Callable] = None,
             exc: Optional[Callable[[], BaseException]] = None) -> None:
    """Arm a fault point. `times` bounds how many fires apply (None =
    every call); `fn` overrides the corrupt action; `exc` overrides the
    raised exception factory."""
    with _lock:
        _active[name] = _Fault(name, mode, arg=arg, times=times, fn=fn,
                               exc=exc)


def deactivate(name: str) -> None:
    with _lock:
        _active.pop(name, None)


def reset() -> None:
    """Disarm everything and forget hit counts (test teardown)."""
    with _lock:
        _active.clear()
        _hits.clear()


def active() -> bool:
    return bool(_active)


def hits(name: str) -> int:
    """Times the point actually applied (cumulative until reset())."""
    with _lock:
        return _hits.get(name, 0)


@contextmanager
def injected(name: str, mode: str = "raise", **kw):
    """Scope a fault to a `with` block."""
    activate(name, mode, **kw)
    try:
        yield
    finally:
        deactivate(name)


@contextmanager
def suppressed():
    """Disarm every fault point for the current thread inside the block.
    For OBSERVERS of faulty state — the snapshot scrubber's golden-row
    build and repair writes go through the very code paths the
    `snapshot.write` point instruments; without suppression an unbounded
    corrupt fault would corrupt the golden rows identically (scrub
    reports clean while both sides diverge from host truth) and
    re-corrupt each row the instant it is repaired."""
    prev = getattr(_suppress, "on", False)
    _suppress.on = True
    try:
        yield
    finally:
        _suppress.on = prev


_MODES = ("raise", "latency", "drop", "corrupt")


def registered_points() -> frozenset:
    """The point names documented in this module's registry docstring
    (the 'Wired points' section) — the authority parse() validates
    against and the drift-guard test holds equal to the fire() call
    sites in the tree."""
    names = []
    in_registry = False
    for ln in (__doc__ or "").splitlines():
        if ln.startswith("Wired points"):
            in_registry = True
            continue
        if in_registry:
            if ln.startswith("Modes:"):
                break
            # entries are indented exactly two spaces; continuation
            # lines are indented further
            if ln.startswith("  ") and len(ln) > 2 and ln[2] != " ":
                names.append(ln.split()[0])
    return frozenset(names)


def parse(spec: str):
    """Parse "name=mode[:arg[:times]],..." into a list of
    (name, mode, arg, times) tuples. Raises ValueError naming the
    offending token for an unknown point, an unknown mode, a
    non-float arg, a negative/non-int times, or extra fields — a
    malformed chaos spec must fail loudly, not silently arm nothing."""
    out = []
    points = registered_points()
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(
                f"KTPU_FAULTPOINTS: malformed token {item!r} "
                f"(expected name=mode[:arg[:times]])")
        name, rest = item.split("=", 1)
        name = name.strip()
        if name not in points:
            raise ValueError(
                f"KTPU_FAULTPOINTS: unknown fault point {name!r} in "
                f"token {item!r} (see the utils/faultpoints.py registry)")
        parts = rest.split(":")
        if len(parts) > 3:
            raise ValueError(
                f"KTPU_FAULTPOINTS: too many fields in token {item!r} "
                f"(expected name=mode[:arg[:times]])")
        mode = parts[0].strip() or "raise"
        if mode not in _MODES:
            raise ValueError(
                f"KTPU_FAULTPOINTS: unknown mode {mode!r} in token "
                f"{item!r} (modes: {', '.join(_MODES)})")
        arg = 0.0
        if len(parts) > 1 and parts[1]:
            try:
                arg = float(parts[1])
            except ValueError:
                raise ValueError(
                    f"KTPU_FAULTPOINTS: non-numeric arg {parts[1]!r} in "
                    f"token {item!r}") from None
            if arg < 0:
                raise ValueError(
                    f"KTPU_FAULTPOINTS: negative arg {parts[1]!r} in "
                    f"token {item!r}")
        times = None
        if len(parts) > 2 and parts[2]:
            try:
                times = int(parts[2])
            except ValueError:
                raise ValueError(
                    f"KTPU_FAULTPOINTS: non-integer times {parts[2]!r} "
                    f"in token {item!r}") from None
            if times < 0:
                raise ValueError(
                    f"KTPU_FAULTPOINTS: negative times {parts[2]!r} in "
                    f"token {item!r}")
        out.append((name, mode, arg, times))
    return out


def activate_spec(spec: str) -> None:
    """Validate + arm a full KTPU_FAULTPOINTS spec string (the chaos
    campaign's reproducer strings re-enter here). All-or-nothing: a
    ValueError from parse() arms no point."""
    for name, mode, arg, times in parse(spec):
        activate(name, mode, arg=arg, times=times)


_env = os.environ.get("KTPU_FAULTPOINTS", "")
if _env:
    activate_spec(_env)
