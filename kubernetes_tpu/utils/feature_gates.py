"""Feature gates (reference: pkg/features/kube_features.go:298
defaultKubernetesFeatureGates + apiserver feature_gate.go). Parsed from
a "Name=true,Other=false" string like --feature-gates."""

from __future__ import annotations

from typing import Dict

# Scheduling-relevant defaults from the reference's v1.11-dev gate table.
DEFAULT_FEATURES: Dict[str, bool] = {
    "PodPriority": True,  # alpha->beta in 1.11; priority queue + preemption
    "TaintNodesByCondition": False,
    "VolumeScheduling": False,
    "BalanceAttachedNodeVolumes": False,
    "EnableEquivalenceClassCache": False,
    "ResourceLimitsPriorityFunction": False,
    "ScheduleDaemonSetPods": False,
    # framework-specific gates
    "TPUWaveScheduling": True,  # batch wavefronts on device
    "TPUShardedScoring": False,  # pjit over the nodes axis (parallel/)
}


class FeatureGates:
    def __init__(self, overrides: Dict[str, bool] = None):
        self._gates = dict(DEFAULT_FEATURES)
        if overrides:
            self._gates.update(overrides)

    @staticmethod
    def parse(spec: str) -> "FeatureGates":
        overrides = {}
        for part in filter(None, (s.strip() for s in spec.split(","))):
            name, _, val = part.partition("=")
            overrides[name] = val.strip().lower() in ("true", "1", "yes", "")
        return FeatureGates(overrides)

    def enabled(self, name: str) -> bool:
        return self._gates.get(name, False)

    def set(self, name: str, value: bool):
        self._gates[name] = value
