"""Prometheus-style metrics registry.

Analog of pkg/scheduler/metrics/metrics.go:30-87 — the same series names
are registered so dashboards built against the reference carry over:
e2e_scheduling_latency, scheduling_algorithm_latency,
scheduling_algorithm_predicate_evaluation,
scheduling_algorithm_priority_evaluation,
scheduling_algorithm_preemption_evaluation, binding_latency,
pod_preemption_victims, total_preemption_attempts.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional


def bounded_label(value: str, allowed: Iterable[str],
                  other: str = "Other") -> str:
    """Clamp a dynamic label value to a known set, bucketing everything
    else into `other` — the cardinality guard for labels fed from free
    text (predicate names from extenders, plugin messages). A label
    value minted per unique string grows /metrics without bound and can
    break exposition parsing; ktpu-lint's metrics-hygiene rule requires
    dynamic label values to route through this helper or come from a
    family's declared value set."""
    v = str(value)
    return v if v in allowed else other


class Counter:
    kind = "counter"

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, delta: float = 1.0):
        with self._lock:
            self.value += delta


class Gauge:
    """A value that can go down (prometheus Gauge) — queue depths,
    in-flight counts, target sizes. Counters only ever accumulate, so
    exporting a queue depth through one (the only pre-existing type)
    would be a lie the first time the queue drains."""

    kind = "gauge"

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float):
        with self._lock:
            self.value = float(v)

    def inc(self, delta: float = 1.0):
        with self._lock:
            self.value += delta

    def dec(self, delta: float = 1.0):
        with self._lock:
            self.value -= delta


class _LabelDecl:
    """Per-family label-cardinality declaration, checked at labels()
    time. `values` maps a label name to its closed value set — an
    undeclared value raises, so a free-text leak fails the first test
    that exercises it instead of growing /metrics forever. `open_labels`
    names labels that are *intentionally* unbounded (zones, resources,
    devices) and therefore pruned via remove()/zeroing when their
    subject disappears. ktpu-lint's metrics-hygiene rule reads the same
    declarations statically."""

    def __init__(self, labelnames, values, open_labels):
        self.values: Dict[str, frozenset] = {
            k: frozenset(v) for k, v in (values or {}).items()}
        self.open_labels = frozenset(open_labels or ())
        for ln in list(self.values) + list(self.open_labels):
            if ln not in labelnames:
                raise ValueError(f"declared label {ln!r} not in {labelnames}")

    def check(self, family: str, labelnames, key) -> None:
        for ln, v in zip(labelnames, key):
            allowed = self.values.get(ln)
            if allowed is not None and v not in allowed:
                raise ValueError(
                    f"{family}: label {ln}={v!r} outside the declared "
                    f"value set {sorted(allowed)} — extend the family's "
                    f"values= declaration or bucket through "
                    f"bounded_label()")


class LabeledCounter:
    """Counter family over a fixed label set; children render in
    Prometheus exposition form (`name{stage="bind"} 3`). The reference
    registers scheduling error series with a stage label
    (metrics.go `scheduling_errors`-style vectors); this is the minimal
    analog the registry + /metrics endpoint can serve.

    `values=` declares a closed per-label value set (enforced here,
    checked statically by ktpu-lint); `open_labels=` marks labels whose
    value space is intentionally open (see _LabelDecl)."""

    def __init__(self, name: str, labelnames=("stage",), help_: str = "",
                 values: Optional[Dict[str, Iterable[str]]] = None,
                 open_labels: Iterable[str] = ()):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self.decl = _LabelDecl(self.labelnames, values, open_labels)
        self._children: Dict[tuple, Counter] = {}
        self._lock = threading.Lock()

    def labels(self, **kw) -> Counter:
        # a label omitted by the caller defaults to "" and is dropped
        # from the rendered series (Prometheus treats an empty label
        # value as absent) — so a family can grow a dimension (e.g.
        # scheduling_errors_total's `device`) without touching every
        # existing call site or renaming their series
        key = tuple(str(kw.get(ln, "")) for ln in self.labelnames)
        self.decl.check(self.name, self.labelnames, key)
        with self._lock:
            c = self._children.get(key)
            if c is None:
                rendered = ",".join(
                    f'{ln}="{v}"' for ln, v in zip(self.labelnames, key)
                    if v != "")
                c = Counter(f"{self.name}{{{rendered}}}")
                self._children[key] = c
            return c

    def value(self, **kw) -> float:
        key = tuple(str(kw.get(ln, "")) for ln in self.labelnames)
        with self._lock:
            c = self._children.get(key)
            return c.value if c is not None else 0.0

    def total(self) -> float:
        with self._lock:
            return sum(c.value for c in self._children.values())

    def children(self) -> List[Counter]:
        with self._lock:
            return list(self._children.values())


class LabeledGauge:
    """Gauge family over a fixed label set (mirrors LabeledCounter —
    children render as `name{queue="active"} 3`, same values=/open_labels=
    cardinality declarations)."""

    def __init__(self, name: str, labelnames=("queue",), help_: str = "",
                 values: Optional[Dict[str, Iterable[str]]] = None,
                 open_labels: Iterable[str] = ()):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self.decl = _LabelDecl(self.labelnames, values, open_labels)
        self._children: Dict[tuple, Gauge] = {}
        self._lock = threading.Lock()

    def labels(self, **kw) -> Gauge:
        # omitted labels default to "" and are dropped from the rendered
        # series — same dimension-growth contract as LabeledCounter
        key = tuple(str(kw.get(ln, "")) for ln in self.labelnames)
        self.decl.check(self.name, self.labelnames, key)
        with self._lock:
            g = self._children.get(key)
            if g is None:
                rendered = ",".join(
                    f'{ln}="{v}"' for ln, v in zip(self.labelnames, key)
                    if v != "")
                g = Gauge(f"{self.name}{{{rendered}}}")
                self._children[key] = g
            return g

    def value(self, **kw) -> float:
        key = tuple(str(kw.get(ln, "")) for ln in self.labelnames)
        with self._lock:
            g = self._children.get(key)
            return g.value if g is not None else 0.0

    def remove(self, **kw) -> None:
        """Drop a child series so /metrics stops exporting it — a gauge
        whose subject disappeared (a deleted zone, a drained resource)
        must vanish, not freeze at its last value."""
        key = tuple(str(kw[ln]) for ln in self.labelnames)
        with self._lock:
            self._children.pop(key, None)

    def children(self) -> List[Gauge]:
        with self._lock:
            return list(self._children.values())


class Histogram:
    """Fixed-bucket histogram (reference uses exponential buckets starting
    at 1ms: prometheus.ExponentialBuckets(1000, 2, 15) in microseconds).

    Alongside the export buckets, a bounded reservoir of raw observations
    backs `quantile` so it reports a real number even past the top bucket
    — the bucket-only estimate saturated to the 16.4s ceiling (or inf)
    exactly at the drain-heavy scales the benchmark cares about."""

    RESERVOIR = 1 << 16

    def __init__(self, name: str, help_: str = "", buckets: Optional[List[float]] = None):
        self.name = name
        self.help = help_
        self.buckets = buckets or [0.001 * (2**i) for i in range(20)]
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.total = 0
        self.max = 0.0
        self._samples: List[float] = []
        # sorted-reservoir cache: bench reporting calls quantile() per
        # percentile, and re-sorting up to 64k samples each time was
        # O(quantiles * n log n); observe() invalidates
        self._sorted: Optional[List[float]] = None
        # deterministic LCG for reservoir sampling — keeps tests seedless
        self._rng = 0x2545F4914F6CDD1D
        self._lock = threading.Lock()

    def observe(self, v: float):
        with self._lock:
            self._sorted = None
            self.sum += v
            self.total += 1
            if v > self.max:
                self.max = v
            if len(self._samples) < self.RESERVOIR:
                self._samples.append(v)
            else:
                # Vitter's algorithm R: replace a uniform index with
                # probability RESERVOIR/total
                self._rng = (self._rng * 6364136223846793005 + 1442695040888963407) & (2**64 - 1)
                j = self._rng % self.total
                if j < self.RESERVOIR:
                    self._samples[j] = v
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def quantile(self, q: float) -> float:
        """Quantile from the raw-sample reservoir (exact until the
        reservoir cap, sampled beyond); always finite."""
        with self._lock:
            if self.total == 0:
                return 0.0
            if self._sorted is None:
                self._sorted = sorted(self._samples)
            s = self._sorted
            idx = min(int(math.ceil(q * len(s))) - 1, len(s) - 1)
            return s[max(idx, 0)]


class Metrics:
    """Registry with the reference scheduler's series pre-registered."""

    def __init__(self):
        self.e2e_scheduling_latency = Histogram("e2e_scheduling_latency")
        # per-POD latency from first enqueue to assume+bind-dispatch (the
        # BASELINE target tracks p99 schedule latency alongside
        # throughput; e2e_scheduling_latency spans whole waves/rounds)
        self.pod_scheduling_latency = Histogram("pod_scheduling_latency")
        self.scheduling_algorithm_latency = Histogram("scheduling_algorithm_latency")
        self.predicate_evaluation = Histogram("scheduling_algorithm_predicate_evaluation")
        self.priority_evaluation = Histogram("scheduling_algorithm_priority_evaluation")
        self.preemption_evaluation = Histogram("scheduling_algorithm_preemption_evaluation")
        self.binding_latency = Histogram("binding_latency")
        self.pod_preemption_victims = Counter("pod_preemption_victims")
        self.total_preemption_attempts = Counter("total_preemption_attempts")
        self.schedule_attempts = Counter("schedule_attempts_total")
        # gang (coscheduling) series: attempts counts whole-gang placement
        # tries; wait_seconds spans first-member-parked -> gang released
        # into the active queue (minMember reached)
        self.gang_schedule_attempts = Counter("gang_schedule_attempts_total")
        self.gang_wait_seconds = Histogram("gang_wait_seconds")
        self.pods_scheduled = Counter("pods_scheduled_total")
        self.pods_failed = Counter("pods_failed_total")
        # robustness layer: per-stage error attribution (bind worker /
        # device wave / extender webhook / device dispatch), snapshot
        # scrubber audit series, and device-path circuit-breaker trips.
        # `device` is filled only by stage=dispatch (ops/kernel.py
        # record_dispatch attributes the culprit mesh device, bounded to
        # the active set + "unknown"); every other site omits it and
        # keeps its un-suffixed series
        self.scheduling_errors = LabeledCounter("scheduling_errors_total",
                                                ("stage", "device"),
                                                open_labels=("device",))
        self.snapshot_scrub_runs = Counter("snapshot_scrub_runs_total")
        self.snapshot_scrub_divergences = Counter(
            "snapshot_scrub_divergences_total")
        self.snapshot_scrub_repairs = Counter("snapshot_scrub_repairs_total")
        self.snapshot_scrub_duration = Histogram(
            "snapshot_scrub_duration_seconds")
        self.device_path_trips = Counter("device_path_breaker_trips_total")
        # live breaker state (0=closed, 1=half-open, 2=open), set on
        # every transition — the trips counter says degradation HAS
        # happened; this gauge says whether scheduling is degraded NOW
        self.breaker_state = Gauge("device_path_breaker_state")
        # control-plane resilience layer: reflector relist cycles (every
        # list+watch re-entry, error-driven or watchdog-forced), streams
        # declared stale by the watchdog, bind POST retry attempts beyond
        # the first, and assumed pods expired without bind confirmation
        # (an expiry means a lost confirmation — never silent)
        self.reflector_relists = Counter("reflector_relists_total")
        self.watch_stale = Counter("watch_stale_total")
        self.bind_retries = Counter("bind_retries_total")
        self.cache_assumed_expired = Counter("cache_assumed_expired_total")
        # control-plane outage plane (sched/storehealth.py + the bind
        # spool): store-path breaker state (0=connected, 1=degraded,
        # 2=disconnected) set on every transition, trips into
        # DISCONNECTED, per-op store failures, and bind intents spooled
        # into the journal while disconnected (the spool DEPTH rides
        # scheduler_pending_pods{queue="spool"})
        self.store_breaker_state = Gauge("scheduler_store_breaker_state")
        self.store_breaker_trips = Counter(
            "scheduler_store_breaker_trips_total")
        self.store_errors = LabeledCounter(
            "store_errors_total", ("op",),
            values={"op": ("get", "list", "bind", "create", "update",
                           "delete", "watch")})
        self.binds_spooled = Counter("scheduler_binds_spooled_total")
        # queue depth per area, refreshed by the scheduler housekeeping
        # step — the cluster autoscaler and operators both watch it
        # (a Counter can't report a depth that drains)
        self.pending_pods = LabeledGauge("scheduler_pending_pods", ("queue",))
        # overload-control plane (sched/queue.py "Overload control" +
        # utils/watchdog.py): pods parked by priority-aware load
        # shedding per class, pending depth banded by priority class
        # (the client-go workqueue-depth signal made class-aware), wave
        # deadline overruns by stage (dispatch = watchdog-abandoned
        # device dispatch; host = featurize/upload exceeded the round
        # budget), and the adaptive wave cap those host overruns drive.
        # Class values are sched/queue.py QUEUE_CLASSES verbatim.
        self.shed_total = LabeledCounter(
            "scheduler_shed_total", ("class",),
            values={"class": ("system", "high", "normal", "low")})
        self.queue_class_pods = LabeledGauge(
            "scheduler_queue_class_pods", ("class",),
            values={"class": ("system", "high", "normal", "low")})
        self.wave_deadline_overruns = LabeledCounter(
            "scheduler_wave_deadline_overruns_total", ("stage",),
            values={"stage": ("dispatch", "host")})
        self.effective_wave_size = Gauge("scheduler_effective_wave_size")
        # poison-work isolation (sched/scheduler.py input-fault plane):
        # pods convicted of poisoning the batched scheduling pass, by
        # attribution route — featurize (typed PodFeaturizeError, direct
        # uid), sentinel (the kernel's numeric-integrity isfinite plane),
        # bisect (wave bisection converged on the culprit), gang
        # (quarantined with a convicted gangmate — atomicity extends to
        # conviction), golden (the exact per-pod path crashed on the
        # pod, attribution free)
        self.poison_pods = LabeledCounter(
            "scheduler_poison_pods_total", ("reason",),
            values={"reason": ("featurize", "sentinel", "bisect", "gang",
                               "golden")})
        # continuously-checked cluster invariants (chaos/invariants.py):
        # one child per named invariant the post-round checker can fail.
        # Any nonzero child is a scheduler bug — the chaos campaign and
        # the storm/meshfault benches gate on the family staying zero.
        self.invariant_violations = LabeledCounter(
            "scheduler_invariant_violations_total", ("invariant",),
            values={"invariant": ("conservation", "double_bind",
                                  "capacity", "snapshot_usage",
                                  "gang_atomic", "state_machine")})
        # node lifecycle / eviction storm control: per-zone health state
        # (1 on the current state's child, 0 on the others), evictions
        # actually executed per zone, evictions due-but-held by the
        # rate limiter or a suspended zone, and zone-suspension entries
        # (FullDisruption transitions)
        # zone names come from node labels (open, one series per live
        # zone); the state set is the controller's closed enum
        # (controllers/nodelifecycle.py ZONE_STATES)
        self.zone_health = LabeledGauge(
            "node_lifecycle_zone_health", ("zone", "state"),
            values={"state": ("Normal", "PartialDisruption",
                              "FullDisruption")},
            open_labels=("zone",))
        self.zone_evictions = LabeledCounter(
            "node_lifecycle_evictions_total", ("zone",),
            open_labels=("zone",))
        self.eviction_queue_depth = LabeledGauge(
            "node_lifecycle_eviction_queue_depth", ("zone",),
            open_labels=("zone",))
        self.eviction_suspensions = Counter(
            "node_lifecycle_suspensions_total")
        # cluster-autoscaler series (autoscaler's scaled_up/down analogs)
        self.autoscaler_scale_ups = Counter(
            "cluster_autoscaler_scaled_up_nodes_total")
        self.autoscaler_scale_downs = Counter(
            "cluster_autoscaler_scaled_down_nodes_total")
        # device telemetry (fed where ops/kernel.py dispatches): jit
        # program-cache hits/misses per shape bucket, compile seconds on
        # misses, snapshot HBM footprint + host->device upload bytes,
        # device->host result-fetch bytes, and device-vs-host wave
        # attribution (how much scheduling actually ran on device)
        # program names are the record_dispatch() call sites; bucket is
        # intentionally open — one value per compiled shape bucket, the
        # same cardinality as the jit program cache itself
        self.device_jit_events = LabeledCounter(
            "device_jit_cache_events_total", ("program", "bucket", "event"),
            values={"program": ("wave", "round", "gang", "telemetry",
                                "preempt"),
                    "event": ("hit", "miss")},
            open_labels=("bucket",))
        self.device_jit_compile_seconds = Histogram(
            "device_jit_compile_seconds")
        self.snapshot_hbm_bytes = Gauge("snapshot_hbm_bytes")
        # per-device footprint under mesh sharding (each device holds
        # 1/shards of every node group + a full pod/term replica); the
        # unlabeled gauge above sums TRUE per-shard bytes across devices
        # device ids are open (mesh size varies) but bounded by the
        # visible device count; stale children are zeroed on fallback
        self.snapshot_hbm_device_bytes = LabeledGauge(
            "snapshot_hbm_bytes_per_device", ("device",),
            open_labels=("device",))
        self.snapshot_upload_bytes = Counter("snapshot_upload_bytes_total")
        # memory-governance plane (ISSUE 20): per-vocabulary interner
        # sizes (the closed label set IS VocabSet.NAMES — the soak
        # harness gates on every child plateauing under node churn),
        # HBM budget headroom (budget - projected footprint; negative =
        # over budget, only exported when a budget is configured),
        # compactions by trigger, and round-boundary capacity faults
        # (RESOURCE_EXHAUSTED / MemoryError classified as
        # capacity, not device faults)
        self.snapshot_vocab_size = LabeledGauge(
            "snapshot_vocab_size", ("vocab",),
            values={"vocab": ("label_keys", "label_values", "taint_keys",
                              "taint_values", "resources", "ports",
                              "namespaces", "zones", "images",
                              "pod_label_keys")})
        self.hbm_headroom_bytes = Gauge("scheduler_hbm_headroom_bytes")
        self.snapshot_compactions_total = LabeledCounter(
            "snapshot_compactions_total", ("trigger",),
            values={"trigger": ("cadence", "governor", "oom")})
        self.capacity_faults = Counter("scheduler_capacity_faults_total")
        self.device_fetch_bytes = Counter("device_fetch_bytes_total")
        # mesh fault tolerance (sched/breaker.py MeshFaultManager +
        # parallel/mesh.py reform_mesh): how many devices the scheduling
        # mesh currently spans (the degradation ladder's live rung: 8 ->
        # 4 -> 2 -> 1; 1 when unsharded), reforms by direction (down =
        # device loss shrank the mesh, up = a healed device re-admitted
        # by a recovery probe grew it back), and a per-device quarantine
        # flag (1 while quarantined; the child is removed on re-admit so
        # /metrics never freezes a healed device at 1). Device names are
        # open but bounded by the visible device count, like the
        # per-device HBM gauge above.
        self.mesh_devices = Gauge("scheduler_mesh_devices")
        self.mesh_reforms = LabeledCounter(
            "mesh_reform_total", ("direction",),
            values={"direction": ("down", "up")})
        self.device_quarantined = LabeledGauge(
            "device_quarantined", ("device",), open_labels=("device",))
        self.waves_total = LabeledCounter("scheduler_waves_total", ("path",))
        # degraded-mode visibility: breaker-open pods the hostwave twin
        # can't encode, routed to the exact per-pod golden path, by
        # reason (affinity = untwinned inter-pod-affinity plane;
        # multi_tk = multi-topology-key required terms)
        self.degraded_golden_pods = LabeledCounter(
            "scheduler_degraded_golden_pods_total", ("reason",),
            values={"reason": ("affinity", "multi_tk")})
        # decision observatory (score decomposition, tracing only):
        # margin-of-victory distribution over placed pods (winner's
        # weighted total minus the best DIFFERENT node's), and the
        # accumulated weighted contribution of each priority to winning
        # totals — the skew ratio between children says which priority
        # actually drives placements under the current weights
        self.score_margin = Histogram("scheduler_score_margin")
        # ops/scores.py SCORE_STACK verbatim (tests/test_analysis.py
        # asserts the two stay in lockstep)
        self.score_priority_points = LabeledCounter(
            "scheduler_score_priority_points_total", ("priority",),
            values={"priority": (
                "LeastRequested", "BalancedAllocation", "MostRequested",
                "NodeAffinity", "TaintToleration", "SelectorSpread",
                "PreferAvoid", "ImageLocality", "InterPodAffinity",
                "TopologySpread", "TopologyCompactness",
                "HostExtra")})
        # counterfactual shadow scoring (sched/weights.py): per
        # candidate-profile placement divergence (would-have-chosen !=
        # chosen over the traced decomposition — a top-K lower bound),
        # pods scored per profile (the rate denominator), and the
        # margin-over-runner-up delta distribution (candidate margin
        # minus production margin; negative = the candidate decides
        # less decisively). {profile} values are the loaded
        # WeightProfile names — a declared set bounded at
        # sched/weights.py MAX_PROFILES, overflow bucketed through
        # bounded_label into "Other"
        self.shadow_divergence = LabeledCounter(
            "scheduler_shadow_divergence_total", ("profile",))
        self.shadow_scored_pods = LabeledCounter(
            "scheduler_shadow_scored_pods_total", ("profile",))
        # score-scale buckets (weighted totals live in 0..~100k with the
        # default PreferAvoid weight; deltas are typically single-digit
        # and can be negative — sub-first-bucket values land in the
        # first cumulative bucket, the reservoir keeps exact quantiles)
        self.shadow_margin_delta = Histogram(
            "scheduler_shadow_margin_delta",
            buckets=[-100.0, -50.0, -20.0, -10.0, -5.0, -2.0, -1.0, 0.0,
                     1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0])
        # autopilot promotion pipeline (autopilot/controller.py):
        # terminal verdicts per candidate run — promoted counts the
        # go-live transition, rolled_back the regression watch firing
        # after one (a force-promoted regression increments both)
        self.autopilot_promotions = LabeledCounter(
            "scheduler_autopilot_promotions_total", ("outcome",),
            values={"outcome": (
                "promoted", "rejected_shadow", "rejected_replay",
                "rolled_back", "aborted")})
        # first-fail predicate attribution for unschedulable pods —
        # previously reachable only through events and FitError text,
        # invisible to dashboards
        self.unschedulable_reasons = LabeledCounter(
            "scheduler_unschedulable_reasons_total", ("predicate",))
        # cluster-state telemetry plane (ops/telemetry.py, refreshed
        # once per traced round): requested/allocatable/free per
        # resource, the fragmentation index (1 - largest free block /
        # total free), feasibility headroom per canonical pod shape,
        # and per-zone utilization
        # resource/zone labels are open by design (extended resources
        # and zones come from cluster state) and PRUNED on disappearance
        # by the telemetry exporter — cardinality tracks the live
        # cluster, not its history
        self.cluster_requested = LabeledGauge(
            "scheduler_cluster_requested", ("resource",),
            open_labels=("resource",))
        self.cluster_allocatable = LabeledGauge(
            "scheduler_cluster_allocatable", ("resource",),
            open_labels=("resource",))
        self.cluster_free_largest = LabeledGauge(
            "scheduler_cluster_free_largest_block", ("resource",),
            open_labels=("resource",))
        self.cluster_fragmentation = LabeledGauge(
            "scheduler_cluster_fragmentation_index", ("resource",),
            open_labels=("resource",))
        # ops/telemetry.py CANONICAL_SHAPES names verbatim
        # (tests/test_analysis.py asserts lockstep)
        self.feasibility_headroom = LabeledGauge(
            "scheduler_feasibility_headroom", ("shape",),
            values={"shape": ("1c-2g", "2c-8g", "4c-16g", "8c-32g")})
        self.zone_utilization = LabeledGauge(
            "scheduler_zone_utilization", ("zone", "resource"),
            open_labels=("zone", "resource"))

    def all_series(self):
        out = {}
        for k, v in vars(self).items():
            if isinstance(v, (Counter, Gauge, Histogram)):
                out[k] = v
            elif isinstance(v, (LabeledCounter, LabeledGauge)):
                for c in v.children():
                    out[c.name] = c
        return out
