"""TCP relay primitive shared by the port-forward data path.

Both ends of `kubectl port-forward` are the same machine here — the
kubectl local listener and the kubelet's relay to the pod backend
(kubelet/server.py, cli/kubectl.py) — so the one-connection
accept → connect → bidirectional-pump structure lives once, in this
module, instead of drifting apart in two copies."""

from __future__ import annotations

import socket
import threading


def pump(src: socket.socket, dst: socket.socket) -> None:
    """Copy bytes src -> dst until EOF, then half-close dst so the far
    end observes the EOF too."""
    try:
        while True:
            data = src.recv(4096)
            if not data:
                break
            dst.sendall(data)
    except OSError:
        pass
    finally:
        try:
            dst.shutdown(socket.SHUT_WR)
        except OSError:
            pass


def relay(conn: socket.socket, backend) -> None:
    """Connect to `backend` (host, port) and pump both directions of
    `conn` until either side closes; closes both sockets."""
    try:
        up = socket.create_connection(backend, timeout=10)
    except OSError:
        conn.close()
        return
    t = threading.Thread(target=pump, args=(conn, up), daemon=True)
    t.start()
    pump(up, conn)
    t.join(timeout=10)
    conn.close()
    up.close()


def relay_once(lsock: socket.socket, backend, accept_timeout=None) -> None:
    """Accept ONE connection on `lsock` and relay it to `backend`.
    Closes the listener after (or on) the accept — a fresh relay needs a
    fresh listener, which is the port-forward contract here."""
    if accept_timeout is not None:
        lsock.settimeout(accept_timeout)
    try:
        conn, _ = lsock.accept()
    except OSError:
        lsock.close()
        return
    lsock.close()
    relay(conn, backend)


def node_daemon_endpoint(store, name):
    """(host, kubelet_port) for a Node's serving endpoint, or None if
    the node is absent or publishes no daemon endpoint — ONE resolution
    idiom shared by the apiserver's exec/log proxy and the
    metrics-server scraper (the reference reads
    node.Status.DaemonEndpoints.KubeletEndpoint)."""
    node = (store.get("nodes", "", name)
            or store.get("nodes", "default", name))
    if node is None or not node.status.kubelet_port:
        return None
    host = next((a.address for a in node.status.addresses if a.address),
                "127.0.0.1")
    return host, node.status.kubelet_port
