"""Step + lock-contention profiling: the pprof analog.

Reference: the scheduler exposes pprof and contention profiling behind
EnableProfiling/EnableContentionProfiling
(cmd/kube-scheduler/app/server.go:229-233; contention via
goruntime.SetBlockProfileRate(1)). The question those answer —
"where did this round's 8 seconds go?" — is answered here by:

  * a step profiler fed by every utils.trace.Trace the scheduler
    already emits (pipeline rounds, waves, preemption chunks): each
    named step accumulates count / total / max, and report() prints
    the cumulative breakdown (pprof's debug=1 text form).
  * a contention profiler: instrument_lock() swaps a component's lock
    for a wait-time-recording proxy (SetBlockProfileRate(1) analog),
    so time spent BLOCKED on the scheduler mutex or store lock shows
    up by name.

Both are opt-in (enable()/instrument_lock) and served by the
kube-scheduler health server at /debug/profile, like the reference's
--profiling / --contention-profiling flags.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple


class StepStats:
    __slots__ = ("count", "total", "max")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def add(self, dt: float):
        self.count += 1
        self.total += dt
        if dt > self.max:
            self.max = dt


class Profiler:
    def __init__(self):
        self._lock = threading.Lock()
        # (trace name prefix, step) -> stats
        self._steps: Dict[Tuple[str, str], StepStats] = {}
        self._contention: Dict[str, StepStats] = {}

    # -- step profile (fed by utils.trace.Trace) ---------------------------

    def record_step(self, trace_name: str, step: str, dt: float):
        # normalize per-invocation names ("pipeline of 173" -> "pipeline")
        prefix = trace_name.split(" of ")[0]
        with self._lock:
            key = (prefix, step)
            st = self._steps.get(key)
            if st is None:
                st = self._steps[key] = StepStats()
            st.add(dt)

    def record_wait(self, lock_name: str, dt: float):
        with self._lock:
            st = self._contention.get(lock_name)
            if st is None:
                st = self._contention[lock_name] = StepStats()
            st.add(dt)

    def step_totals(self, top: Optional[int] = None) -> Dict[str, float]:
        """Cumulative seconds per 'phase/step', descending — the
        structured form of report()'s step table (bench embeds it in
        the BENCH json as the per-stage breakdown)."""
        with self._lock:
            items = sorted(self._steps.items(), key=lambda kv: -kv[1].total)
        if top is not None:
            items = items[:top]
        return {f"{phase}/{step}": st.total for (phase, step), st in items}

    def report(self) -> str:
        """pprof debug=1 style text: cumulative step time, descending —
        'where the seconds went'."""
        with self._lock:
            steps = sorted(self._steps.items(),
                           key=lambda kv: -kv[1].total)
            cont = sorted(self._contention.items(),
                          key=lambda kv: -kv[1].total)
        lines = ["# step profile (cumulative seconds, descending)",
                 f"{'phase':<18}{'step':<22}{'count':>7}{'total_s':>10}"
                 f"{'max_s':>9}"]
        for (phase, step), st in steps:
            lines.append(f"{phase:<18}{step:<22}{st.count:>7}"
                         f"{st.total:>10.3f}{st.max:>9.3f}")
        lines.append("")
        lines.append("# lock contention (seconds blocked acquiring)")
        lines.append(f"{'lock':<30}{'count':>7}{'total_s':>10}{'max_s':>9}")
        for name, st in cont:
            lines.append(f"{name:<30}{st.count:>7}{st.total:>10.3f}"
                         f"{st.max:>9.3f}")
        return "\n".join(lines) + "\n"


# the active profiler; None = profiling disabled (zero overhead beyond
# one attribute read per trace step)
_ACTIVE: Optional[Profiler] = None


def enable() -> Profiler:
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = Profiler()
    return _ACTIVE


def disable():
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[Profiler]:
    return _ACTIVE


class _ProfiledLock:
    """Lock proxy recording time blocked in acquire (block-profile
    analog). Wraps RLock/Lock alike; context-manager compatible."""

    def __init__(self, inner, name: str):
        self._inner = inner
        self._name = name

    def acquire(self, *a, **kw):
        prof = _ACTIVE
        if prof is None:
            return self._inner.acquire(*a, **kw)
        # fast path: uncontended acquire costs one extra monotonic read
        if self._inner.acquire(blocking=False):
            return True
        t0 = time.monotonic()
        got = self._inner.acquire(*a, **kw)
        prof.record_wait(self._name, time.monotonic() - t0)
        return got

    def release(self):
        return self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __getattr__(self, item):  # notify/wait for Condition-style users
        return getattr(self._inner, item)


def instrument_lock(obj, attr: str, name: str):
    """Swap obj.<attr> for a contention-recording proxy (the
    SetBlockProfileRate(1) analog, scoped to one lock)."""
    inner = getattr(obj, attr)
    if isinstance(inner, _ProfiledLock):
        return inner
    wrapped = _ProfiledLock(inner, name)
    setattr(obj, attr, wrapped)
    return wrapped
