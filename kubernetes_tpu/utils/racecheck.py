"""Lock-order race detection for tests.

Analog of the reference's `go test -race` reliance (SURVEY.md §5: race
detection is part of its test infrastructure). CPython can't have the
compiler instrument memory accesses, but the framework's shared state is
all lock-guarded — so the practical analog is a lock-ORDER watcher: wrap
the component locks, record the acquisition graph across threads, and
flag inversions (lock pairs taken in both orders), which are exactly the
latent deadlocks a data-race detector's happens-before analysis would
surface here. Used by tests/test_racecheck.py to run the
scheduler/store/kubelet concurrently under instrumentation.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Set, Tuple


class LockOrderWatcher:
    def __init__(self):
        self._mu = threading.Lock()
        self._held = threading.local()
        # directed edges name_a -> name_b: b was acquired while a held
        self.edges: Set[Tuple[str, str]] = set()
        self.violations: List[str] = []
        self._names: Dict[int, str] = {}

    def _stack(self) -> List[str]:
        if not hasattr(self._held, "stack"):
            self._held.stack = []
        return self._held.stack

    def wrap(self, name: str, lock):
        """Instrument a Lock/RLock-like object; returns a proxy with the
        same acquire/release/context-manager surface."""
        watcher = self

        class _Proxy:
            def acquire(self, *a, **kw):
                ok = lock.acquire(*a, **kw)
                if ok:
                    watcher._on_acquire(name)
                return ok

            def release(self):
                watcher._on_release(name)
                lock.release()

            def __enter__(self):
                self.acquire()
                return self

            def __exit__(self, *exc):
                self.release()

            def __getattr__(self, item):
                # Condition objects (wait/notify/notify_all) and any other
                # lock-like surface pass through to the real object
                return getattr(lock, item)

        return _Proxy()

    def _on_acquire(self, name: str):
        stack = self._stack()
        if name in stack:
            # re-entrant acquisition can't block: record no edges at all
            # (an a->r edge here would pair with the earlier r->a and
            # report a false inversion for `with r: with a: with r:`)
            stack.append(name)
            return
        with self._mu:
            for held in stack:
                edge = (held, name)
                if (name, held) in self.edges and edge not in self.edges:
                    self.violations.append(
                        f"lock-order inversion: {held!r} -> {name!r} here, "
                        f"{name!r} -> {held!r} elsewhere (potential "
                        f"deadlock)")
                self.edges.add(edge)
        stack.append(name)

    def _on_release(self, name: str):
        stack = self._stack()
        if name in stack:
            stack.reverse()
            stack.remove(name)
            stack.reverse()

    def assert_clean(self):
        if self.violations:
            raise AssertionError("; ".join(self.violations))


def instrument(watcher: LockOrderWatcher, obj, attr: str, name: str):
    """Replace obj.<attr> (a lock) with a watched proxy.

    Must run BEFORE any concurrency touches the object: a thread that
    captured the original lock object would not contend with threads
    acquiring the proxy, silently breaking mutual exclusion."""
    setattr(obj, attr, watcher.wrap(name, getattr(obj, attr)))
