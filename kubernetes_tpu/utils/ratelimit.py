"""Token-bucket rate limiter.

Reference: client-go util/flowcontrol/throttle.go
NewTokenBucketRateLimiter — the limiter behind the node lifecycle
controller's per-zone RateLimitedTimedQueue (zonePodEvictor /
zoneNoExecuteTainter in node_lifecycle_controller.go). Tokens accrue at
`qps` up to `burst`; TryAccept consumes one without blocking. The
controller swaps a zone's rate as the zone's health state changes
(SwapLimiter), so the SAME queue drains at the primary rate in a
healthy zone, at the secondary rate in a partially-disrupted one, and
not at all (qps 0) while eviction is suspended.

Clock-injectable so chaos tests drive the drain deterministically: the
bucket refills from the difference between successive clock readings,
never from wall time.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class TokenBucket:
    """Non-blocking token bucket. qps <= 0 means "never admit" (the
    suspended / halted eviction states), not "unlimited"."""

    def __init__(self, qps: float, burst: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self.burst = float(max(burst, 1.0))
        self._qps = float(qps)
        self._tokens = self.burst  # starts full, like flowcontrol's bucket
        self._last = clock()
        self._lock = threading.Lock()

    @property
    def qps(self) -> float:
        return self._qps

    def _refill(self, now: float) -> None:
        if now > self._last and self._qps > 0:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self._qps)
        self._last = max(self._last, now)

    def try_take(self, now: Optional[float] = None, n: float = 1.0) -> bool:
        """TryAccept: consume n tokens if available, never block."""
        now = now if now is not None else self.clock()
        with self._lock:
            self._refill(now)
            if self._qps <= 0 or self._tokens < n:
                return False
            self._tokens -= n
            return True

    def available(self, now: Optional[float] = None) -> float:
        now = now if now is not None else self.clock()
        with self._lock:
            self._refill(now)
            return self._tokens if self._qps > 0 else 0.0

    def swap_rate(self, qps: float, now: Optional[float] = None) -> None:
        """SwapLimiter: change the refill rate in place. Accrued tokens
        are kept (capped at burst) — entering a slower state must not
        grant a fresh burst, and recovering to a faster one must not
        confiscate what already accrued."""
        now = now if now is not None else self.clock()
        with self._lock:
            self._refill(now)
            if self._qps <= 0 and qps > 0:
                # while qps<=0 no tokens accrued; restart accrual from now
                self._last = now
            self._qps = float(qps)
