"""Step tracing (analog of apiserver/pkg/util/trace/trace.go:33 utiltrace).

The scheduler wraps every cycle in a Trace and logs it when it exceeds a
threshold (reference: generic_scheduler.go:108-160, 100ms)."""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

log = logging.getLogger("kubernetes_tpu")


class Trace:
    def __init__(self, name: str, clock=time.monotonic):
        self.name = name
        self.clock = clock
        self.start = clock()
        self.steps: List[Tuple[float, str]] = []

    def step(self, msg: str):
        now = self.clock()
        self.steps.append((now, msg))
        # feed the step profiler when enabled (utils/profiling.py): the
        # traces the scheduler already emits become the pprof-style
        # where-did-the-time-go breakdown with no extra instrumentation
        from . import profiling

        prof = profiling.active()
        if prof is not None:
            last = self.steps[-2][0] if len(self.steps) > 1 else self.start
            prof.record_step(self.name, msg, now - last)

    def total(self) -> float:
        return self.clock() - self.start

    def log_if_long(self, threshold: float = 0.1):
        total = self.total()
        if total >= threshold:
            last = self.start
            lines = [f"Trace {self.name!r} (total {total*1e3:.1f}ms):"]
            for t, msg in self.steps:
                lines.append(f"  +{(t-last)*1e3:.1f}ms {msg}")
                last = t
            log.info("\n".join(lines))
        return total
