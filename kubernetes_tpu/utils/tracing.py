"""Flight recorder: per-pod span tracing + a bounded round ledger.

The benches say *what* regressed (pods/s, p99) but never *where*: the
host-path preemption cliff and the mixed5k p99 are aggregate numbers
with no per-pod or per-stage attribution. This module is the analog of
the reference's tracing surface (EnableProfiling's pprof endpoints plus
the utiltrace logs) rebuilt around the wave model:

  * every scheduling **round** (pipeline / wave / gang / degraded)
    records named stage spans — featurize, upload, device_wave or
    host_wave, fetch, commit, preempt — so a round's wall time is
    attributable to >=95% by named spans;
  * every **pod** gets async spans keyed by UID (queue_wait, bind) plus
    instant events (bind retries, ambiguity resolutions, breaker trips,
    preemption what-ifs), so one slow pod can be traced end to end;
  * the last `max_rounds` rounds live in a ring buffer, exported from
    the kube-scheduler HealthServer at `/debug/trace` as Chrome
    trace-event JSON (Perfetto-loadable) or a plain-text timeline;
  * each finished round appends one structured ledger record (pending
    count, snapshot shape, device-vs-host path, outcome counts, span
    seconds) to an optional JSONL file — the offline substrate the
    learned scoring head trains on.

Opt-in exactly like utils/profiling.py: a process-global recorder
behind `enable()`/`disable()`, with `active()` returning None when off
so every instrumentation site costs one attribute read.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

# Round-ledger JSONL schema version, stamped on every record as "v".
# Bump when a field changes meaning or disappears; ADDING fields is not
# a version bump (downstream training jobs must ignore unknown keys).
# The schema is documented in README "Round-ledger JSONL schema".
#
# v2 (shadow-scoring observatory): every round record carries
# `weights_version` (the live WeightProfile the round dispatched under,
# or "static"), and traced rounds may carry `shadow` (per-candidate
# counterfactual divergence) and `golden` (decomposition coverage
# gaps). v1 readers that honor the ignore-unknown-keys contract parse
# v2 records unchanged — the bump marks that `scores`/decision weights
# now describe the LIVE vector, not necessarily the static defaults.
#
# v2 additions (autopilot, no bump — additive): standalone
# `kind: "autopilot"` records (round 0, no spans) ledger every
# candidate-lifecycle transition of the promotion pipeline; the file
# itself is size-capped and rotates to "<path>.1" (LEDGER_MAX_BYTES).
LEDGER_VERSION = 2

# bounded per-pod decision map (the /debug/score backing store): the
# most recent placement decision per pod UID, evicted oldest-first
MAX_DECISIONS = 4096

# ledger rotation: the JSONL file is size-capped — when an append would
# push it past the cap, the file is renamed to "<path>.1" (replacing any
# previous rotation) and a fresh file starts. One rotation generation
# keeps at most 2x the cap on disk, so a long autopilot run can never
# fill the volume; readers (autopilot/dataset.py) stream "<path>.1"
# first, then "<path>", so rotation loses at most one generation of
# history, never recent records. 0 disables the cap (unbounded append,
# the pre-rotation behavior).
LEDGER_MAX_BYTES = 64 * 1024 * 1024

# standalone (round-less) ledger records retained in memory for
# ledger_rows() / /debug endpoints — autopilot transitions and the like
MAX_EXTRA_RECORDS = 256


class Span:
    __slots__ = ("name", "cat", "t0", "t1", "tid", "args")

    def __init__(self, name: str, cat: str, t0: float, t1: float,
                 tid: int, args: Optional[Dict[str, Any]] = None):
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.t1 = t1
        self.tid = tid
        self.args = args or {}


class Event:
    __slots__ = ("name", "t", "tid", "args")

    def __init__(self, name: str, t: float, tid: int,
                 args: Optional[Dict[str, Any]] = None):
        self.name = name
        self.t = t
        self.tid = tid
        self.args = args or {}


class PodSpan:
    """Per-pod async span (Chrome 'b'/'e' pair keyed by the pod UID)."""

    __slots__ = ("uid", "name", "t0", "t1", "args")

    def __init__(self, uid: str, name: str, t0: float, t1: float,
                 args: Optional[Dict[str, Any]] = None):
        self.uid = uid
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.args = args or {}


# per-round caps so one 30k-pod mixed round cannot balloon the ring
# buffer; drops are counted in the ledger, never silent
MAX_POD_SPANS_PER_ROUND = 8192
MAX_EVENTS_PER_ROUND = 4096


class RoundTrace:
    """One scheduling round's spans/events. Stage spans are laid down by
    `mark()` (contiguous segments from the previous mark, exactly like
    utils.trace.Trace.step) so coverage of the round wall is structural,
    not best-effort."""

    def __init__(self, rec: "FlightRecorder", rid: int, kind: str,
                 meta: Optional[Dict[str, Any]] = None):
        self._rec = rec
        self.rid = rid
        self.kind = kind
        self.t0 = rec.now()
        self.t1: Optional[float] = None
        self._last_mark = self.t0
        self.meta = dict(meta or {})
        self.spans: List[Span] = []
        self.events: deque = deque(maxlen=MAX_EVENTS_PER_ROUND)
        self.pod_spans: deque = deque(maxlen=MAX_POD_SPANS_PER_ROUND)
        self.pod_span_drops = 0
        self.event_drops = 0
        self.ledger: Dict[str, Any] = {}

    # -- recording -----------------------------------------------------------

    def mark(self, name: str, cat: str = "stage", **args):
        """Close a stage span from the previous mark (or round start) to
        now. Consecutive marks therefore tile the round wall."""
        now = self._rec.now()
        with self._rec._lock:
            self.spans.append(Span(name, cat, self._last_mark, now,
                                   self._rec._tid(), args or None))
            self._last_mark = now

    def add_span(self, name: str, t0: float, t1: float, cat: str = "stage",
                 **args):
        """Explicit-interval span (gang_wait, autoscaler what-ifs)."""
        with self._rec._lock:
            self.spans.append(Span(name, cat, t0, t1, self._rec._tid(),
                                   args or None))

    def event(self, name: str, **args):
        with self._rec._lock:
            if len(self.events) == self.events.maxlen:
                self.event_drops += 1
            self.events.append(Event(name, self._rec.now(),
                                     self._rec._tid(), args or None))

    def pod_span(self, uid: str, name: str, duration: float, **args):
        """Per-pod span ENDING now, `duration` seconds long. Durations
        come from the scheduler's (possibly virtual) clock; anchoring the
        end at recorder-now keeps the timeline monotonic either way."""
        now = self._rec.now()
        with self._rec._lock:
            if len(self.pod_spans) == self.pod_spans.maxlen:
                self.pod_span_drops += 1
            self.pod_spans.append(
                PodSpan(uid, name, now - max(duration, 0.0), now,
                        args or None))

    # -- summaries -----------------------------------------------------------

    def wall(self) -> float:
        end = self.t1 if self.t1 is not None else self._rec.now()
        return end - self.t0

    def span_seconds(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for s in self.spans:
            out[s.name] = out.get(s.name, 0.0) + (s.t1 - s.t0)
        return out


class FlightRecorder:
    """Bounded ring buffer of the last N rounds' traces + the optional
    per-round JSONL ledger. Thread-safe: stage marks run under the
    scheduler lock, but bind spans land from binder threads and
    autoscaler what-ifs from the controller thread."""

    def __init__(self, max_rounds: int = 64,
                 ledger_path: Optional[str] = None,
                 clock=time.monotonic,
                 ledger_max_bytes: int = LEDGER_MAX_BYTES):
        self.clock = clock
        self.ledger_path = ledger_path
        self.ledger_max_bytes = int(ledger_max_bytes)
        self.ledger_rotations = 0
        # file appends serialize on their own lock, never _lock: a slow
        # or rotating disk write must not block span recording
        self._ledger_io = threading.Lock()
        self._ledger_bytes: Optional[int] = None
        self._lock = threading.Lock()
        self.epoch = clock()
        self.epoch_wall = time.time()
        self.rounds: deque = deque(maxlen=max_rounds)
        self._next_rid = 1
        self._current: Optional[RoundTrace] = None
        # spans/events recorded outside any round (breaker trips while
        # idle, autoscaler simulations between rounds)
        self.background = RoundTrace(self, 0, "background")
        self._tids: Dict[int, int] = {}
        self._tid_names: Dict[int, str] = {}
        self.ledger_records = 0
        # decision observatory: pod UID -> the score decomposition of
        # its most recent placement (scheduler._record_decisions feeds
        # it; /debug/score?uid= serves it)
        self.decisions: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        # standalone records appended outside any round (autopilot
        # promotion transitions), served alongside round records
        self.extra_records: deque = deque(maxlen=MAX_EXTRA_RECORDS)
        # round observers: called with each finished round's ledger
        # record, OUTSIDE the recorder lock (the autopilot regression
        # watch subscribes here). An observer must never fail a round.
        self.observers: List[Callable[[Dict[str, Any]], None]] = []

    def now(self) -> float:
        return self.clock()

    def _tid(self) -> int:
        """Stable small int per thread (Chrome trace tid); caller may
        hold _lock — plain dict ops only."""
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            tid = self._tids[ident] = len(self._tids)
            self._tid_names[tid] = threading.current_thread().name
        return tid

    # -- round lifecycle -----------------------------------------------------

    def begin_round(self, kind: str, **meta) -> RoundTrace:
        with self._lock:
            rt = RoundTrace(self, self._next_rid, kind, meta)
            self._next_rid += 1
            self.rounds.append(rt)
            self._current = rt
        return rt

    def end_round(self, rt: RoundTrace, **ledger_fields):
        rt.t1 = self.now()
        with self._lock:
            # conditional fields are absent, never null-padded (the
            # documented schema contract): a round that placed nothing
            # has no `scores` key, not "scores": null
            rt.ledger.update({k: v for k, v in ledger_fields.items()
                              if v is not None})
            if self._current is rt:
                self._current = None
            # record built under the lock (span/event containers are
            # append-racy from binder threads); the file write is not
            rec = self._ledger_record(rt)
        self._write_ledger_line(rec)
        for fn in list(self.observers):
            try:
                fn(rec)
            except Exception:
                pass  # an observer must never fail a scheduling round

    def _write_ledger_line(self, rec: Dict[str, Any]) -> None:
        """Append one record to the JSONL ledger, rotating the file to
        `<path>.1` when the append would push it past ledger_max_bytes.
        Serialized on _ledger_io (never _lock): end_round and
        append_record can land from different threads and the
        size-check + rename + write must be atomic against each other."""
        if not self.ledger_path:
            return
        line = json.dumps(rec) + "\n"
        with self._ledger_io:
            try:
                if self._ledger_bytes is None:
                    # adopt whatever an earlier run left behind so the
                    # cap holds across process restarts
                    try:
                        self._ledger_bytes = os.path.getsize(
                            self.ledger_path)
                    except OSError:
                        self._ledger_bytes = 0
                if (self.ledger_max_bytes > 0 and self._ledger_bytes > 0
                        and self._ledger_bytes + len(line)
                        > self.ledger_max_bytes):
                    os.replace(self.ledger_path, self.ledger_path + ".1")
                    self.ledger_rotations += 1
                    self._ledger_bytes = 0
                with open(self.ledger_path, "a") as f:
                    f.write(line)
                self._ledger_bytes += len(line)
                self.ledger_records += 1
            except OSError:
                pass  # a full disk must never fail a scheduling round

    def append_record(self, kind: str, **fields) -> Dict[str, Any]:
        """Standalone ledger record outside any round — the autopilot
        controller ledgers every candidate-lifecycle transition through
        here (kind "autopilot"). Carries the schema version and a
        round of 0 (no round envelope); conditional fields follow the
        absent-not-null contract like round records."""
        rec: Dict[str, Any] = {
            "v": LEDGER_VERSION, "round": 0, "kind": kind,
            "ts": round(self.epoch_wall + (self.now() - self.epoch), 6)}
        rec.update({k: v for k, v in fields.items() if v is not None})
        with self._lock:
            self.extra_records.append(rec)
        self._write_ledger_line(rec)
        return rec

    def current(self) -> RoundTrace:
        """The in-flight round, or the background pseudo-round."""
        with self._lock:
            return self._current if self._current is not None \
                else self.background

    def event(self, name: str, **args):
        self.current().event(name, **args)

    def add_span(self, name: str, t0: float, t1: float, cat: str = "stage",
                 **args):
        self.current().add_span(name, t0, t1, cat=cat, **args)

    def pod_span(self, uid: str, name: str, duration: float, **args):
        self.current().pod_span(uid, name, duration, **args)

    # -- decision observatory ------------------------------------------------

    def record_decision(self, uid: str, entry: Dict[str, Any]) -> None:
        """Store one pod's placement decomposition (bounded; newest
        decision per UID wins — a requeued pod's final placement is the
        one that matters)."""
        with self._lock:
            self.decisions[uid] = entry
            self.decisions.move_to_end(uid)
            while len(self.decisions) > MAX_DECISIONS:
                self.decisions.popitem(last=False)

    def decision(self, uid: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self.decisions.get(uid)

    def recent_decisions(self, n: int = 64) -> List[Tuple[str, Dict[str, Any]]]:
        """The most recent (uid, entry) pairs, newest last."""
        with self._lock:
            items = list(self.decisions.items())
        return items[-n:]

    # -- ledger --------------------------------------------------------------

    def _ledger_record(self, rt: RoundTrace) -> Dict[str, Any]:
        rec = {
            "v": LEDGER_VERSION,
            "round": rt.rid,
            "kind": rt.kind,
            "ts": round(self.epoch_wall + (rt.t0 - self.epoch), 6),
            "wall_s": round(rt.wall(), 6),
            "spans": {k: round(v, 6) for k, v in rt.span_seconds().items()},
        }
        if rt.meta:
            rec.update(rt.meta)
        if rt.ledger:
            rec.update(rt.ledger)
        if rt.pod_span_drops:
            rec["pod_span_drops"] = rt.pod_span_drops
        if rt.event_drops:
            rec["event_drops"] = rt.event_drops
        return rec

    def ledger_rows(self) -> List[Dict[str, Any]]:
        """The ring buffer's rounds as ledger records (finished rounds
        only) plus buffered standalone records — what the JSONL file
        would contain, served live."""
        with self._lock:
            return ([self._ledger_record(r) for r in self.rounds
                     if r.t1 is not None] + list(self.extra_records))

    # -- exports -------------------------------------------------------------

    def _us(self, t: float) -> float:
        return round((t - self.epoch) * 1e6, 1)

    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome trace-event JSON (Perfetto-loadable): rounds and stage
        spans as complete ('X') events, per-pod spans as async 'b'/'e'
        pairs keyed by UID, instant events as 'i'."""
        with self._lock:
            # snapshot every container under the lock: the scheduler /
            # binder threads append to the in-flight round (and the
            # background pseudo-round) while the HTTP thread exports
            rounds = [(rt, list(rt.spans), list(rt.events),
                       list(rt.pod_spans))
                      for rt in list(self.rounds) + [self.background]]
            tid_names = dict(self._tid_names)
        ev: List[Dict[str, Any]] = [
            {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
             "args": {"name": "kube-scheduler"}}]
        for tid, name in tid_names.items():
            ev.append({"ph": "M", "name": "thread_name", "pid": 1,
                       "tid": tid, "args": {"name": name}})
        for rt, spans, events, pod_spans in rounds:
            if rt.rid:  # background has no round envelope
                end = rt.t1 if rt.t1 is not None else self.now()
                ev.append({"name": f"round {rt.rid} [{rt.kind}]",
                           "cat": "round", "ph": "X",
                           "ts": self._us(rt.t0),
                           "dur": round((end - rt.t0) * 1e6, 1),
                           "pid": 1, "tid": 0,
                           "args": {**rt.meta, **rt.ledger}})
            for s in spans:
                ev.append({"name": s.name, "cat": s.cat, "ph": "X",
                           "ts": self._us(s.t0),
                           "dur": round((s.t1 - s.t0) * 1e6, 1),
                           "pid": 1, "tid": s.tid, "args": s.args})
            for e in events:
                ev.append({"name": e.name, "cat": "event", "ph": "i",
                           "s": "t", "ts": self._us(e.t), "pid": 1,
                           "tid": e.tid, "args": e.args})
            for p in pod_spans:
                base = {"cat": "pod", "id": p.uid, "name": p.name,
                        "pid": 1, "tid": 0}
                ev.append({**base, "ph": "b", "ts": self._us(p.t0),
                           "args": {"uid": p.uid, **p.args}})
                ev.append({**base, "ph": "e", "ts": self._us(p.t1)})
        return {"traceEvents": ev, "displayTimeUnit": "ms"}

    def text_timeline(self) -> str:
        """Plain-text per-round timeline — the log-greppable export."""
        with self._lock:
            rounds = [(rt, list(rt.spans), list(rt.events),
                       len(rt.pod_spans)) for rt in self.rounds]
            bg_spans = len(self.background.spans)
            bg_events = len(self.background.events)
        lines = [f"# flight recorder: {len(rounds)} rounds buffered, "
                 f"{self.ledger_records} ledger records written"]
        for rt, spans, events, n_pod_spans in rounds:
            wall = rt.wall()
            head = (f"round {rt.rid} [{rt.kind}] "
                    f"+{(rt.t0 - self.epoch):.3f}s wall={wall*1e3:.1f}ms")
            if rt.meta:
                head += " " + " ".join(f"{k}={v}" for k, v in rt.meta.items())
            if rt.ledger:
                head += " " + " ".join(
                    f"{k}={v}" for k, v in rt.ledger.items()
                    if not isinstance(v, dict))
            lines.append(head)
            for s in spans:
                lines.append(f"  +{(s.t0 - rt.t0)*1e3:8.1f}ms "
                             f"{s.name:<16} {(s.t1 - s.t0)*1e3:8.1f}ms"
                             + (f"  {s.args}" if s.args else ""))
            for e in events:
                lines.append(f"  +{(e.t - rt.t0)*1e3:8.1f}ms "
                             f"! {e.name} {e.args}")
            if n_pod_spans:
                lines.append(f"  ({n_pod_spans} pod spans"
                             + (f", {rt.pod_span_drops} dropped"
                                if rt.pod_span_drops else "") + ")")
        if bg_spans or bg_events:
            lines.append(f"background: {bg_spans} spans, "
                         f"{bg_events} events")
        return "\n".join(lines) + "\n"


def _fmt_score(v) -> str:
    if v is None:
        return "-"
    f = float(v)
    return f"{int(f)}" if f == int(f) else f"{f:.2f}"


def format_decision(uid: str, e: Dict[str, Any]) -> str:
    """One-line human rendering of a decision entry — the V(10)
    "Host %s => Score %d" log line, upgraded to an explanation:
    "p1 -> node-42 won by 3 over node-7: LeastRequested 8 vs 6, ..."."""
    head = f"{e.get('pod', uid)} -> {e['node']}"
    margin = e.get("margin")
    if margin is not None and e.get("runner_up"):
        head += f" won by {_fmt_score(margin)} over {e['runner_up']}"
    parts = []
    for name, p in e.get("parts", {}).items():
        if not p.get("weight"):
            continue
        parts.append(f"{name} {_fmt_score(p.get('chosen'))}"
                     f" vs {_fmt_score(p.get('runner_up'))}")
    tail = f" (total {_fmt_score(e.get('total'))}, round {e.get('round')}"
    # which weight vector decided this placement — "static", or the
    # live WeightProfile's name@version (the hot-swap observability)
    wver = e.get("weights_version")
    if wver:
        tail += f", weights {wver}"
    tail += ")"
    return head + ": " + ", ".join(parts) + tail


# the active recorder; None = tracing disabled (zero overhead beyond one
# attribute read per instrumentation site)
_ACTIVE: Optional[FlightRecorder] = None


def enable(max_rounds: int = 64, ledger_path: Optional[str] = None,
           clock=time.monotonic,
           ledger_max_bytes: Optional[int] = None) -> FlightRecorder:
    """Install the process-global recorder. An already-active recorder
    is returned as-is EXCEPT that a newly-requested ledger path (and
    its rotation cap) is adopted (the caller asked for a ledger; losing
    it silently cost a run's records) — ring size and clock stay with
    the original."""
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = FlightRecorder(
            max_rounds=max_rounds, ledger_path=ledger_path, clock=clock,
            ledger_max_bytes=(LEDGER_MAX_BYTES if ledger_max_bytes is None
                              else ledger_max_bytes))
    elif ledger_path and not _ACTIVE.ledger_path:
        _ACTIVE.ledger_path = ledger_path
        if ledger_max_bytes is not None:
            _ACTIVE.ledger_max_bytes = int(ledger_max_bytes)
    return _ACTIVE


def disable():
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[FlightRecorder]:
    return _ACTIVE


def event(name: str, **args):
    """Convenience instant event: no-op when tracing is off."""
    rec = _ACTIVE
    if rec is not None:
        rec.event(name, **args)


class _SpanCtx:
    __slots__ = ("rec", "name", "cat", "args", "t0")

    def __init__(self, rec, name, cat, args):
        self.rec = rec
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self.t0 = self.rec.now()
        return self

    def __exit__(self, *exc):
        self.rec.add_span(self.name, self.t0, self.rec.now(),
                          cat=self.cat, **self.args)
        return False


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


def span(name: str, cat: str = "stage", **args):
    """Context-manager span attached to the current round (or the
    background pseudo-round); the shared no-op when tracing is off."""
    rec = _ACTIVE
    if rec is None:
        return _NULL
    return _SpanCtx(rec, name, cat, args)
