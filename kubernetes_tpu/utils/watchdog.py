"""Device-dispatch watchdog: a deadline budget around jitted dispatches.

The device-path circuit breaker (sched/breaker.py) only trips on RAISED
exceptions — a wedged XLA dispatch that silently never returns (the
observed axon-tunnel failure mode: every call into the runtime blocks
indefinitely, machine-wide, for hours) would wedge the scheduling loop
forever with the breaker still CLOSED. The watchdog closes that gap:
each dispatch through the ops/kernel.py `record_dispatch` seam runs on
a worker thread with a deadline; a dispatch that exceeds it is
ABANDONED — the thread keeps running against the wedged runtime (a
thread cannot be killed, and the runtime owns the hang), but the
scheduling loop gets `DispatchTimeout` immediately, feeds the breaker,
and the round completes through the numpy hostwave twin. Scheduling
never stalls behind a wedged dispatch.

Abandoned-but-still-running dispatches are tracked: while any is
outstanding the scheduler refuses to dispatch AT ALL (including the
breaker's half-open probe — see Scheduler._device_admitted), because a
runtime with a wedged wave in flight would eat the probe the same way.

Cold compiles are not hangs: a first dispatch at a new shape bucket
legitimately takes 10-40s on TPU, so unwarmed dispatches get the
deadline scaled by `compile_scale`.

Results of an abandoned dispatch are discarded when the thread finally
returns — kernel dispatches are pure functions over device arrays; all
scheduler state mutation happens host-side after a successful fetch,
so nothing partial can escape an abandoned wave.
"""

from __future__ import annotations

import atexit
import threading
import time
import weakref
from typing import Callable, List, Optional

# Live watchdogs, weakly held: ONE module-level atexit hook drains
# whatever is still alive at interpreter exit. Weak refs so the hook
# never pins a discarded watchdog's whole object graph (on_abandon is
# typically a bound Scheduler method -> store -> HBM mirrors).
_LIVE: List["weakref.ref"] = []


def _drain_all() -> None:
    for ref in list(_LIVE):
        wd = ref()
        if wd is not None:
            wd.drain()


atexit.register(_drain_all)


class DispatchTimeout(RuntimeError):
    """A device dispatch exceeded its watchdog deadline and was
    abandoned. The dispatch may still complete eventually; its result
    is discarded either way."""

    def __init__(self, program: str, deadline_s: float):
        super().__init__(
            f"device dispatch {program!r} exceeded its "
            f"{deadline_s:.3f}s deadline and was abandoned")
        self.program = program
        self.deadline_s = deadline_s


class DispatchWatchdog:
    """Deadline harness for device dispatches. `deadline_s` <= 0
    disarms it entirely (run() degenerates to fn()). One worker thread
    per guarded dispatch — ~50-100us of overhead against the ~50ms
    fixed cost of a device program execution."""

    def __init__(self, deadline_s: float, compile_scale: float = 20.0,
                 on_abandon: Optional[Callable[[str, float], None]] = None):
        self.deadline_s = float(deadline_s)
        # unwarmed shape buckets compile inside the dispatch: scale the
        # budget rather than charging a legitimate 10-40s TPU compile
        # as a hang
        self.compile_scale = float(compile_scale)
        # fired (program, deadline_s) on every abandonment — feeds
        # scheduler_wave_deadline_overruns_total{stage=dispatch} and
        # the flight recorder
        self.on_abandon = on_abandon
        self.abandoned_total = 0
        # completion events of abandoned dispatches still in flight;
        # pruned on read (list, not set: determinism rule)
        self._inflight: List[threading.Event] = []
        self._lock = threading.Lock()
        # exit-time drain (module-level hook, weakly registered): a
        # daemon worker still blocked inside native XLA code while the
        # interpreter tears the runtime down aborts the whole process
        # (C++ terminate -> SIGABRT, exit 134) — a successful run that
        # once hit a wedged dispatch would read as a crash to any
        # supervisor. Bounded wait, best effort.
        _LIVE[:] = [r for r in _LIVE if r() is not None]
        _LIVE.append(weakref.ref(self))

    def armed(self) -> bool:
        return self.deadline_s > 0

    def outstanding(self) -> int:
        """Abandoned dispatches whose worker threads are STILL blocked
        in the runtime. While this is non-zero the runtime is presumed
        wedged and no new dispatch should be issued."""
        with self._lock:
            self._inflight = [e for e in self._inflight if not e.is_set()]
            return len(self._inflight)

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait (bounded) for every abandoned dispatch to return.
        Registered at exit; also useful for tests that must not leak a
        still-running dispatch into the next scenario. True when the
        runtime is quiet again."""
        deadline = time.monotonic() + timeout
        with self._lock:
            pending = list(self._inflight)
        for e in pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not e.wait(remaining):
                return False
        return True

    def run(self, fn: Callable, program: str = "wave",
            warm: bool = True):
        """Run one dispatch under the deadline. Raises DispatchTimeout
        on abandonment; re-raises fn's own exception otherwise."""
        if not self.armed():
            return fn()
        deadline = self.deadline_s * (1.0 if warm else self.compile_scale)
        done = threading.Event()
        box: dict = {}

        def _worker():
            try:
                box["out"] = fn()
            except BaseException as e:  # re-raised on the caller below
                box["exc"] = e
            finally:
                done.set()

        t = threading.Thread(target=_worker, daemon=True,
                             name=f"dispatch-{program}")
        t.start()
        if not done.wait(deadline):
            with self._lock:
                self.abandoned_total += 1
                self._inflight.append(done)
            if self.on_abandon is not None:
                self.on_abandon(program, deadline)
            raise DispatchTimeout(program, deadline)
        if "exc" in box:
            raise box["exc"]
        return box["out"]
