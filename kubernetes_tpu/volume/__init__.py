"""Volume plugin layer — pkg/volume analog."""

from .plugin import (Attacher, Detacher, Mounter, Spec, Unmounter,
                     VolumePlugin, VolumePluginMgr, default_plugin_mgr)
from .mount import InMemoryMount, MountPoint
from .manager import VolumeManager
