"""CSI: the out-of-process volume driver seam.

Reference: pkg/volume/csi/csi_plugin.go:45 — the in-tree "csi" plugin is
a SHIM: every operation crosses a process boundary to a driver speaking
the CSI protocol (gRPC over a unix socket; Identity/Controller/Node
services). The extensibility seam is the point, not any particular
driver. Here the wire protocol is JSON-over-HTTP on a loopback socket —
same boundary, same RPC shapes:

  GET  /identity                      GetPluginInfo
  POST /controller/create-volume      CreateVolume      {name, capacity}
  POST /controller/delete-volume      DeleteVolume      {volume_id}
  POST /controller/publish            ControllerPublishVolume {volume_id, node}
  POST /controller/unpublish          ControllerUnpublishVolume
  POST /node/publish                  NodePublishVolume {volume_id, pod_uid, target}
  POST /node/unpublish                NodeUnpublishVolume

Driver DISCOVERY is an API object: creating a `CSIDriver` (name +
endpoint) registers the driver cluster-wide — the analog of the
kubelet's plugin-socket watcher plus the CSIDriver object of later
Kubernetes. The shim (CSIPlugin) resolves endpoints through the store
at call time, so drivers can appear/disappear at runtime.

A pod's CSI volume flows exactly like any attachable in-tree volume:
the provisioner creates the PV (CreateVolume), the PV controller binds
the claim, the scheduler places the pod, the attach/detach controller
calls ControllerPublishVolume before recording the attachment in
node.status, the kubelet volume manager gates on that and then calls
NodePublishVolume to mount, and teardown unwinds through
NodeUnpublish/ControllerUnpublish/DeleteVolume.

`python -m kubernetes_tpu.volume.csi --port N` serves the in-memory
mock driver standalone — a genuinely separate process, for the
out-of-process integration test.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..api import types as api
from .plugin import Mounter, Spec, Unmounter, VolumePlugin

CSI_SOURCE_KIND = "CSI"
# the reference external-provisioner's claim annotation
PROVISIONER_ANNOTATION = "volume.beta.kubernetes.io/storage-provisioner"


class CSIError(Exception):
    pass


# -- the driver side (what a storage vendor ships) ----------------------------


class MockCSIDriver:
    """In-memory driver implementing the protocol semantics the CSI spec
    demands: idempotent creates, publish tracked per (volume, node),
    node-publish tracked per (volume, target); operations on unknown
    volumes fail. The csi-sanity mock driver analog."""

    def __init__(self, name: str = "mock.csi.k8s.io"):
        self.name = name
        self._lock = threading.Lock()
        self.volumes: Dict[str, dict] = {}          # id -> {name, capacity}
        self.published: Dict[str, str] = {}         # id -> node
        self.node_published: Dict[tuple, dict] = {}  # (id, target) -> info

    def handle(self, method: str, path: str, body: dict) -> dict:
        if path == "/identity":
            return {"name": self.name, "capabilities":
                    ["CONTROLLER_SERVICE", "CREATE_DELETE_VOLUME"]}
        with self._lock:
            if path == "/controller/create-volume":
                name = body["name"]
                for vid, v in self.volumes.items():
                    if v["name"] == name:  # idempotency by name
                        return {"volume_id": vid,
                                "capacity": v["capacity"]}
                vid = f"vol-{len(self.volumes)}-{name}"
                self.volumes[vid] = {"name": name,
                                     "capacity": int(body.get("capacity", 0))}
                return {"volume_id": vid,
                        "capacity": self.volumes[vid]["capacity"]}
            if path == "/controller/delete-volume":
                self.volumes.pop(body["volume_id"], None)  # idempotent
                return {}
            vid = body.get("volume_id")
            if path == "/controller/publish":
                if vid not in self.volumes:
                    raise CSIError(f"unknown volume {vid!r}")
                node = body["node"]
                cur = self.published.get(vid)
                if cur is not None and cur != node:
                    raise CSIError(f"{vid} already published to {cur}")
                self.published[vid] = node
                return {"publish_context": {"device": f"/dev/csi/{vid}"}}
            if path == "/controller/unpublish":
                self.published.pop(vid, None)
                return {}
            if path == "/node/publish":
                if vid not in self.volumes:
                    raise CSIError(f"unknown volume {vid!r}")
                key = (vid, body["target"])
                self.node_published[key] = {"pod_uid": body.get("pod_uid")}
                return {"payload": {"csi/device": f"/dev/csi/{vid}"}}
            if path == "/node/unpublish":
                self.node_published.pop((vid, body.get("target")), None)
                return {}
        raise CSIError(f"unknown CSI call {path!r}")


class CSIDriverServer:
    """Serves a driver implementation over the wire protocol."""

    def __init__(self, driver, host: str = "127.0.0.1", port: int = 0):
        self.driver = driver
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _serve(self):
                length = int(self.headers.get("Content-Length") or 0)
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    resp = outer.driver.handle(self.command, self.path, body)
                    code, payload = 200, json.dumps(resp).encode()
                except CSIError as e:
                    code, payload = 422, json.dumps(
                        {"error": str(e)}).encode()
                except Exception as e:
                    code, payload = 500, json.dumps(
                        {"error": repr(e)}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST = _serve

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "CSIDriverServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="csi-driver")
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


# -- the cluster side (the in-tree shim) --------------------------------------


class CSIClient:
    """HTTP client for one driver endpoint."""

    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def call(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self.url + path, method=method,
            data=json.dumps(body or {}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("error", "")
            except Exception:
                msg = str(e)
            raise CSIError(msg)
        except OSError as e:
            raise CSIError(f"driver unreachable: {e}")


def register_driver(store, name: str, endpoint: str) -> None:
    """Publish a CSIDriver object — cluster-wide driver discovery."""
    from ..runtime.store import Conflict

    try:
        store.create("csidrivers", api.CSIDriver(
            metadata=api.ObjectMeta(name=name, namespace=""),
            endpoint=endpoint))
    except Conflict:
        cur = store.get("csidrivers", "", name)
        if cur is not None and cur.endpoint != endpoint:
            cur.endpoint = endpoint
            store.update("csidrivers", cur)


def _client_for(store, driver_name: str,
                timeout: float = 10.0) -> CSIClient:
    obj = (store.get("csidrivers", "", driver_name)
           or store.get("csidrivers", "default", driver_name))
    if obj is None:
        raise CSIError(f"CSI driver {driver_name!r} is not registered")
    return CSIClient(obj.endpoint, timeout=timeout)


class _CSIMounter(Mounter):
    def set_up(self) -> None:
        pv = self.spec.pv
        client = _client_for(self.store, pv.spec.csi_driver)
        target = f"{self.pod.metadata.uid}/{self.spec.name}"
        resp = client.call("POST", "/node/publish", {
            "volume_id": pv.spec.source_id,
            "pod_uid": self.pod.metadata.uid,
            "target": target})
        payload = dict(resp.get("payload") or {})
        # teardown needs the driver + handle + target; carry them on the
        # mount record (the reference writes vol_data.json next to the
        # mount dir for the same reason)
        payload["csi/driver"] = pv.spec.csi_driver
        payload["csi/handle"] = pv.spec.source_id
        payload["csi/target"] = target
        self.mount.mount(self.pod.metadata.uid, self.spec.name,
                         kind=self.plugin.name, payload=payload,
                         read_only=(self.spec.volume.read_only
                                    if self.spec.volume else False))


class _CSIUnmounter(Unmounter):
    def __init__(self, plugin, volume_name, pod_uid, mount_backend, store):
        super().__init__(plugin, volume_name, pod_uid, mount_backend)
        self.store = store

    def tear_down(self) -> None:
        m = self.mount.get(self.pod_uid, self.volume_name)
        if m is not None and m.payload.get("csi/driver"):
            # NodeUnpublish must SUCCEED before the mount record is
            # dropped: the record is the only state that drives retries,
            # so removing it on failure would leak the driver's
            # node-publish entry forever (the driver may then refuse
            # ControllerUnpublish/DeleteVolume). The raise is caught by
            # the volume manager, which keeps the record and retries.
            _client_for(self.store, m.payload["csi/driver"]).call(
                "POST", "/node/unpublish", {
                    "volume_id": m.payload.get("csi/handle"),
                    "target": m.payload.get("csi/target")})
        self.mount.unmount(self.pod_uid, self.volume_name)


class _CSIAttacher:
    def __init__(self, store):
        self.store = store

    def attach(self, spec: Spec, node_name: str) -> str:
        pv = spec.pv
        # short timeout: this runs inside the attach/detach controller's
        # sync — a dead driver must not stall the worker 10s per volume
        # per retry while unrelated nodes queue behind it
        client = _client_for(self.store, pv.spec.csi_driver, timeout=2.0)
        client.call("POST", "/controller/publish", {
            "volume_id": pv.spec.source_id, "node": node_name})
        return pv.metadata.name

    def wait_for_attach(self, spec: Spec, node) -> bool:
        return (spec.pv is not None and
                spec.pv.metadata.name in set(node.status.volumes_attached))


class _CSIDetacher:
    def __init__(self, store):
        self.store = store

    def detach_pv(self, pv: api.PersistentVolume, node_name: str) -> None:
        client = _client_for(self.store, pv.spec.csi_driver, timeout=2.0)
        client.call("POST", "/controller/unpublish", {
            "volume_id": pv.spec.source_id, "node": node_name})


class CSIPlugin(VolumePlugin):
    """csi_plugin.go:45 — the shim. All state lives in the driver and
    the API objects; the plugin itself is stateless (safe to construct
    per component)."""

    name = "kubernetes.io/csi"
    attachable = True

    def __init__(self, store=None):
        self.store = store

    def can_support(self, spec: Spec) -> bool:
        return spec.source_kind == CSI_SOURCE_KIND

    def new_mounter(self, spec, pod, mount_backend, store=None, mgr=None):
        return _CSIMounter(self, spec, pod, mount_backend,
                           store or self.store)

    def new_unmounter(self, volume_name, pod_uid, mount_backend):
        return _CSIUnmounter(self, volume_name, pod_uid, mount_backend,
                             self.store)

    def new_attacher(self) -> _CSIAttacher:
        return _CSIAttacher(self.store)

    def new_detacher(self) -> _CSIDetacher:
        return _CSIDetacher(self.store)


# -- dynamic provisioning (external-provisioner analog) -----------------------


class CSIProvisioner:
    """external-provisioner sidecar analog: claims annotated with
    volume.beta.kubernetes.io/storage-provisioner=<driver> get a PV
    provisioned via CreateVolume; deleting a bound claim whose PV was
    provisioned here deletes the backing volume (reclaim policy Delete,
    the provisioner default)."""

    def __init__(self, store, driver_name: str):
        self.store = store
        self.driver_name = driver_name

    def sync(self) -> int:
        from ..runtime.store import Conflict

        made = 0
        pvs = {pv.metadata.name: pv
               for pv in self.store.list("persistentvolumes")}
        claims = list(self.store.list("persistentvolumeclaims"))
        claimed = {pvc.spec.volume_name for pvc in claims
                   if pvc.spec.volume_name}
        # PVs provisioned for a claim the binder hasn't processed yet:
        # the claim references them by CONSTRUCTION (pvc-<uid> naming),
        # not yet by volume_name — reclaiming those would provision/
        # destroy flip-flop and could delete the backing volume out from
        # under a concurrent bind
        claimed |= {f"pvc-{pvc.metadata.uid}" for pvc in claims}
        for pvc in claims:
            ann = (pvc.metadata.annotations or {}).get(
                PROVISIONER_ANNOTATION)
            if ann != self.driver_name or pvc.spec.volume_name:
                continue
            pv_name = f"pvc-{pvc.metadata.uid}"
            if pv_name in pvs:
                continue  # provisioned, waiting for the binder
            capacity = int(pvc.spec.requests.get("storage", 0))
            client = _client_for(self.store, self.driver_name)
            resp = client.call("POST", "/controller/create-volume", {
                "name": pv_name, "capacity": capacity})
            pv = api.PersistentVolume(
                metadata=api.ObjectMeta(
                    name=pv_name, namespace="",
                    annotations={PROVISIONER_ANNOTATION: self.driver_name}),
                spec=api.PersistentVolumeSpec(
                    source_kind=CSI_SOURCE_KIND,
                    source_id=resp["volume_id"],
                    csi_driver=self.driver_name,
                    capacity={"storage": capacity},
                    storage_class_name=pvc.spec.storage_class_name))
            try:
                self.store.create("persistentvolumes", pv)
                made += 1
            except Conflict:
                pass
        # reclaim: a provisioned PV whose claim is gone -> DeleteVolume
        for pv in list(pvs.values()):
            if (pv.metadata.annotations or {}).get(
                    PROVISIONER_ANNOTATION) != self.driver_name:
                continue
            if pv.metadata.name in claimed:
                continue
            try:
                client = _client_for(self.store, self.driver_name)
                client.call("POST", "/controller/delete-volume",
                            {"volume_id": pv.spec.source_id})
                self.store.delete("persistentvolumes", "",
                                  pv.metadata.name)
            except (CSIError, KeyError):
                pass
        return made


def main(argv=None) -> int:
    """Standalone mock driver process: prints its endpoint, serves until
    killed. The out-of-process half of the CSI integration test."""
    import argparse
    import sys
    import time

    ap = argparse.ArgumentParser(prog="csi-mock-driver")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--name", default="mock.csi.k8s.io")
    args = ap.parse_args(argv)
    srv = CSIDriverServer(MockCSIDriver(args.name), port=args.port).start()
    print(srv.url, flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
