"""Kubelet volume manager: desired/actual state reconciliation.

Reference: pkg/kubelet/volumemanager/ — DesiredStateOfWorld (what pods
need, populator populator.go), ActualStateOfWorld (what's mounted,
cache/actual_state_of_world.go), and the reconciler
(reconciler/reconciler.go:147): unmount orphans, wait for attachable
volumes to appear in node.status (the attach/detach controller's write),
then mount. WaitForAttachAndMount (volume_manager.go:371) is the
kubelet syncPod gate.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from ..api import types as api
from .mount import InMemoryMount
from .plugin import Spec, VolumePluginMgr, default_plugin_mgr


class VolumeManager:
    def __init__(self, store, node_name: str,
                 plugin_mgr: Optional[VolumePluginMgr] = None,
                 mount_backend: Optional[InMemoryMount] = None):
        self.store = store
        self.node_name = node_name
        self.plugins = plugin_mgr or default_plugin_mgr(store)
        self.mount = mount_backend or InMemoryMount()
        self._lock = threading.Lock()
        # desired: (pod uid, volume name) -> (pod, Spec)
        self._desired: Dict[Tuple[str, str], Tuple[api.Pod, Spec]] = {}
        # reconcile is called from the per-pod readiness gate, so it must
        # be a no-op unless desired state or the node's attach set changed
        self._dirty = True
        self._last_attached: Set[str] = set()

    # -- desired state populator (populator.go) ---------------------------

    def _resolve_spec(self, pod: api.Pod, v: api.Volume) -> Optional[Spec]:
        if v.pvc_name:
            pvc = self.store.get("persistentvolumeclaims", pod.namespace,
                                 v.pvc_name)
            if pvc is None or not pvc.spec.volume_name:
                return None  # unbound claim: not mountable yet
            pv = self.store.get("persistentvolumes", "", pvc.spec.volume_name) \
                or self.store.get("persistentvolumes", "default",
                                  pvc.spec.volume_name)
            if pv is None:
                return None
            # keep the pod's volume alongside the PV: mounts are keyed by
            # the POD volume name (what containers reference), while
            # plugin matching falls through to the PV's source kind
            return Spec(volume=v, pv=pv)
        return Spec(volume=v)

    def _mountable(self, pod: api.Pod, v: api.Volume) -> Optional[Spec]:
        """Spec for a volume this manager can mount; None for unbound
        claims (gate stays closed) and for sources no plugin recognizes
        (ignored entirely, matching the pre-plugin-layer gate that only
        looked at PVC claims — a raise here would take down the whole
        kubelet sync loop)."""
        spec = self._resolve_spec(pod, v)
        if spec is None:
            return None
        try:
            self.plugins.find_plugin_by_spec(spec)
        except ValueError:
            return None
        return spec

    def note_pod(self, pod: api.Pod) -> None:
        """Add/refresh a pod's volumes in the desired state."""
        with self._lock:
            for v in pod.spec.volumes:
                spec = self._mountable(pod, v)
                key = (pod.metadata.uid, v.name)
                if spec is not None and key not in self._desired:
                    self._desired[key] = (pod, spec)
                    self._dirty = True

    def forget_pod(self, pod_uid: str) -> None:
        with self._lock:
            for key in [k for k in self._desired if k[0] == pod_uid]:
                del self._desired[key]
                self._dirty = True

    # -- reconciler (reconciler.go:147) -----------------------------------

    def reconcile(self, node: Optional[api.Node] = None) -> None:
        """Unmount what's mounted but not desired; mount what's desired,
        PV-backed attachable volumes only once the attach/detach
        controller has recorded them on the node. Inline attachable
        volumes (pod-spec GCEPD/EBS/...) mount without waiting: the
        controller only manages PV-backed attachments
        (controllers/attachdetach.py) — for inline sources the kubelet
        itself is the attacher, as when the reference runs with
        --enable-controller-attach-detach=false."""
        attached = set(node.status.volumes_attached) if node else set()
        with self._lock:
            if not self._dirty and attached == self._last_attached:
                return
            self._dirty = False
            self._last_attached = attached
            desired = dict(self._desired)
        mounted: Set[Tuple[str, str]] = {
            (m.pod_uid, m.volume_name) for m in self.mount.list()}
        for pod_uid, vname in mounted - set(desired):
            # orphaned mount: the pod is gone (reconciler.go:166).
            # Tear down through the owning plugin — out-of-process
            # plugins (CSI NodeUnpublish) must observe the unmount, not
            # just the mount table
            rec = self.mount.get(pod_uid, vname)
            plugin = (self.plugins.find_plugin_by_name(rec.kind)
                      if rec is not None else None)
            if plugin is not None:
                try:
                    plugin.new_unmounter(vname, pod_uid,
                                         self.mount).tear_down()
                except Exception:
                    # the mount record survives a failed out-of-process
                    # teardown so the next pass retries NodeUnpublish —
                    # dropping it would leak the driver's publish state
                    self._dirty = True
            else:
                self.mount.unmount(pod_uid, vname)
        still_waiting = False
        for (pod_uid, vname), (pod, spec) in desired.items():
            if (pod_uid, vname) in mounted:
                continue
            plugin = self.plugins.find_plugin_by_spec(spec)
            if plugin.attachable and spec.pv is not None:
                if spec.pv.metadata.name not in attached:
                    still_waiting = True
                    continue  # waiting on the attach/detach controller
            try:
                plugin.new_mounter(spec, pod, self.mount, self.store,
                                   mgr=self.plugins).set_up()
            except Exception as e:
                # an out-of-process mount (CSI NodePublish) can fail or
                # time out; the pod stays gated and the mount retries
                # next pass — a raise here would take down the whole
                # kubelet sync loop and (worse) leave _dirty cleared,
                # wedging the manager permanently
                import sys

                print(f"# volume mount {vname!r} for pod {pod_uid} "
                      f"failed: {e}", file=sys.stderr)
                still_waiting = True
        if still_waiting:
            self._dirty = True  # retry next pass even if nothing changes

    # -- kubelet gate (volume_manager.go:371) ------------------------------

    def volumes_ready(self, pod: api.Pod,
                      node: Optional[api.Node] = None) -> bool:
        """All of the pod's volumes mounted? (WaitForAttachAndMount, minus
        the blocking — the kubelet sync loop polls.) Runs one reconcile
        pass first so ready pods don't wait an extra sync."""
        self.note_pod(pod)
        self.reconcile(node)  # no-op unless desired/attach state changed
        for v in pod.spec.volumes:
            if v.pvc_name:
                pass  # claim-backed: must mount (gate stays closed if unbound)
            elif self._mountable(pod, v) is None:
                continue  # unrecognized source: never gates the pod
            if self.mount.get(pod.metadata.uid, v.name) is None:
                return False
        return True

    def mounted_payload(self, pod: api.Pod, volume_name: str):
        m = self.mount.get(pod.metadata.uid, volume_name)
        return None if m is None else m.payload
