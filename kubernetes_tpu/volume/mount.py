"""Mount backend — pkg/util/mount analog.

The reference's mount.Interface wraps the real mount(2)/umount(2)
syscalls; tests run against FakeMounter's in-memory mount table
(util/mount/fake.go). This framework's node model has no real
filesystems, so the in-memory table IS the dataplane: a mount point
per (pod uid, volume name) carrying the materialized payload for
API-backed volumes (configmap/secret/downward), which is what the pod's
containers would read.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass
class MountPoint:
    pod_uid: str
    volume_name: str
    kind: str
    payload: Dict[str, str] = field(default_factory=dict)
    read_only: bool = False


class InMemoryMount:
    def __init__(self):
        self._lock = threading.Lock()
        self._table: Dict[Tuple[str, str], MountPoint] = {}
        self.mount_count = 0
        self.unmount_count = 0

    def mount(self, pod_uid: str, volume_name: str, kind: str,
              payload=None, read_only: bool = False) -> None:
        with self._lock:
            self._table[(pod_uid, volume_name)] = MountPoint(
                pod_uid=pod_uid, volume_name=volume_name, kind=kind,
                payload=dict(payload or {}), read_only=read_only)
            self.mount_count += 1

    def unmount(self, pod_uid: str, volume_name: str) -> None:
        with self._lock:
            if self._table.pop((pod_uid, volume_name), None) is not None:
                self.unmount_count += 1

    def get(self, pod_uid: str, volume_name: str):
        with self._lock:
            return self._table.get((pod_uid, volume_name))

    def list(self) -> List[MountPoint]:
        with self._lock:
            return list(self._table.values())

    def pod_mounts(self, pod_uid: str) -> List[MountPoint]:
        with self._lock:
            return [m for (uid, _), m in self._table.items()
                    if uid == pod_uid]
