"""Volume plugin framework.

Reference: pkg/volume/plugins.go (VolumePlugin interface :87,
VolumePluginMgr :318 FindPluginBySpec) and pkg/volume/volume.go
(Mounter/Unmounter :91-123, Attacher/Detacher in attacher.go). The
reference resolves a pod volume to exactly one plugin by probing every
registered plugin's CanSupport; attachable plugins additionally
participate in the attach/detach controller's flow before kubelet
mounts. The same seams are kept here so the kubelet volume manager
(manager.py), the attach/detach controller, and the scheduler's volume
predicates all speak plugin language rather than switch on source kinds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from ..api import types as api


@dataclass
class Spec:
    """What the reference calls volume.Spec: either a pod-inline volume
    or a PersistentVolume (plugins.go:58)."""

    volume: Optional[api.Volume] = None
    pv: Optional[api.PersistentVolume] = None

    @property
    def name(self) -> str:
        if self.volume is not None:
            return self.volume.name
        return self.pv.metadata.name if self.pv is not None else ""

    @property
    def source_kind(self) -> str:
        if self.volume is not None and self.volume.source_kind:
            return self.volume.source_kind
        if self.pv is not None:
            return self.pv.spec.source_kind
        return ""


class Mounter:
    """volume.go:100 Mounter — SetUp makes the volume available at the
    pod's mount point."""

    def __init__(self, plugin: "VolumePlugin", spec: Spec, pod: api.Pod,
                 mount_backend, store=None):
        self.plugin = plugin
        self.spec = spec
        self.pod = pod
        self.mount = mount_backend
        self.store = store

    def payload(self) -> Dict[str, str]:
        """Data materialized into the mount (configmap/secret/downward
        content; empty for block/fs volumes)."""
        return {}

    def set_up(self) -> None:
        self.mount.mount(self.pod.metadata.uid, self.spec.name,
                         kind=self.plugin.name, payload=self.payload(),
                         read_only=(self.spec.volume.read_only
                                    if self.spec.volume else False))


class Unmounter:
    def __init__(self, plugin: "VolumePlugin", volume_name: str,
                 pod_uid: str, mount_backend):
        self.plugin = plugin
        self.volume_name = volume_name
        self.pod_uid = pod_uid
        self.mount = mount_backend

    def tear_down(self) -> None:
        self.mount.unmount(self.pod_uid, self.volume_name)


class Attacher:
    """attacher.go Attacher: Attach returns once the volume is reachable
    from the node; the controller records it in node.status."""

    def attach(self, spec: Spec, node_name: str) -> str:
        raise NotImplementedError

    def wait_for_attach(self, spec: Spec, node) -> bool:
        attached = set(node.status.volumes_attached)
        return (spec.pv is not None
                and spec.pv.metadata.name in attached)


class Detacher:
    def detach(self, volume_name: str, node_name: str) -> None:
        raise NotImplementedError


class VolumePlugin:
    """plugins.go:87 VolumePlugin."""

    name = "abstract"
    attachable = False

    def can_support(self, spec: Spec) -> bool:
        raise NotImplementedError

    def new_mounter(self, spec: Spec, pod: api.Pod, mount_backend,
                    store=None, mgr: "Optional[VolumePluginMgr]" = None
                    ) -> Mounter:
        """mgr: the configured plugin manager, for plugins that resolve
        sub-sources (projected) — they must consult the SAME roster the
        volume manager was built with, not a fresh default."""
        return Mounter(self, spec, pod, mount_backend, store)

    def new_unmounter(self, volume_name: str, pod_uid: str,
                      mount_backend) -> Unmounter:
        return Unmounter(self, volume_name, pod_uid, mount_backend)


class GenericPVPlugin(VolumePlugin):
    """Fallback for PersistentVolumes without a recognized source kind
    (this model allows source-less PVs; the reference would reject them
    at validation). Attachable: the attach/detach controller manages
    every PV-backed volume here, so the kubelet still gates on
    node.status.volumesAttached."""

    name = "kubernetes.io/generic-pv"
    attachable = True

    def can_support(self, spec: Spec) -> bool:
        return False  # fallback only, never matched in the scan


class VolumePluginMgr:
    """plugins.go:318 — exactly-one-plugin resolution."""

    def __init__(self, plugins: List[VolumePlugin]):
        self.plugins = list(plugins)
        self._generic_pv = GenericPVPlugin()

    def find_plugin_by_spec(self, spec: Spec) -> VolumePlugin:
        matches = [p for p in self.plugins if p.can_support(spec)]
        if not matches:
            if spec.pv is not None:
                return self._generic_pv
            raise ValueError(f"no volume plugin supports {spec.name!r}")
        if len(matches) > 1:
            names = [p.name for p in matches]
            raise ValueError(f"multiple plugins match {spec.name!r}: {names}")
        return matches[0]

    def find_attachable_plugin_by_spec(self, spec: Spec
                                       ) -> Optional[VolumePlugin]:
        try:
            p = self.find_plugin_by_spec(spec)
        except ValueError:
            return None
        return p if p.attachable else None

    def find_plugin_by_name(self, name: str) -> Optional[VolumePlugin]:
        """Resolve a plugin from a mount record's kind — the teardown
        direction (the reference resolves the same way from the mount
        dir's vol_data.json)."""
        return next((p for p in self.plugins if p.name == name), None)


def default_plugin_mgr(store=None) -> VolumePluginMgr:
    """ProbeVolumePlugins analog (cmd/kube-controller-manager/app/
    plugins.go:56 + pkg/kubelet/volume_host.go): the in-tree roster plus
    the CSI shim (which needs the store to resolve driver endpoints)."""
    from . import plugins as pl
    from .csi import CSIPlugin

    return VolumePluginMgr([
        pl.EmptyDirPlugin(), pl.HostPathPlugin(), pl.ConfigMapPlugin(),
        pl.SecretPlugin(), pl.DownwardAPIPlugin(), pl.ProjectedPlugin(),
        pl.NFSPlugin(), pl.LocalPlugin(),
        pl.PDPlugin("GCEPersistentDisk"),
        pl.PDPlugin("AWSElasticBlockStore"),
        pl.PDPlugin("AzureDisk"), pl.PDPlugin("RBD"), pl.PDPlugin("ISCSI"),
        CSIPlugin(store),
    ])
