"""In-tree volume plugins.

Reference: pkg/volume/{empty_dir,host_path,configmap,secret,
downward_api,projected,nfs,local,gce_pd,aws_ebs,azure_dd,rbd,iscsi}/ —
each directory is one plugin implementing CanSupport + mounters. The
API-backed plugins (configmap/secret/downward/projected) materialize
store content into the mount payload, re-resolved at every SetUp the
same way the reference re-fetches on remount (configmap.go:191).
"""

from __future__ import annotations

from typing import Dict

from ..api import types as api
from .plugin import Attacher, Detacher, Mounter, Spec, VolumePlugin

PD_KINDS = ("GCEPersistentDisk", "AWSElasticBlockStore", "AzureDisk",
            "RBD", "ISCSI")


class EmptyDirPlugin(VolumePlugin):
    name = "kubernetes.io/empty-dir"

    def can_support(self, spec: Spec) -> bool:
        return spec.volume is not None and spec.volume.empty_dir


class HostPathPlugin(VolumePlugin):
    name = "kubernetes.io/host-path"

    def can_support(self, spec: Spec) -> bool:
        return spec.volume is not None and bool(spec.volume.host_path)

    def new_mounter(self, spec, pod, mount_backend, store=None,
                    mgr=None):
        class _M(Mounter):
            def payload(self):
                return {"hostPath": self.spec.volume.host_path}

        return _M(self, spec, pod, mount_backend, store)


class _APIBackedMounter(Mounter):
    kind = ""
    field = ""

    def payload(self) -> Dict[str, str]:
        name = getattr(self.spec.volume, self.field)
        obj = (self.store.get(self.kind, self.pod.namespace, name)
               if self.store is not None else None)
        if obj is None:
            # reference: missing optional sources mount empty; missing
            # required ones error — modeled as empty + marker
            return {"__missing__": name}
        return dict(obj.data)


class ConfigMapPlugin(VolumePlugin):
    name = "kubernetes.io/configmap"

    def can_support(self, spec: Spec) -> bool:
        return spec.volume is not None and bool(spec.volume.config_map)

    def new_mounter(self, spec, pod, mount_backend, store=None,
                    mgr=None):
        class _M(_APIBackedMounter):
            kind, field = "configmaps", "config_map"

        return _M(self, spec, pod, mount_backend, store)


class SecretPlugin(VolumePlugin):
    name = "kubernetes.io/secret"

    def can_support(self, spec: Spec) -> bool:
        return spec.volume is not None and bool(spec.volume.secret)

    def new_mounter(self, spec, pod, mount_backend, store=None,
                    mgr=None):
        class _M(_APIBackedMounter):
            kind, field = "secrets", "secret"

        return _M(self, spec, pod, mount_backend, store)


class DownwardAPIPlugin(VolumePlugin):
    name = "kubernetes.io/downward-api"

    def can_support(self, spec: Spec) -> bool:
        return spec.volume is not None and bool(spec.volume.downward_api)

    def new_mounter(self, spec, pod, mount_backend, store=None,
                    mgr=None):
        class _M(Mounter):
            def payload(self):
                out = {}
                meta = self.pod.metadata
                fields = {
                    "metadata.name": meta.name,
                    "metadata.namespace": meta.namespace,
                    "metadata.uid": meta.uid,
                    "spec.nodeName": self.pod.spec.node_name,
                }
                for path, ref in self.spec.volume.downward_api.items():
                    out[path] = fields.get(ref, "")
                return out

        return _M(self, spec, pod, mount_backend, store)


class ProjectedPlugin(VolumePlugin):
    """projected/projected.go — one mount fed by several sub-sources."""

    name = "kubernetes.io/projected"
    _default_mgr = None

    def can_support(self, spec: Spec) -> bool:
        return spec.volume is not None and bool(spec.volume.projected)

    def new_mounter(self, spec, pod, mount_backend, store=None,
                    mgr=None):
        outer = self
        if mgr is None:
            # fallback for direct plugin use; cached, not per-SetUp
            from .plugin import default_plugin_mgr

            if ProjectedPlugin._default_mgr is None:
                ProjectedPlugin._default_mgr = default_plugin_mgr()
            mgr = ProjectedPlugin._default_mgr

        class _M(Mounter):
            def payload(self):
                merged: Dict[str, str] = {}
                for sub in self.spec.volume.projected:
                    sub_spec = Spec(volume=sub)
                    p = mgr.find_plugin_by_spec(sub_spec)
                    if p.name == outer.name:
                        continue  # no recursive projection
                    m = p.new_mounter(sub_spec, self.pod, self.mount,
                                      self.store, mgr=mgr)
                    merged.update(m.payload())
                return merged

        return _M(self, spec, pod, mount_backend, store)


class NFSPlugin(VolumePlugin):
    name = "kubernetes.io/nfs"

    def can_support(self, spec: Spec) -> bool:
        return spec.volume is not None and bool(spec.volume.nfs_server)

    def new_mounter(self, spec, pod, mount_backend, store=None,
                    mgr=None):
        class _M(Mounter):
            def payload(self):
                v = self.spec.volume
                return {"server": v.nfs_server, "path": v.nfs_path}

        return _M(self, spec, pod, mount_backend, store)


class LocalPlugin(VolumePlugin):
    name = "kubernetes.io/local-volume"

    def can_support(self, spec: Spec) -> bool:
        return (spec.pv is not None
                and spec.pv.spec.source_kind == "Local")


class _PDAttacher(Attacher):
    def __init__(self, registry):
        self.registry = registry  # (volume, node) attachment set

    def attach(self, spec: Spec, node_name: str) -> str:
        self.registry.add((spec.name, node_name))
        return spec.name


class _PDDetacher(Detacher):
    def __init__(self, registry):
        self.registry = registry

    def detach(self, volume_name: str, node_name: str) -> None:
        self.registry.discard((volume_name, node_name))


class PDPlugin(VolumePlugin):
    """One attachable block-device plugin per cloud disk family
    (gce_pd/aws_ebs/azure_dd/rbd/iscsi directories in the reference;
    the per-cloud differences are provider API calls, which live behind
    the cloud seam here)."""

    attachable = True

    def __init__(self, kind: str):
        assert kind in PD_KINDS, kind
        self.kind = kind
        self.name = f"kubernetes.io/{kind.lower()}"
        self.attachments = set()

    def can_support(self, spec: Spec) -> bool:
        return spec.source_kind == self.kind

    def new_attacher(self) -> _PDAttacher:
        return _PDAttacher(self.attachments)

    def new_detacher(self) -> _PDDetacher:
        return _PDDetacher(self.attachments)
