// Versioned in-memory KV store with a bounded watch ring — the native
// storage engine behind runtime/nativestore.py.
//
// Architectural role: the reference's L0 is a *native external store*
// (etcd v3.2.18, a Go binary spoken to over gRPC — WORKSPACE:23,
// staging/src/k8s.io/apiserver/pkg/storage/etcd3/). This library is the
// framework's equivalent: object bytes live behind a C ABI, every
// mutation gets a monotonically increasing revision (etcd ModRevision),
// compare-and-swap updates (etcd3/store.go:262 GuaranteedUpdate txn),
// and watchers replay history from a revision out of a bounded window
// (mvcc watchable store; "compacted" history -> error 3, the 410 Gone
// analog).
//
// The C ABI is deliberately narrow (new/free, put, del, get, list,
// poll, rev) so it binds with ctypes — no pybind11 dependency.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Entry {
    std::string value;
    int64_t mod_rev;
};

struct Event {
    int64_t rev;
    bool is_delete;
    bool is_create;
    std::string key;
    std::string value;  // new value for PUT, last value for DELETE
};

struct Store {
    std::mutex mu;
    std::map<std::string, Entry> data;  // ordered: prefix scans are ranges
    std::deque<Event> ring;
    size_t ring_capacity;
    int64_t rev = 0;
};

char* dup_buffer(const std::string& s) {
    char* out = static_cast<char*>(std::malloc(s.size() + 1));
    std::memcpy(out, s.data(), s.size());
    out[s.size()] = '\0';
    return out;
}

void push_event(Store* st, Event ev) {
    st->ring.push_back(std::move(ev));
    while (st->ring.size() > st->ring_capacity) st->ring.pop_front();
}

// JSON string escaping for the poll/list framing (values are already
// JSON documents; keys need escaping).
void append_json_string(std::string& out, const std::string& s) {
    out.push_back('"');
    for (char c : s) {
        switch (c) {
            case '"': out += "\\\""; break;
            case '\\': out += "\\\\"; break;
            case '\n': out += "\\n"; break;
            case '\t': out += "\\t"; break;
            case '\r': out += "\\r"; break;
            default:
                if (static_cast<unsigned char>(c) < 0x20) {
                    char buf[8];
                    std::snprintf(buf, sizeof buf, "\\u%04x", c);
                    out += buf;
                } else {
                    out.push_back(c);
                }
        }
    }
    out.push_back('"');
}

}  // namespace

extern "C" {

// error codes
enum { KV_OK = 0, KV_CONFLICT = 1, KV_NOT_FOUND = 2, KV_COMPACTED = 3 };

void* kv_new(int ring_capacity) {
    Store* st = new Store();
    st->ring_capacity = ring_capacity > 0 ? ring_capacity : 4096;
    return st;
}

void kv_free(void* h) { delete static_cast<Store*>(h); }

void kv_buf_free(char* buf) { std::free(buf); }

int64_t kv_rev(void* h) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    return st->rev;
}

// expect_rev semantics (etcd txn guards):
//   -1 : unconditional upsert
//    0 : create — key must not exist (If ModRevision == 0)
//   >0 : update — key's mod_rev must equal expect_rev (CAS)
int64_t kv_put(void* h, const char* key, const char* value,
               int64_t expect_rev, int* err) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    auto it = st->data.find(key);
    if (expect_rev == 0 && it != st->data.end()) {
        *err = KV_CONFLICT;
        return 0;
    }
    if (expect_rev > 0) {
        if (it == st->data.end()) {
            *err = KV_NOT_FOUND;
            return 0;
        }
        if (it->second.mod_rev != expect_rev) {
            *err = KV_CONFLICT;
            return 0;
        }
    }
    bool created = (it == st->data.end());
    st->rev += 1;
    st->data[key] = Entry{value, st->rev};
    push_event(st, Event{st->rev, false, created, key, value});
    *err = KV_OK;
    return st->rev;
}

int64_t kv_delete(void* h, const char* key, int* err) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    auto it = st->data.find(key);
    if (it == st->data.end()) {
        *err = KV_NOT_FOUND;
        return 0;
    }
    st->rev += 1;
    push_event(st, Event{st->rev, true, false, key,
                         std::move(it->second.value)});
    st->data.erase(it);
    *err = KV_OK;
    return st->rev;
}

// Returns malloc'd value or NULL; *mod_rev gets the entry's revision.
char* kv_get(void* h, const char* key, int64_t* mod_rev) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    auto it = st->data.find(key);
    if (it == st->data.end()) return nullptr;
    *mod_rev = it->second.mod_rev;
    return dup_buffer(it->second.value);
}

// Prefix scan -> JSON lines `{"key":...,"rev":N,"value":<doc>}`.
// *rev gets the store revision of the snapshot (list resourceVersion).
char* kv_list(void* h, const char* prefix, int64_t* rev) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    *rev = st->rev;
    std::string out;
    std::string pfx(prefix);
    for (auto it = st->data.lower_bound(pfx);
         it != st->data.end() && it->first.compare(0, pfx.size(), pfx) == 0;
         ++it) {
        out += "{\"key\":";
        append_json_string(out, it->first);
        out += ",\"rev\":" + std::to_string(it->second.mod_rev);
        out += ",\"value\":" + it->second.value + "}\n";
    }
    return dup_buffer(out);
}

// Events with rev > since_rev as JSON lines
// `{"rev":N,"type":"PUT"|"DELETE","create":0|1,"key":...,"value":<doc>}`.
// err: KV_COMPACTED when since_rev predates the ring window.
// *next_rev gets the last delivered (or current) revision.
char* kv_poll(void* h, int64_t since_rev, int max_events,
              int64_t* next_rev, int* err) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    *err = KV_OK;
    *next_rev = since_rev;
    if (!st->ring.empty() && since_rev + 1 < st->ring.front().rev &&
        since_rev < st->rev) {
        // window check: only events newer than the ring start are
        // replayable; an older horizon means history was dropped
        if (since_rev < st->ring.front().rev - 1) {
            *err = KV_COMPACTED;
            return nullptr;
        }
    }
    std::string out;
    int n = 0;
    for (const Event& ev : st->ring) {
        if (ev.rev <= since_rev) continue;
        if (max_events > 0 && n >= max_events) break;
        out += "{\"rev\":" + std::to_string(ev.rev);
        out += ",\"type\":\"";
        out += ev.is_delete ? "DELETE" : "PUT";
        out += "\",\"create\":";
        out += ev.is_create ? "1" : "0";
        out += ",\"key\":";
        append_json_string(out, ev.key);
        out += ",\"value\":" + ev.value + "}\n";
        *next_rev = ev.rev;
        ++n;
    }
    return dup_buffer(out);
}

int64_t kv_count(void* h, const char* prefix) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    std::string pfx(prefix);
    int64_t n = 0;
    for (auto it = st->data.lower_bound(pfx);
         it != st->data.end() && it->first.compare(0, pfx.size(), pfx) == 0;
         ++it)
        ++n;
    return n;
}

}  // extern "C"
