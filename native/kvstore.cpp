// Versioned KV store with a bounded watch ring and optional durability
// (write-ahead log + snapshot) — the native storage engine behind
// runtime/nativestore.py.
//
// Architectural role: the reference's L0 is a *native external store*
// (etcd v3.2.18, a Go binary spoken to over gRPC — WORKSPACE:23,
// staging/src/k8s.io/apiserver/pkg/storage/etcd3/). This library is the
// framework's equivalent: object bytes live behind a C ABI, every
// mutation gets a monotonically increasing revision (etcd ModRevision),
// compare-and-swap updates (etcd3/store.go:262 GuaranteedUpdate txn),
// and watchers replay history from a revision out of a bounded window
// (mvcc watchable store; "compacted" history -> error 3, the 410 Gone
// analog).
//
// Durability (etcd's WAL + snapshot model, wal/wal.go + snap/): opening
// with kv_open(dir) replays <dir>/snapshot then <dir>/wal; every
// mutation appends a length-framed, checksummed WAL record and
// fflush()es it (crash-of-process safe; kv_sync() adds fdatasync for
// power-loss durability). When the WAL exceeds a record threshold the
// store writes a fresh snapshot (atomic tmp+rename) and truncates the
// WAL — compaction. After reopen the watch ring starts empty at the
// recovered revision: pollers resuming from an older revision get
// KV_COMPACTED and must relist, exactly the 410-Gone contract.
//
// The C ABI is deliberately narrow (new/open/free, put, del, get, list,
// poll, rev, snapshot, sync) so it binds with ctypes — no pybind11
// dependency.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#ifndef _WIN32
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>
#endif

namespace {

struct Entry {
    std::string value;
    int64_t mod_rev;
};

struct Event {
    int64_t rev;
    bool is_delete;
    bool is_create;
    std::string key;
    std::string value;  // new value for PUT, last value for DELETE
};

struct Store {
    std::mutex mu;
    std::map<std::string, Entry> data;  // ordered: prefix scans are ranges
    std::deque<Event> ring;
    size_t ring_capacity;
    int64_t rev = 0;
    // events with rev <= compacted_rev are no longer replayable (ring
    // overflow or restart); poll() from before this horizon -> KV_COMPACTED
    int64_t compacted_rev = 0;
    // durability (empty dir -> memory-only)
    std::string dir;
    std::FILE* wal = nullptr;
    int64_t wal_records = 0;
    int64_t snapshot_every = 10000;  // WAL records between snapshots
    bool snap_in_progress = false;   // one background compaction at a time
    // latched on any WAL append failure: acknowledging a write whose WAL
    // record did not land would break the durability contract, so all
    // further mutations fail with KV_IO until reopen
    bool io_error = false;
};

// ---- WAL / snapshot encoding ------------------------------------------------
//
// WAL record:  u32 len | u8 op(0=put,1=del) | i64 rev | u32 klen |
//              key bytes | value bytes | u32 check(len ^ 0xA5A5A5A5)
// A torn tail (crash mid-append) fails the length/check validation and
// replay stops there — everything before it is intact.
// Snapshot:    u64 magic | i64 rev | repeated { u32 klen | u32 vlen |
//              i64 mod_rev | key | value }

constexpr uint64_t kSnapMagic = 0x6b76736e61703031ULL;  // "kvsnap01"
constexpr uint32_t kWalCheck = 0xA5A5A5A5u;

bool write_all(std::FILE* f, const void* p, size_t n) {
    return std::fwrite(p, 1, n, f) == n;
}

bool read_all(std::FILE* f, void* p, size_t n) {
    return std::fread(p, 1, n, f) == n;
}

bool append_wal_record(Store* st, bool is_delete, int64_t rev,
                       const std::string& key, const std::string& value) {
    if (!st->wal) return true;
    uint8_t op = is_delete ? 1 : 0;
    uint32_t klen = static_cast<uint32_t>(key.size());
    uint32_t len = static_cast<uint32_t>(1 + 8 + 4 + key.size() + value.size());
    uint32_t check = len ^ kWalCheck;
    bool ok = write_all(st->wal, &len, 4) && write_all(st->wal, &op, 1) &&
              write_all(st->wal, &rev, 8) && write_all(st->wal, &klen, 4) &&
              write_all(st->wal, key.data(), key.size()) &&
              write_all(st->wal, value.data(), value.size()) &&
              write_all(st->wal, &check, 4);
    if (ok && std::fflush(st->wal) != 0) ok = false;
    if (ok) st->wal_records += 1;
    return ok;
}

void fsync_dir(const std::string& dir) {
#ifndef _WIN32
    // a rename is only durable once the directory entry is on disk
    int fd = ::open(dir.c_str(), O_RDONLY | O_DIRECTORY);
    if (fd >= 0) {
        fsync(fd);
        ::close(fd);
    }
#else
    (void)dir;
#endif
}

bool file_exists(const std::string& p) {
#ifndef _WIN32
    struct stat sb;
    return ::stat(p.c_str(), &sb) == 0;
#else
    std::FILE* f = std::fopen(p.c_str(), "rb");
    if (f) std::fclose(f);
    return f != nullptr;
#endif
}

// Serialize `data` at `rev` into <dir>/snapshot atomically (tmp + fsync +
// rename + dir fsync). Pure function of its arguments — callable without
// the store mutex.
bool write_snapshot_file(const std::string& dir,
                         const std::map<std::string, Entry>& data,
                         int64_t rev) {
    std::string tmp = dir + "/snapshot.tmp";
    std::string fin = dir + "/snapshot";
    std::FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) return false;
    bool ok = write_all(f, &kSnapMagic, 8) && write_all(f, &rev, 8);
    for (auto it = data.begin(); ok && it != data.end(); ++it) {
        uint32_t klen = static_cast<uint32_t>(it->first.size());
        uint32_t vlen = static_cast<uint32_t>(it->second.value.size());
        ok = write_all(f, &klen, 4) && write_all(f, &vlen, 4) &&
             write_all(f, &it->second.mod_rev, 8) &&
             write_all(f, it->first.data(), klen) &&
             write_all(f, it->second.value.data(), vlen);
    }
    if (ok) {
        std::fflush(f);
#ifndef _WIN32
        fsync(fileno(f));
#endif
    }
    std::fclose(f);
    if (!ok) { std::remove(tmp.c_str()); return false; }
    if (std::rename(tmp.c_str(), fin.c_str()) != 0) return false;
    fsync_dir(dir);
    return true;
}

// Compaction in two halves so the expensive file IO never holds st->mu:
// begin (mu held) rotates the WAL to wal.old and copies the state;
// finish (no mu) writes the snapshot and removes wal.old. Recovery
// replays snapshot -> wal.old -> wal, so a crash at ANY point between
// the halves loses nothing (record revs <= the snapshot rev are skipped).
struct SnapJob {
    std::map<std::string, Entry> data;
    int64_t rev = 0;
};

bool begin_snapshot_locked(Store* st, SnapJob* job) {
    if (st->dir.empty() || st->snap_in_progress || !st->wal || st->io_error)
        return false;
    std::string w = st->dir + "/wal", wo = st->dir + "/wal.old";
    if (file_exists(wo)) return false;  // a failed finish left it; keep it
    std::fflush(st->wal);
    std::fclose(st->wal);
    st->wal = nullptr;
    if (std::rename(w.c_str(), wo.c_str()) != 0) {
        st->wal = std::fopen(w.c_str(), "ab");
        if (!st->wal) st->io_error = true;
        return false;
    }
    st->wal = std::fopen(w.c_str(), "wb");
    if (!st->wal) {
        st->io_error = true;
        return false;
    }
    st->wal_records = 0;
    job->data = st->data;
    job->rev = st->rev;
    st->snap_in_progress = true;
    return true;
}

bool finish_snapshot(Store* st, SnapJob* job) {
    bool ok = write_snapshot_file(st->dir, job->data, job->rev);
    if (ok) std::remove((st->dir + "/wal.old").c_str());
    std::lock_guard<std::mutex> lock(st->mu);
    st->snap_in_progress = false;
    // on failure wal.old stays: recovery still replays it, and the next
    // begin_snapshot_locked is skipped until it's consolidated at reopen
    return ok;
}

bool load_snapshot(Store* st) {
    std::FILE* f = std::fopen((st->dir + "/snapshot").c_str(), "rb");
    if (!f) return true;  // no snapshot yet
    uint64_t magic = 0;
    int64_t rev = 0;
    if (!read_all(f, &magic, 8) || magic != kSnapMagic ||
        !read_all(f, &rev, 8)) {
        std::fclose(f);
        return false;
    }
    st->rev = rev;
    while (true) {
        uint32_t klen = 0, vlen = 0;
        int64_t mod_rev = 0;
        if (!read_all(f, &klen, 4)) break;  // clean EOF
        if (!read_all(f, &vlen, 4) || !read_all(f, &mod_rev, 8)) break;
        std::string key(klen, '\0'), value(vlen, '\0');
        if (!read_all(f, key.data(), klen) || !read_all(f, value.data(), vlen))
            break;
        st->data[std::move(key)] = Entry{std::move(value), mod_rev};
    }
    std::fclose(f);
    return true;
}

// Replay one WAL file; records at/below the recovered revision are
// skipped. Returns the byte offset of the last VALID record's end — a
// torn tail after it must be truncated away before appending, or records
// written after the tear would be unreachable on the next replay.
long replay_wal_file(Store* st, const std::string& path) {
    std::FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) return 0;
    long valid_end = 0;
    while (true) {
        uint32_t len = 0;
        if (!read_all(f, &len, 4)) break;
        if (len < 13 || len > (1u << 30)) break;  // corrupt/torn tail
        std::vector<char> buf(len);
        if (!read_all(f, buf.data(), len)) break;
        uint32_t check = 0;
        if (!read_all(f, &check, 4) || check != (len ^ kWalCheck)) break;
        uint8_t op = static_cast<uint8_t>(buf[0]);
        int64_t rev;
        std::memcpy(&rev, buf.data() + 1, 8);
        uint32_t klen;
        std::memcpy(&klen, buf.data() + 9, 4);
        if (13 + klen > len) break;
        valid_end = std::ftell(f);
        std::string key(buf.data() + 13, klen);
        std::string value(buf.data() + 13 + klen, len - 13 - klen);
        if (rev <= st->rev) continue;  // already in snapshot
        st->rev = rev;
        if (op == 1) {
            st->data.erase(key);
        } else {
            st->data[std::move(key)] = Entry{std::move(value), rev};
        }
        st->wal_records += 1;
    }
    std::fclose(f);
    return valid_end;
}

char* dup_buffer(const std::string& s) {
    char* out = static_cast<char*>(std::malloc(s.size() + 1));
    std::memcpy(out, s.data(), s.size());
    out[s.size()] = '\0';
    return out;
}

void push_event(Store* st, Event ev) {
    st->ring.push_back(std::move(ev));
    while (st->ring.size() > st->ring_capacity) {
        st->compacted_rev = st->ring.front().rev;
        st->ring.pop_front();
    }
}

// JSON string escaping for the poll/list framing (values are already
// JSON documents; keys need escaping).
void append_json_string(std::string& out, const std::string& s) {
    out.push_back('"');
    for (char c : s) {
        switch (c) {
            case '"': out += "\\\""; break;
            case '\\': out += "\\\\"; break;
            case '\n': out += "\\n"; break;
            case '\t': out += "\\t"; break;
            case '\r': out += "\\r"; break;
            default:
                if (static_cast<unsigned char>(c) < 0x20) {
                    char buf[8];
                    std::snprintf(buf, sizeof buf, "\\u%04x", c);
                    out += buf;
                } else {
                    out.push_back(c);
                }
        }
    }
    out.push_back('"');
}

}  // namespace

extern "C" {

// error codes
enum { KV_OK = 0, KV_CONFLICT = 1, KV_NOT_FOUND = 2, KV_COMPACTED = 3,
       KV_IO = 4 };

void* kv_new(int ring_capacity) {
    Store* st = new Store();
    st->ring_capacity = ring_capacity > 0 ? ring_capacity : 4096;
    return st;
}

// Open (or create) a durable store rooted at dir: replay snapshot + WAL,
// then append subsequent mutations to the WAL. snapshot_every <= 0 keeps
// the default compaction threshold. Returns NULL on unrecoverable IO.
void* kv_open(const char* dir, int ring_capacity, int64_t snapshot_every) {
    Store* st = static_cast<Store*>(kv_new(ring_capacity));
    st->dir = dir ? dir : "";
    if (st->dir.empty()) return st;
    if (snapshot_every > 0) st->snapshot_every = snapshot_every;
    if (!load_snapshot(st)) { delete st; return nullptr; }
    std::string w = st->dir + "/wal", wo = st->dir + "/wal.old";
    bool had_old = file_exists(wo);
    if (had_old) replay_wal_file(st, wo);  // interrupted compaction
    long valid_end = replay_wal_file(st, w);
    if (had_old) {
        // consolidate: the full recovered state replaces snapshot +
        // wal.old + wal, so the stale segment never shadows new appends
        if (!write_snapshot_file(st->dir, st->data, st->rev)) {
            delete st;
            return nullptr;
        }
        std::remove(wo.c_str());
        st->wal = std::fopen(w.c_str(), "wb");
        st->wal_records = 0;
    } else {
#ifndef _WIN32
        // chop any torn tail so post-recovery appends stay reachable
        if (file_exists(w)) ::truncate(w.c_str(), valid_end);
#endif
        st->wal = std::fopen(w.c_str(), "ab");
    }
    // nothing older than the recovered revision is replayable: watchers
    // resuming from before it must relist (410 Gone analog)
    st->compacted_rev = st->rev;
    if (!st->wal) { delete st; return nullptr; }
    return st;
}

void kv_free(void* h) {
    Store* st = static_cast<Store*>(h);
    if (st->wal) std::fclose(st->wal);
    delete st;
}

// Force a snapshot + WAL truncation now (manual compaction). 0 on success.
int kv_snapshot(void* h) {
    Store* st = static_cast<Store*>(h);
    SnapJob job;
    {
        std::lock_guard<std::mutex> lock(st->mu);
        if (st->dir.empty()) return 0;
        if (!begin_snapshot_locked(st, &job)) return -1;
    }
    return finish_snapshot(st, &job) ? 0 : -1;
}

// fdatasync the WAL (power-loss durability point). 0 on success.
int kv_sync(void* h) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    if (!st->wal) return 0;
    if (std::fflush(st->wal) != 0) return -1;
#ifndef _WIN32
    return fsync(fileno(st->wal)) == 0 ? 0 : -1;
#else
    return 0;
#endif
}

void kv_buf_free(char* buf) { std::free(buf); }

int64_t kv_rev(void* h) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    return st->rev;
}

// expect_rev semantics (etcd txn guards):
//   -1 : unconditional upsert
//    0 : create — key must not exist (If ModRevision == 0)
//   >0 : update — key's mod_rev must equal expect_rev (CAS)
int64_t kv_put(void* h, const char* key, const char* value,
               int64_t expect_rev, int* err) {
    Store* st = static_cast<Store*>(h);
    SnapJob job;
    bool do_snap = false;
    int64_t out;
    {
        std::lock_guard<std::mutex> lock(st->mu);
        auto it = st->data.find(key);
        if (expect_rev == 0 && it != st->data.end()) {
            *err = KV_CONFLICT;
            return 0;
        }
        if (expect_rev > 0) {
            if (it == st->data.end()) {
                *err = KV_NOT_FOUND;
                return 0;
            }
            if (it->second.mod_rev != expect_rev) {
                *err = KV_CONFLICT;
                return 0;
            }
        }
        // WAL-first: the mutation is acknowledged only after its record
        // is in the log — a failed append must not change state
        if (st->io_error) {
            *err = KV_IO;
            return 0;
        }
        int64_t next = st->rev + 1;
        if (!append_wal_record(st, false, next, key, value)) {
            st->io_error = true;
            *err = KV_IO;
            return 0;
        }
        bool created = (it == st->data.end());
        st->rev = next;
        st->data[key] = Entry{value, next};
        push_event(st, Event{next, false, created, key, value});
        if (st->wal && st->wal_records >= st->snapshot_every)
            do_snap = begin_snapshot_locked(st, &job);
        *err = KV_OK;
        out = next;
    }
    if (do_snap) finish_snapshot(st, &job);
    return out;
}

int64_t kv_delete(void* h, const char* key, int* err) {
    Store* st = static_cast<Store*>(h);
    SnapJob job;
    bool do_snap = false;
    int64_t out;
    {
        std::lock_guard<std::mutex> lock(st->mu);
        auto it = st->data.find(key);
        if (it == st->data.end()) {
            *err = KV_NOT_FOUND;
            return 0;
        }
        if (st->io_error) {
            *err = KV_IO;
            return 0;
        }
        int64_t next = st->rev + 1;
        if (!append_wal_record(st, true, next, key, std::string())) {
            st->io_error = true;
            *err = KV_IO;
            return 0;
        }
        st->rev = next;
        push_event(st, Event{next, true, false, key,
                             std::move(it->second.value)});
        st->data.erase(it);
        if (st->wal && st->wal_records >= st->snapshot_every)
            do_snap = begin_snapshot_locked(st, &job);
        *err = KV_OK;
        out = next;
    }
    if (do_snap) finish_snapshot(st, &job);
    return out;
}

// Returns malloc'd value or NULL; *mod_rev gets the entry's revision.
char* kv_get(void* h, const char* key, int64_t* mod_rev) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    auto it = st->data.find(key);
    if (it == st->data.end()) return nullptr;
    *mod_rev = it->second.mod_rev;
    return dup_buffer(it->second.value);
}

// Prefix scan -> JSON lines `{"key":...,"rev":N,"value":<doc>}`.
// *rev gets the store revision of the snapshot (list resourceVersion).
char* kv_list(void* h, const char* prefix, int64_t* rev) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    *rev = st->rev;
    std::string out;
    std::string pfx(prefix);
    for (auto it = st->data.lower_bound(pfx);
         it != st->data.end() && it->first.compare(0, pfx.size(), pfx) == 0;
         ++it) {
        out += "{\"key\":";
        append_json_string(out, it->first);
        out += ",\"rev\":" + std::to_string(it->second.mod_rev);
        out += ",\"value\":" + it->second.value + "}\n";
    }
    return dup_buffer(out);
}

// Events with rev > since_rev as JSON lines
// `{"rev":N,"type":"PUT"|"DELETE","create":0|1,"key":...,"value":<doc>}`.
// err: KV_COMPACTED when since_rev predates the ring window.
// *next_rev gets the last delivered (or current) revision.
char* kv_poll(void* h, int64_t since_rev, int max_events,
              int64_t* next_rev, int* err) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    *err = KV_OK;
    *next_rev = since_rev;
    // only events newer than the compaction horizon are replayable: the
    // horizon advances on ring overflow and jumps to the recovered
    // revision after kv_open (the ring does not survive restarts)
    if (since_rev < st->compacted_rev) {
        *err = KV_COMPACTED;
        return nullptr;
    }
    std::string out;
    int n = 0;
    for (const Event& ev : st->ring) {
        if (ev.rev <= since_rev) continue;
        if (max_events > 0 && n >= max_events) break;
        out += "{\"rev\":" + std::to_string(ev.rev);
        out += ",\"type\":\"";
        out += ev.is_delete ? "DELETE" : "PUT";
        out += "\",\"create\":";
        out += ev.is_create ? "1" : "0";
        out += ",\"key\":";
        append_json_string(out, ev.key);
        out += ",\"value\":" + ev.value + "}\n";
        *next_rev = ev.rev;
        ++n;
    }
    return dup_buffer(out);
}

int64_t kv_count(void* h, const char* prefix) {
    Store* st = static_cast<Store*>(h);
    std::lock_guard<std::mutex> lock(st->mu);
    std::string pfx(prefix);
    int64_t n = 0;
    for (auto it = st->data.lower_bound(pfx);
         it != st->data.end() && it->first.compare(0, pfx.size(), pfx) == 0;
         ++it)
        ++n;
    return n;
}

}  // extern "C"
