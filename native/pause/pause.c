/*
 * Pod sandbox holder — the framework's one tiny native daemon, mirroring
 * the role of the reference's pause container (build/pause/pause.c:
 * a process that holds the pod's namespaces alive and reaps orphaned
 * children as pid 1). Re-implemented, not copied: same contract —
 * ignore-nothing signal handling, zombie reaping, block forever.
 */

#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

static void on_term(int sig) {
    (void)sig;
    _exit(0);
}

static void on_chld(int sig) {
    (void)sig;
    /* reap every exited child (pid-1 duty inside the pod sandbox) */
    while (waitpid(-1, NULL, WNOHANG) > 0) {
    }
}

int main(int argc, char **argv) {
    (void)argc;
    (void)argv;
    struct sigaction sa_term = {0}, sa_chld = {0};
    sa_term.sa_handler = on_term;
    sa_chld.sa_handler = on_chld;
    sa_chld.sa_flags = SA_NOCLDSTOP;
    if (sigaction(SIGINT, &sa_term, NULL) < 0 ||
        sigaction(SIGTERM, &sa_term, NULL) < 0 ||
        sigaction(SIGCHLD, &sa_chld, NULL) < 0) {
        perror("sigaction");
        return 1;
    }
    for (;;) {
        pause(); /* wake only for signals; handlers do the rest */
    }
}
