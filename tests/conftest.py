import os

# Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
# exercised without TPU hardware (the driver validates the real-TPU path
# separately via __graft_entry__.py / bench.py).
#
# The environment pins JAX_PLATFORMS=axon and the axon sitecustomize
# imports jax at interpreter startup, so jax's config has already
# snapshotted "axon" — setting os.environ here is too late. Update the
# live config instead (backends are initialized lazily, so this works as
# long as no device op ran yet).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Deregister the axon PJRT factory entirely: jax's backends() initializes
# EVERY registered factory on first use regardless of jax_platforms, and
# a wedged axon tunnel (observed: SIGKILLed TPU runs wedge the relay
# machine-wide for hours) then hangs make_c_api_client inside the first
# jax.devices() of a CPU-only test run. Tests never want the axon
# backend; dropping its factory before any backend init makes the suite
# immune to tunnel state.
try:  # noqa: SIM105
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass  # jax internals moved: lazy-init ordering still usually works


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tier-2 tests (tier-1 runs -m 'not slow')")
    config.addinivalue_line(
        "markers", "faults: fault-injection / robustness suite (make chaos)")
    config.addinivalue_line(
        "markers", "chaos: component-kill / control-plane resilience suite "
                   "(make chaos)")
    config.addinivalue_line(
        "markers", "autoscale: cluster-autoscaler suite (NodeGroup "
                   "scale-up/scale-down what-ifs on the device path)")
    config.addinivalue_line(
        "markers", "partition: zone disruption / eviction storm-control "
                   "suite (mass node failure; make chaos)")
    config.addinivalue_line(
        "markers", "observability: flight-recorder / metrics-exposition "
                   "suite (/debug/trace, /metrics, round ledger)")
    config.addinivalue_line(
        "markers", "hostpath: vectorized numpy host twin suite "
                   "(device==host parity, breaker-open degraded waves; "
                   "make chaos)")
    config.addinivalue_line(
        "markers", "mesh: mesh-sharded scheduling plane suite "
                   "(sharded==unsharded parity on the forced 8-device "
                   "CPU mesh; make multichip)")
    config.addinivalue_line(
        "markers", "telemetry: decision observatory / cluster-state "
                   "telemetry suite (score decomposition parity, "
                   "/debug/score, telemetry plane device==twin; "
                   "make obs / make chaos)")
    config.addinivalue_line(
        "markers", "analysis: ktpu-lint static-analysis rule engine "
                   "suite (per-rule historical-bug fixtures + the live "
                   "tree gate behind make lint)")
    config.addinivalue_line(
        "markers", "racecheck: runtime lock-order watcher suite incl. "
                   "the runtime-edges ⊆ static-lock-graph bridge "
                   "(make chaos)")
    config.addinivalue_line(
        "markers", "storm: overload control / storm survival suite "
                   "(priority-aware load shedding, device-dispatch "
                   "watchdog, clock-driven burst SLO gates; tier-1 + "
                   "make chaos)")
    config.addinivalue_line(
        "markers", "shadow: shadow-scoring observatory suite (live "
                   "WeightProfile hot swap/rollback, counterfactual "
                   "divergence, /debug/shadow; make obs / make chaos)")
    config.addinivalue_line(
        "markers", "meshfault: mesh fault-tolerance suite (device-loss "
                   "detection, quarantine/probe, reform ladder "
                   "8->4->2->1->heal, twin salvage parity; make chaos + "
                   "make multichip)")
    config.addinivalue_line(
        "markers", "poison: poison-work isolation suite (input-fault "
                   "attribution vs device faults, wave bisection, pod "
                   "quarantine/re-probe, numeric-integrity sentinels; "
                   "make chaos)")
    config.addinivalue_line(
        "markers", "autopilot: autopilot suite (ledger dataset + ridge "
                   "trainer, shadow/replay promotion gates, regression "
                   "watch auto-rollback, /debug/autopilot; make chaos)")
    config.addinivalue_line(
        "markers", "campaign: chaos-campaign suite (cluster-invariant "
                   "checker, seeded fault-schedule sampling/replay, "
                   "failing-schedule shrinking, KTPU_FAULTPOINTS "
                   "reproducers; make chaos — full budgeted run behind "
                   "make chaos-campaign)")
    config.addinivalue_line(
        "markers", "topology: topology & heterogeneity suite "
                   "(PodTopologySpread kernels, dense rack/superpod/"
                   "accel-gen columns, gang compactness scoring, "
                   "device==twin parity; make chaos + make obs)")
    config.addinivalue_line(
        "markers", "outage: control-plane outage survival suite "
                   "(store-path breaker, disconnected-mode bind spool, "
                   "durable intent journal, crash-restart replay; "
                   "make chaos)")
    config.addinivalue_line(
        "markers", "soak: resource-exhaustion survival suite (HBM "
                   "budget governor, vocab & row compaction, "
                   "capacity-fault OOM recovery, churn-plateau "
                   "regression gates; make chaos + make soak)")


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_faultpoints():
    """Fault points are process-global; never let one test's armed
    faults leak into the next."""
    from kubernetes_tpu.utils import faultpoints

    faultpoints.reset()
    yield
    faultpoints.reset()
