"""Shared fixture builders for tests (analog of the reference's
pkg/scheduler/testing fakes)."""

from __future__ import annotations

from typing import Dict, List, Optional

from kubernetes_tpu.api import types as api


def make_node(
    name: str,
    cpu="4",
    memory="8Gi",
    pods=110,
    labels: Optional[Dict[str, str]] = None,
    taints: Optional[List[api.Taint]] = None,
    unschedulable: bool = False,
    conditions: Optional[List[api.NodeCondition]] = None,
    **kw,
) -> api.Node:
    alloc = api.resource_list(cpu=cpu, memory=memory, pods=pods,
                              ephemeral_storage=kw.pop("ephemeral_storage", "100Gi"),
                              **kw)
    conds = conditions if conditions is not None else [
        api.NodeCondition(api.NODE_READY, api.COND_TRUE)
    ]
    return api.Node(
        metadata=api.ObjectMeta(name=name, labels=dict(labels or {})),
        spec=api.NodeSpec(unschedulable=unschedulable, taints=list(taints or [])),
        status=api.NodeStatus(capacity=dict(alloc), allocatable=alloc, conditions=conds),
    )


def make_pod(
    name: str,
    cpu=None,
    memory=None,
    namespace="default",
    labels: Optional[Dict[str, str]] = None,
    node_name: str = "",
    node_selector: Optional[Dict[str, str]] = None,
    affinity: Optional[api.Affinity] = None,
    tolerations: Optional[List[api.Toleration]] = None,
    ports: Optional[List[int]] = None,
    priority: Optional[int] = None,
    owner_uid: str = "",
    owner_kind: str = "ReplicaSet",
    **kw,
) -> api.Pod:
    reqs = {}
    if cpu is not None or memory is not None or kw:
        reqs = api.resource_list(cpu=cpu, memory=memory, **kw)
    container = api.Container(
        name="c",
        resources=api.ResourceRequirements(requests=reqs),
        ports=[api.ContainerPort(container_port=p, host_port=p) for p in (ports or [])],
    )
    owners = []
    if owner_uid:
        owners = [api.OwnerReference(kind=owner_kind, name=owner_uid,
                                     uid=owner_uid, controller=True)]
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=namespace,
                                labels=dict(labels or {}), owner_references=owners),
        spec=api.PodSpec(
            node_name=node_name,
            node_selector=dict(node_selector or {}),
            affinity=affinity,
            tolerations=list(tolerations or []),
            containers=[container],
            priority=priority,
        ),
    )
