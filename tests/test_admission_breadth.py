"""Admission/validation/quota breadth (round-4 verdict item 9).

Per-plugin tests for the round-5 admission additions (PodPreset,
ImagePolicyWebhook, OwnerReferencesPermissionEnforcement,
DenyEscalatingExec, DefaultStorageClass, NamespaceAutoProvision —
references under plugin/pkg/admission/), the generalized quota
evaluator set (pkg/quota/evaluator/core), and the per-kind validation
tables (pkg/apis/core/validation) including update-immutability."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api import validation
from kubernetes_tpu.api.labels import LabelSelector
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.server import admission as adm

from helpers import make_pod


def _admit(plugin, op, kind, obj, old=None, user=None, store=None):
    plugin.admit(op, kind, obj, old, user, store or ObjectStore())


class TestPodPreset:
    def test_injects_env_and_volumes_to_matching_pods(self):
        store = ObjectStore()
        store.create("podpresets", api.PodPreset(
            metadata=api.ObjectMeta(name="db-creds"),
            selector=LabelSelector(match_labels={"role": "app"}),
            env={"DB_HOST": "db.default.svc"},
            volumes=[api.Volume(name="cache", empty_dir=True)]))
        pod = make_pod("p1")
        pod.metadata.labels = {"role": "app"}
        _admit(adm.PodPresetAdmission(), "create", "pods", pod, store=store)
        assert pod.spec.containers[0].env["DB_HOST"] == "db.default.svc"
        assert any(v.name == "cache" for v in pod.spec.volumes)
        assert any(k.startswith("podpreset.admission.kubernetes.io/")
                   for k in pod.metadata.annotations)
        # non-matching pod untouched
        other = make_pod("p2")
        other.metadata.labels = {"role": "other"}
        _admit(adm.PodPresetAdmission(), "create", "pods", other,
               store=store)
        assert "DB_HOST" not in other.spec.containers[0].env

    def test_env_conflict_skips_preset(self):
        store = ObjectStore()
        store.create("podpresets", api.PodPreset(
            metadata=api.ObjectMeta(name="x"),
            env={"MODE": "preset"}))
        pod = make_pod("p1")
        pod.spec.containers[0].env = {"MODE": "mine"}
        _admit(adm.PodPresetAdmission(), "create", "pods", pod, store=store)
        assert pod.spec.containers[0].env["MODE"] == "mine"
        assert not pod.metadata.annotations


class _PolicyBackend:
    def __init__(self, allow):
        outer_allow = allow

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n))
                images = [c["image"]
                          for c in body["spec"]["containers"]]
                ok = outer_allow(images)
                payload = json.dumps({"status": {
                    "allowed": ok,
                    "reason": "" if ok else "image denied"}}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}/review"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class TestImagePolicyWebhook:
    def test_backend_decides(self):
        backend = _PolicyBackend(
            lambda images: all(":latest" not in i for i in images))
        try:
            plugin = adm.ImagePolicyWebhook(backend.url)
            ok_pod = make_pod("ok")
            ok_pod.spec.containers[0].image = "app:v1.2"
            _admit(plugin, "create", "pods", ok_pod)
            bad = make_pod("bad")
            bad.spec.containers[0].image = "app:latest"
            with pytest.raises(adm.AdmissionError):
                _admit(plugin, "create", "pods", bad)
        finally:
            backend.stop()

    def test_unreachable_backend_respects_default_allow(self):
        dead = adm.ImagePolicyWebhook("http://127.0.0.1:1/x", timeout=0.5)
        with pytest.raises(adm.AdmissionError):
            _admit(dead, "create", "pods", make_pod("p"))
        lax = adm.ImagePolicyWebhook("http://127.0.0.1:1/x",
                                     default_allow=True, timeout=0.5)
        _admit(lax, "create", "pods", make_pod("p"))  # no raise


class TestOwnerReferencesPermissionEnforcement:
    def test_block_owner_deletion_requires_finalizer_permission(self):
        from kubernetes_tpu.server.auth import (PolicyRule, RBACAuthorizer,
                                                RoleBinding, UserInfo)

        authz = RBACAuthorizer(bindings=[RoleBinding(
            "deployer", [PolicyRule(["update"],
                                    ["replicasets/finalizers"])])])
        plugin = adm.OwnerReferencesPermissionEnforcement(authz)
        pod = make_pod("p")
        pod.metadata.owner_references = [api.OwnerReference(
            kind="ReplicaSet", name="rs", uid="u1", controller=True,
            block_owner_deletion=True)]
        _admit(plugin, "create", "pods", pod,
               user=UserInfo("deployer"))  # allowed
        with pytest.raises(adm.AdmissionError):
            _admit(plugin, "create", "pods", pod, user=UserInfo("rando"))
        # refs without the blocking flag never need the permission
        pod2 = make_pod("p2")
        pod2.metadata.owner_references = [api.OwnerReference(
            kind="ReplicaSet", name="rs", uid="u1", controller=True)]
        _admit(plugin, "create", "pods", pod2, user=UserInfo("rando"))


class TestDenyEscalatingExec:
    def test_privileged_pod_exec_denied(self):
        plugin = adm.DenyEscalatingExec()
        priv = make_pod("priv")
        priv.spec.containers[0].privileged = True
        with pytest.raises(adm.AdmissionError):
            _admit(plugin, "create", "pods/exec", priv)
        hostnet = make_pod("hn")
        hostnet.spec.host_network = True
        with pytest.raises(adm.AdmissionError):
            _admit(plugin, "create", "pods/attach", hostnet)
        _admit(plugin, "create", "pods/exec", make_pod("plain"))
        # ordinary pod CREATION is not this plugin's business
        _admit(plugin, "create", "pods", priv)

    def test_enforced_on_the_apiserver_exec_path(self):
        from kubernetes_tpu.cli import kubectl
        from kubernetes_tpu.kubemark.hollow import HollowNode
        from kubernetes_tpu.server import APIServer
        import io

        store = ObjectStore()
        srv = APIServer(store,
                        admission=adm.AdmissionChain.default()).start()
        node = HollowNode(store, "n1", serve=True)
        try:
            pod = make_pod("priv", node_name="n1")
            pod.spec.containers[0].privileged = True
            store.create("pods", pod)
            node.kubelet.sync_once()
            out = io.StringIO()
            rc = kubectl.main(["--server", srv.url, "exec", "priv",
                               "echo", "hi"], out=out)
            assert rc == 1  # 403 from DenyEscalatingExec
        finally:
            node.stop()
            srv.stop()


class TestAdmitDenyExists:
    def test_always_admit_and_deny(self):
        _admit(adm.AlwaysAdmit(), "create", "pods", make_pod("p"))
        with pytest.raises(adm.AdmissionError):
            _admit(adm.AlwaysDeny(), "get", "pods", make_pod("p"))

    def test_namespace_exists(self):
        store = ObjectStore()
        pod = make_pod("p")
        pod.metadata.namespace = "nowhere"
        with pytest.raises(adm.AdmissionError) as ei:
            _admit(adm.NamespaceExists(), "create", "pods", pod, store=store)
        assert ei.value.code == 404
        store.create("namespaces", api.Namespace(
            metadata=api.ObjectMeta(name="nowhere")))
        _admit(adm.NamespaceExists(), "create", "pods", pod, store=store)
        # namespace objects themselves are exempt
        _admit(adm.NamespaceExists(), "create", "namespaces", api.Namespace(
            metadata=api.ObjectMeta(name="new")), store=store)


class TestDenyExecOnPrivileged:
    def test_privileged_only(self):
        plugin = adm.DenyExecOnPrivileged()
        priv = make_pod("priv")
        priv.spec.containers[0].privileged = True
        with pytest.raises(adm.AdmissionError):
            _admit(plugin, "create", "pods/exec", priv)
        # host namespaces alone pass — the deprecated plugin is
        # narrower than DenyEscalatingExec
        hostnet = make_pod("hn")
        hostnet.spec.host_network = True
        _admit(plugin, "create", "pods/exec", hostnet)


class TestPersistentVolumeLabel:
    def test_zone_labels_stamped_on_create(self):
        from kubernetes_tpu.cloud.provider import FakeCloud, Zone

        cloud = FakeCloud()
        cloud.default_zone = Zone(failure_domain="us-x1-a", region="us-x1")
        plugin = adm.PersistentVolumeLabel(cloud=cloud)
        pv = api.PersistentVolume(metadata=api.ObjectMeta(name="pv1"))
        _admit(plugin, "create", "persistentvolumes", pv)
        assert pv.metadata.labels[adm.PersistentVolumeLabel.ZONE_LABEL] \
            == "us-x1-a"
        assert pv.metadata.labels[adm.PersistentVolumeLabel.REGION_LABEL] \
            == "us-x1"
        # user-set labels win (setdefault semantics)
        pv2 = api.PersistentVolume(metadata=api.ObjectMeta(
            name="pv2", labels={adm.PersistentVolumeLabel.ZONE_LABEL: "z9"}))
        _admit(plugin, "create", "persistentvolumes", pv2)
        assert pv2.metadata.labels[adm.PersistentVolumeLabel.ZONE_LABEL] \
            == "z9"
        # updates and cloudless servers are untouched
        _admit(adm.PersistentVolumeLabel(), "create",
               "persistentvolumes", api.PersistentVolume(
                   metadata=api.ObjectMeta(name="pv3")))


class TestDefaultStorageClass:
    def test_default_class_applied(self):
        store = ObjectStore()
        store.create("storageclasses", api.StorageClass(
            metadata=api.ObjectMeta(name="fast", namespace=""),
            provisioner="mock.csi.k8s.io", is_default=True))
        pvc = api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name="c"),
            spec=api.PersistentVolumeClaimSpec(
                requests=api.resource_list(storage="1Gi")))
        _admit(adm.DefaultStorageClass(), "create",
               "persistentvolumeclaims", pvc, store=store)
        assert pvc.spec.storage_class_name == "fast"
        assert pvc.metadata.annotations[
            "volume.beta.kubernetes.io/storage-provisioner"] == \
            "mock.csi.k8s.io"
        # explicit class untouched
        pvc2 = api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name="c2"),
            spec=api.PersistentVolumeClaimSpec(storage_class_name="slow"))
        _admit(adm.DefaultStorageClass(), "create",
               "persistentvolumeclaims", pvc2, store=store)
        assert pvc2.spec.storage_class_name == "slow"

    def test_two_defaults_reject(self):
        store = ObjectStore()
        for n in ("a", "b"):
            store.create("storageclasses", api.StorageClass(
                metadata=api.ObjectMeta(name=n, namespace=""),
                is_default=True))
        pvc = api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name="c"))
        with pytest.raises(adm.AdmissionError):
            _admit(adm.DefaultStorageClass(), "create",
                   "persistentvolumeclaims", pvc, store=store)


class TestNamespaceAutoProvision:
    def test_creates_missing_namespace(self):
        store = ObjectStore()
        pod = make_pod("p")
        pod.metadata.namespace = "brand-new"
        _admit(adm.NamespaceAutoProvision(), "create", "pods", pod,
               store=store)
        assert (store.get("namespaces", "default", "brand-new")
                or store.get("namespaces", "", "brand-new")) is not None


class TestQuotaEvaluators:
    def _ns_with_quota(self, hard):
        store = ObjectStore()
        store.create("resourcequotas", api.ResourceQuota(
            metadata=api.ObjectMeta(name="q"),
            spec=api.ResourceQuotaSpec(hard=hard)))
        return store, adm.ResourceQuotaAdmission()

    def test_service_counts_and_nodeports(self):
        store, q = self._ns_with_quota({"services": 1,
                                        "services.nodeports": 0})
        store.create("services", api.Service(
            metadata=api.ObjectMeta(name="s1")))
        with pytest.raises(adm.AdmissionError):
            _admit(q, "create", "services", api.Service(
                metadata=api.ObjectMeta(name="s2")), store=store)
        store2, q2 = self._ns_with_quota({"services.nodeports": 0})
        with pytest.raises(adm.AdmissionError):
            _admit(q2, "create", "services", api.Service(
                metadata=api.ObjectMeta(name="np"),
                spec=api.ServiceSpec(type="NodePort")), store=store2)

    def test_pvc_count_and_storage_requests(self):
        store, q = self._ns_with_quota(
            {"requests.storage": api.resource_list(storage="5Gi")["storage"]})
        store.create("persistentvolumeclaims", api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name="a"),
            spec=api.PersistentVolumeClaimSpec(
                requests=api.resource_list(storage="4Gi"))))
        with pytest.raises(adm.AdmissionError):
            _admit(q, "create", "persistentvolumeclaims",
                   api.PersistentVolumeClaim(
                       metadata=api.ObjectMeta(name="b"),
                       spec=api.PersistentVolumeClaimSpec(
                           requests=api.resource_list(storage="2Gi"))),
                   store=store)

    def test_generic_object_counts(self):
        store, q = self._ns_with_quota({"count/configmaps": 1})
        store.create("configmaps", api.ConfigMap(
            metadata=api.ObjectMeta(name="a")))
        with pytest.raises(adm.AdmissionError):
            _admit(q, "create", "configmaps", api.ConfigMap(
                metadata=api.ObjectMeta(name="b")), store=store)


class TestValidationBreadth:
    def test_workload_selector_must_match_template(self):
        d = api.Deployment(
            metadata=api.ObjectMeta(name="d"),
            spec=api.DeploymentSpec(
                selector=LabelSelector(match_labels={"app": "x"}),
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(name="t",
                                            labels={"app": "OTHER"}))))
        errs = validation.validate("deployments", d)
        assert any("must match spec.selector" in e.detail for e in errs)

    def test_rbac_rule_requires_api_groups(self):
        role = api.Role(metadata=api.ObjectMeta(name="r"),
                        rules=[api.RBACPolicyRule(verbs=["get"],
                                                  resources=["pods"])])
        errs = validation.validate("roles", role)
        assert any("apiGroups" in e.field for e in errs)

    def test_binding_roleref_immutable(self):
        old = api.ClusterRoleBinding(
            metadata=api.ObjectMeta(name="b"),
            role_ref=api.RoleRef(kind="ClusterRole", name="a"))
        new = api.ClusterRoleBinding(
            metadata=api.ObjectMeta(name="b"),
            role_ref=api.RoleRef(kind="ClusterRole", name="ESCALATED"))
        errs = validation.validate("clusterrolebindings", new, old=old)
        assert any("immutable" in e.detail for e in errs)

    def test_pvc_immutable_after_bind(self):
        old = api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name="c"),
            spec=api.PersistentVolumeClaimSpec(volume_name="pv-1"))
        new = api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name="c"),
            spec=api.PersistentVolumeClaimSpec(volume_name="pv-OTHER"))
        errs = validation.validate("persistentvolumeclaims", new, old=old)
        assert any("immutable" in e.detail for e in errs)

    def test_hpa_pdb_quota_cron_priority(self):
        hpa = api.HorizontalPodAutoscaler(
            metadata=api.ObjectMeta(name="h"),
            spec=api.HorizontalPodAutoscalerSpec(min_replicas=5,
                                                 max_replicas=2))
        assert any("minReplicas" in e.field
                   for e in validation.validate("horizontalpodautoscalers",
                                                hpa))
        pdb = api.PodDisruptionBudget(
            metadata=api.ObjectMeta(name="p"),
            spec=api.PodDisruptionBudgetSpec(min_available=1,
                                             max_unavailable=1))
        assert any("mutually exclusive" in e.detail
                   for e in validation.validate("poddisruptionbudgets", pdb))
        cj = api.CronJob(metadata=api.ObjectMeta(name="c"),
                         spec=api.CronJobSpec(schedule="bogus"))
        assert any("cron" in e.detail
                   for e in validation.validate("cronjobs", cj))
        pc = api.PriorityClass(metadata=api.ObjectMeta(name="huge"),
                               value=2_000_000_000)
        assert any("system classes" in e.detail
                   for e in validation.validate("priorityclasses", pc))

    def test_every_served_kind_validates_metadata(self):
        """No built-in kind escapes: a bad name 422s everywhere."""
        from kubernetes_tpu.api import scheme

        for kind in list(scheme._REGISTRY):
            typ = scheme.type_for_kind(kind)
            if typ is api.CustomObject:
                continue
            try:
                obj = typ(metadata=api.ObjectMeta(name="Bad_NAME!"))
            except TypeError:
                continue  # kinds without standard metadata
            plural = scheme.plural_for_kind(kind)
            errs = validation.validate(plural, obj)
            assert errs, f"{kind}: invalid name accepted"


class TestServiceAccountAutomount:
    def _store_with_sa(self, automount=None):
        from kubernetes_tpu.controllers.serviceaccount import (
            ServiceAccountController)

        store = ObjectStore()
        sa = api.ServiceAccount(metadata=api.ObjectMeta(name="default"))
        sa.automount_service_account_token = automount
        store.create("serviceaccounts", sa)
        ServiceAccountController(store).sync_all()  # mints default-token
        return store

    def test_token_volume_injected(self):
        store = self._store_with_sa()
        assert store.get("secrets", "default", "default-token") is not None
        pod = make_pod("p")
        _admit(adm.ServiceAccountAdmission(), "create", "pods", pod,
               store=store)
        vols = {v.name: v for v in pod.spec.volumes}
        assert vols["default-token"].secret == "default-token"
        # idempotent: an existing volume of the name is left alone
        _admit(adm.ServiceAccountAdmission(), "create", "pods", pod,
               store=store)
        assert sum(1 for v in pod.spec.volumes
                   if v.name == "default-token") == 1

    def test_opt_out_respected(self):
        store = self._store_with_sa(automount=False)
        pod = make_pod("p")
        _admit(adm.ServiceAccountAdmission(), "create", "pods", pod,
               store=store)
        assert not any(v.name == "default-token"
                       for v in pod.spec.volumes)


class TestQuotaScopes:
    def _pod(self, name, cpu=None, deadline=None):
        c = api.Container()
        if cpu:
            c.resources = api.ResourceRequirements(
                requests=api.resource_list(cpu=cpu, memory="64Mi"))
        p = api.Pod(metadata=api.ObjectMeta(name=name),
                    spec=api.PodSpec(containers=[c]))
        p.spec.active_deadline_seconds = deadline
        return p

    def test_besteffort_scope_only_counts_besteffort(self):
        store = ObjectStore()
        store.create("resourcequotas", api.ResourceQuota(
            metadata=api.ObjectMeta(name="be"),
            spec=api.ResourceQuotaSpec(hard={"pods": 1},
                                       scopes=["BestEffort"])))
        q = adm.ResourceQuotaAdmission()
        # a burstable pod is OUTSIDE the scope: unlimited
        _admit(q, "create", "pods", self._pod("b1", cpu="100m"),
               store=store)
        store.create("pods", self._pod("be1"))
        with pytest.raises(adm.AdmissionError):
            _admit(q, "create", "pods", self._pod("be2"), store=store)

    def test_terminating_scope(self):
        store = ObjectStore()
        store.create("resourcequotas", api.ResourceQuota(
            metadata=api.ObjectMeta(name="term"),
            spec=api.ResourceQuotaSpec(hard={"pods": 1},
                                       scopes=["Terminating"])))
        q = adm.ResourceQuotaAdmission()
        _admit(q, "create", "pods", self._pod("forever"), store=store)
        store.create("pods", self._pod("bounded1", deadline=60))
        with pytest.raises(adm.AdmissionError):
            _admit(q, "create", "pods", self._pod("bounded2", deadline=30),
                   store=store)
        # scoped quotas never govern non-pod kinds
        _admit(q, "create", "services", api.Service(
            metadata=api.ObjectMeta(name="s"),
            spec=api.ServiceSpec(ports=[api.ServicePort(port=80)])),
            store=store)


class TestLimitRangePodType:
    def test_pod_aggregate_bounds(self):
        store = ObjectStore()
        store.create("limitranges", api.LimitRange(
            metadata=api.ObjectMeta(name="lr"),
            spec=api.LimitRangeSpec(limits=[api.LimitRangeItem(
                type="Pod",
                max=api.resource_list(cpu="1"),
                min=api.resource_list(cpu="200m"))])))
        lr = adm.LimitRanger()
        ok = api.Pod(metadata=api.ObjectMeta(name="ok"),
                     spec=api.PodSpec(containers=[
                         api.Container(name="a", resources=api.ResourceRequirements(
                             requests=api.resource_list(cpu="300m"))),
                         api.Container(name="b", resources=api.ResourceRequirements(
                             requests=api.resource_list(cpu="300m")))]))
        _admit(lr, "create", "pods", ok, store=store)
        big = api.Pod(metadata=api.ObjectMeta(name="big"),
                      spec=api.PodSpec(containers=[
                          api.Container(name="a", resources=api.ResourceRequirements(
                              requests=api.resource_list(cpu="600m"))),
                          api.Container(name="b", resources=api.ResourceRequirements(
                              requests=api.resource_list(cpu="600m")))]))
        with pytest.raises(adm.AdmissionError):
            _admit(lr, "create", "pods", big, store=store)
        small = api.Pod(metadata=api.ObjectMeta(name="small"),
                        spec=api.PodSpec(containers=[
                            api.Container(name="a", resources=api.ResourceRequirements(
                                requests=api.resource_list(cpu="100m")))]))
        with pytest.raises(adm.AdmissionError):
            _admit(lr, "create", "pods", small, store=store)


class TestQuotaScopeValidation:
    def test_unknown_scope_is_422(self):
        q = api.ResourceQuota(
            metadata=api.ObjectMeta(name="q"),
            spec=api.ResourceQuotaSpec(hard={"pods": 1},
                                       scopes=["Terminatin"]))
        errs = validation.validate("resourcequotas", q)
        assert errs and "spec.scopes" in errs.message()

    def test_pod_max_bounds_limits_not_requests(self):
        store = ObjectStore()
        store.create("limitranges", api.LimitRange(
            metadata=api.ObjectMeta(name="lr"),
            spec=api.LimitRangeSpec(limits=[api.LimitRangeItem(
                type="Pod", max=api.resource_list(memory="1Gi"))])))
        lr = adm.LimitRanger()
        sneaky = api.Pod(
            metadata=api.ObjectMeta(name="sneaky"),
            spec=api.PodSpec(containers=[api.Container(
                name="a",
                resources=api.ResourceRequirements(
                    requests=api.resource_list(memory="256Mi"),
                    limits=api.resource_list(memory="2Gi")))]))
        with pytest.raises(adm.AdmissionError):
            _admit(lr, "create", "pods", sneaky, store=store)
