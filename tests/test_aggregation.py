"""API aggregation tests: APIService registration, request proxying to
an extension apiserver, availability conditions.

Reference test model: kube-aggregator's handler_proxy_test.go (proxy a
request to a test backend through an APIService) and
available_controller_test.go.
"""

import http.server
import json
import threading

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.rest import APIStatusError, RESTClient
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.server import AdmissionChain, APIServer
from kubernetes_tpu.server.aggregator import APIServiceAvailabilityController

import pytest


class _Extension(http.server.BaseHTTPRequestHandler):
    """A tiny extension apiserver: echoes path + method as JSON."""

    def _reply(self):
        body = json.dumps({"servedBy": "extension", "path": self.path,
                           "method": self.command}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    do_GET = do_POST = _reply

    def log_message(self, *a):
        pass


def _start_extension():
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Extension)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv


def _register_apiservice(store, port):
    store.create("services", api.Service(
        metadata=api.ObjectMeta(name="metrics-server", namespace="default"),
        spec=api.ServiceSpec(ports=[api.ServicePort(port=port)])))
    store.create("endpoints", api.Endpoints(
        metadata=api.ObjectMeta(name="metrics-server", namespace="default"),
        subsets=[api.EndpointSubset(
            addresses=[api.EndpointAddress(ip="127.0.0.1")],
            ports=[api.EndpointPort(port=port)])]))
    store.create("apiservices", api.APIService(
        metadata=api.ObjectMeta(name="v1alpha1.custom.metrics.io",
                                namespace=""),
        spec=api.APIServiceSpec(group="custom.metrics.io",
                                version="v1alpha1",
                                service_name="metrics-server",
                                service_port=port)))


class TestAggregation:
    def test_proxy_to_extension_apiserver(self):
        ext = _start_extension()
        store = ObjectStore()
        srv = APIServer(store, admission=AdmissionChain()).start()
        try:
            _register_apiservice(store, ext.server_address[1])
            client = RESTClient(srv.url)
            data = client.request(
                "GET", "/apis/custom.metrics.io/v1alpha1/nodemetrics")
            assert data["servedBy"] == "extension"
            assert data["path"].endswith("/v1alpha1/nodemetrics")
        finally:
            srv.stop()
            ext.shutdown()

    def test_unclaimed_group_is_404_and_no_endpoints_503(self):
        store = ObjectStore()
        srv = APIServer(store, admission=AdmissionChain()).start()
        try:
            client = RESTClient(srv.url)
            with pytest.raises(APIStatusError) as ei:
                client.request("GET", "/apis/nobody.claimed.io/v1/things")
            assert ei.value.code == 404
            # claimed but no backing endpoints -> 503
            store.create("apiservices", api.APIService(
                metadata=api.ObjectMeta(name="v1.down.io", namespace=""),
                spec=api.APIServiceSpec(group="down.io", version="v1",
                                        service_name="gone")))
            with pytest.raises(APIStatusError) as ei:
                client.request("GET", "/apis/down.io/v1/things")
            assert ei.value.code == 503
        finally:
            srv.stop()

    def test_proxy_respects_rbac(self):
        """The aggregator sits behind authorization: a user without
        grants must get 403 before the proxy hop, never a backend
        response (real kube-aggregator authorizes pre-proxy)."""
        from kubernetes_tpu.server import RBACAuthorizer, TokenAuthenticator
        from kubernetes_tpu.server.auth import PolicyRule, RoleBinding, UserInfo

        ext = _start_extension()
        store = ObjectStore()
        authn = TokenAuthenticator({
            "admin-token": UserInfo("admin", groups=["system:masters"]),
            "nobody-token": UserInfo("nobody", groups=[])})
        authz = RBACAuthorizer([
            RoleBinding("system:masters", [PolicyRule(["*"], ["*"])])])
        srv = APIServer(store, authenticator=authn, authorizer=authz).start()
        try:
            _register_apiservice(store, ext.server_address[1])
            admin = RESTClient(srv.url, token="admin-token")
            data = admin.request(
                "GET", "/apis/custom.metrics.io/v1alpha1/nodemetrics")
            assert data["servedBy"] == "extension"
            nobody = RESTClient(srv.url, token="nobody-token")
            with pytest.raises(APIStatusError) as ei:
                nobody.request(
                    "GET", "/apis/custom.metrics.io/v1alpha1/nodemetrics")
            assert ei.value.code == 403
        finally:
            srv.stop()
            ext.shutdown()

    def test_availability_controller(self):
        store = ObjectStore()
        ctrl = APIServiceAvailabilityController(store)
        store.create("apiservices", api.APIService(
            metadata=api.ObjectMeta(name="v1.ext.io", namespace=""),
            spec=api.APIServiceSpec(group="ext.io", version="v1",
                                    service_name="backend")))
        store.create("apiservices", api.APIService(
            metadata=api.ObjectMeta(name="v1.local.io", namespace=""),
            spec=api.APIServiceSpec(group="local.io", version="v1")))
        ctrl.sync_all()

        def cond(name):
            svc = store.get("apiservices", "", name)
            return next(c for c in svc.status.conditions
                        if c.type == "Available")

        assert cond("v1.local.io").status == api.COND_TRUE
        assert cond("v1.ext.io").status == api.COND_FALSE
        assert cond("v1.ext.io").reason == "MissingEndpoints"
        # endpoints appear -> flips Available
        store.create("endpoints", api.Endpoints(
            metadata=api.ObjectMeta(name="backend"),
            subsets=[api.EndpointSubset(
                addresses=[api.EndpointAddress(ip="10.0.0.1")],
                ports=[api.EndpointPort(port=443)])]))
        ctrl.sync_all()
        assert cond("v1.ext.io").status == api.COND_TRUE
