"""ktpu-lint (kubernetes_tpu/analysis) — the invariant-enforcing static
analysis pass.

Covers, per rule, the HISTORICAL bug pattern that motivated it
(reintroduced in fixture corpora and asserted caught):

  determinism      PR 8: gang members kept in a `set`, iterated to build
                   the member batch — placements varied with the uid
                   hash seed
  jit-purity       PR 2: a faultpoints.fire() inside a jitted body runs
                   only at trace time, so injected faults vanish once
                   the compile cache warms
  twin-coverage    PR 7: a device kernel without a hostwave twin loses
                   the degraded path silently
  f32-reduction    PR 9: raw f32 sums reassociate differently on numpy
                   vs XLA vs GSPMD
  lock-discipline  PR 4: device dispatch under the scheduler lock from
                   outside the scheduler; lock-order inversions
  metrics-hygiene  PR 9: unbounded label values grow /metrics forever

plus suppression/baseline mechanics and the live-tree gates: the real
repo analyzes clean, and the determinism/jit-purity baselines are EMPTY
by policy (findings there are fixed, never grandfathered).
"""

import textwrap

import pytest

from kubernetes_tpu.analysis import Baseline, run_analysis
from kubernetes_tpu.analysis.core import Corpus, SourceFile
from kubernetes_tpu.analysis.rules import (DeterminismRule, F32ReductionRule,
                                           JitPurityRule, LockDisciplineRule,
                                           MetricsHygieneRule,
                                           TwinCoverageRule)

pytestmark = pytest.mark.analysis


def corpus(tmp_path, files, test_texts=None) -> Corpus:
    """A Corpus over fixture sources written to a scratch tree."""
    root = tmp_path / "repo"
    c = Corpus(root)
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        c.files[rel] = SourceFile(p, rel)
    c.test_texts = dict(test_texts or {})
    return c


# ---------------------------------------------------------------------------
# determinism — the PR 8 gang-members-in-a-set bug, verbatim pattern
# ---------------------------------------------------------------------------

PR8_FIXTURE = """
    class SchedulingQueue:
        def __init__(self):
            self._gang_members = set()

        def add(self, uid):
            self._gang_members.add(uid)

        def _pop_gangmates_locked(self, out):
            for uid in self._gang_members:
                out.append(uid)
"""


class TestDeterminismRule:
    def run(self, tmp_path, src):
        c = corpus(tmp_path, {"kubernetes_tpu/sched/fix.py": src})
        return DeterminismRule().run(c)

    def test_catches_the_pr8_gang_set_pattern(self, tmp_path):
        fs = self.run(tmp_path, PR8_FIXTURE)
        assert len(fs) == 1
        assert fs[0].rule == "determinism"
        assert "self._gang_members" in fs[0].message
        assert "for uid in self._gang_members" in fs[0].snippet

    def test_local_set_expression_and_materializers(self, tmp_path):
        fs = self.run(tmp_path, """
            def stale(have, want):
                for s in set(have) - want:
                    print(s)

            def listed(have):
                return list(set(have))

            def joined(have):
                return ",".join({h for h in have})
        """)
        assert len(fs) == 3
        assert {f.line for f in fs} == {3, 7, 10}

    def test_order_free_consumers_are_clean(self, tmp_path):
        fs = self.run(tmp_path, """
            def ok(have, want):
                s = set(have)
                n = len(s)
                m = sorted(s)
                if any(x in want for x in m):
                    return min(s | want, default=None)
                return n
        """)
        assert fs == []

    def test_dict_as_ordered_set_is_the_sanctioned_fix(self, tmp_path):
        fs = self.run(tmp_path, """
            from typing import Dict

            def fixed(victims):
                gangs: Dict[str, None] = {}
                for v in victims:
                    gangs[v] = None
                for k in gangs:
                    yield k
        """)
        assert fs == []

    def test_suppression_on_line_above(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/sched/fix.py": textwrap.dedent("""
            def drain(pending):
                # ktpu: allow[determinism] wait-on-ALL, order irrelevant
                for p in set(pending):
                    p.join()
        """)})
        report = run_analysis(corpus=c, rules=[DeterminismRule()],
                              baseline=Baseline())
        assert report.new == []
        assert len(report.suppressed) == 1

    def test_out_of_scope_package_is_not_checked(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/kubelet/fix.py": PR8_FIXTURE})
        assert DeterminismRule().run(c) == []


# ---------------------------------------------------------------------------
# jit-purity — the PR 2 fire()-inside-the-boundary bug, verbatim pattern
# ---------------------------------------------------------------------------

PR2_FIXTURE = """
    import functools
    import time

    import jax

    from ..utils import faultpoints


    def schedule_round(*args, **kw):
        faultpoints.fire("kernel.round")  # correct: outside the boundary
        return _schedule_round(*args, **kw)


    @functools.partial(jax.jit, static_argnames=("n",))
    def _schedule_round(x, *, n):
        faultpoints.fire("kernel.round.inner")  # the PR 2 bug
        return _helper(x) * n


    def _helper(x):
        t = time.monotonic()  # reachable from the root: also impure
        return x + t
"""


class TestJitPurityRule:
    def test_catches_the_pr2_fire_inside_jit(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/ops/fix.py": PR2_FIXTURE})
        fs = JitPurityRule().run(c)
        # the jitted body's fire() and the transitively-reached clock,
        # NOT the entry wrapper's fire() (that one is the sanctioned
        # pattern — outside the boundary)
        fires = [f for f in fs if "fault point" in f.message]
        assert len(fires) == 1 and "inner" in fires[0].snippet
        assert any("wall-clock" in f.message for f in fs)

    def test_self_mutation_and_print_flagged(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/ops/fix.py": """
            import jax

            @jax.jit
            def body(x):
                print(x)
                return x
        """})
        fs = JitPurityRule().run(c)
        assert len(fs) == 1 and "print" in fs[0].message

    def test_assigned_jit_root_is_found(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/ops/fix.py": """
            import jax
            import time

            def _body(x):
                return x + time.time()

            compiled = jax.jit(_body)
        """})
        fs = JitPurityRule().run(c)
        assert len(fs) == 1 and "wall-clock" in fs[0].message

    def test_pure_kernel_is_clean(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/ops/fix.py": """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def body(x):
                return jnp.sum(x.astype(jnp.int32))
        """})
        assert JitPurityRule().run(c) == []

    def test_jax_functional_prng_is_pure_stdlib_rng_is_not(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/ops/fix.py": """
            import random

            import jax
            import numpy as np

            @jax.jit
            def ok(key, x):
                return x + jax.random.uniform(key, x.shape)

            @jax.jit
            def bad_std(x):
                return x + random.random()

            @jax.jit
            def bad_np(x):
                return x + np.random.rand()
        """})
        fs = [f for f in JitPurityRule().run(c) if "RNG" in f.message]
        assert {f.snippet for f in fs} == {
            "return x + random.random()", "return x + np.random.rand()"}


# ---------------------------------------------------------------------------
# twin-coverage
# ---------------------------------------------------------------------------


class TestTwinCoverageRule:
    KERNELS = """
        import jax.numpy as jnp

        def covered(x):
            return jnp.sum(x.astype(jnp.int32))

        def orphan(x):
            return jnp.max(x)

        def _private(x):
            return jnp.min(x)

        def host_util(x):
            return len(x)
    """
    HOSTWAVE = """
        import numpy as np

        def covered(x):
            return np.sum(x.astype(np.int32))
    """

    def make(self, tmp_path, test_texts=None):
        return corpus(tmp_path, {
            "kubernetes_tpu/ops/gang.py": self.KERNELS,
            "kubernetes_tpu/ops/hostwave.py": self.HOSTWAVE,
        }, test_texts)

    def test_missing_twin_and_missing_parity_test(self, tmp_path):
        c = self.make(tmp_path)
        fs = TwinCoverageRule().run(c)
        by_msg = {f.snippet: f.message for f in fs}
        assert any("orphan" in m and "no host twin" in m
                   for m in by_msg.values())
        assert any("covered" in m and "no parity test" in m
                   for m in by_msg.values())
        # private and jnp-free functions are not kernels
        assert not any("_private" in m or "host_util" in m
                       for m in by_msg.values())

    def test_parity_test_naming_both_clears_it(self, tmp_path):
        c = self.make(tmp_path, test_texts={
            "test_x.py": "from kubernetes_tpu.ops import hostwave\n"
                         "def test_covered_parity(): covered()\n"})
        fs = TwinCoverageRule().run(c)
        assert not any("covered" in f.message for f in fs)
        assert any("orphan" in f.message for f in fs)


# ---------------------------------------------------------------------------
# f32-reduction
# ---------------------------------------------------------------------------


class TestF32ReductionRule:
    def test_raw_f32_sum_flagged_exemptions_hold(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/ops/fix.py": """
            import numpy as np

            def raw(x):
                return np.sum(x)

            def int_cast(x):
                return np.sum(x.astype(np.int32))

            def masked(x):
                m = x > 0
                return np.sum(m)

            def f64_accum(x):
                return np.sum(x, dtype=np.float64)

            def where_f32(m, x):
                return np.sum(np.where(m, x, 0.0))
        """})
        fs = F32ReductionRule().run(c)
        assert {f.snippet for f in fs} == {
            "return np.sum(x)", "return np.sum(np.where(m, x, 0.0))"}
        assert all("_pairwise_sum" in f.message for f in fs)

    def test_out_of_scope_is_clean(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/sched/fix.py": """
            import numpy as np

            def raw(x):
                return np.sum(x)
        """})
        assert F32ReductionRule().run(c) == []


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


class TestLockDisciplineRule:
    def test_inversion_detected(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/sched/fix.py": """
            import threading

            class A:
                def __init__(self):
                    self._l1 = threading.Lock()
                    self._l2 = threading.Lock()

                def m1(self):
                    with self._l1:
                        with self._l2:
                            pass

                def m2(self):
                    with self._l2:
                        with self._l1:
                            pass
        """})
        fs = LockDisciplineRule().run(c)
        inv = [f for f in fs if "inversion" in f.message]
        assert len(inv) == 1
        assert "A._l1" in inv[0].message and "A._l2" in inv[0].message

    def test_blocking_io_under_lock(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/state/fix.py": """
            import threading
            import time

            class B:
                def __init__(self):
                    self._lock = threading.Lock()

                def m(self):
                    with self._lock:
                        time.sleep(1)
        """})
        fs = LockDisciplineRule().run(c)
        assert len(fs) == 1 and "blocking call" in fs[0].message

    def test_pr4_device_dispatch_under_scheduler_lock(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/sched/fix.py": """
            import threading

            class Scheduler:
                def __init__(self):
                    self._mu = threading.Lock()

                def fine_inside(self):
                    with self._mu:
                        schedule_wave(1)
        """, "kubernetes_tpu/controllers/clusterautoscaler.py": """
            class Autoscaler:
                def __init__(self, sched):
                    self.sched = Scheduler()

                def whatif(self):
                    with self.sched._mu:
                        schedule_wave(1)
        """})
        fs = LockDisciplineRule().run(c)
        outside = [f for f in fs if "outside the Scheduler" in f.message]
        assert len(outside) == 1
        assert outside[0].path.endswith("clusterautoscaler.py")

    def test_multi_item_with_statement_forms_edges(self, tmp_path):
        """`with a, b:` acquires b while a is held — same edge as
        lexical nesting, and an inversion written that way is caught."""
        c = corpus(tmp_path, {"kubernetes_tpu/sched/fix.py": """
            import threading

            class A:
                def __init__(self):
                    self._l1 = threading.Lock()
                    self._l2 = threading.Lock()

                def m1(self):
                    with self._l1:
                        with self._l2:
                            pass

                def m2(self):
                    with self._l2, self._l1:
                        pass
        """})
        fs = LockDisciplineRule().run(c)
        assert len([f for f in fs if "inversion" in f.message]) == 1

    def test_transitive_acquisition_builds_the_edge(self, tmp_path):
        """A method that takes lock B is called under lock A — the edge
        exists even though no `with` nests lexically."""
        from kubernetes_tpu.analysis.lockgraph import extract_lock_graph

        c = corpus(tmp_path, {"kubernetes_tpu/sched/fix.py": """
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()

                def push(self, x):
                    with self._lock:
                        return x

            class S:
                def __init__(self):
                    self._mu = threading.Lock()
                    self.queue = Q()

                def commit(self, x):
                    with self._mu:
                        self.queue.push(x)
        """})
        g = extract_lock_graph(c)
        assert ("S._mu", "Q._lock") in g.edge_set()


# ---------------------------------------------------------------------------
# metrics-hygiene
# ---------------------------------------------------------------------------


class TestMetricsHygieneRule:
    FIXTURE = """
        from ..utils.metrics import LabeledCounter, bounded_label


        class M:
            def __init__(self):
                self.errors = LabeledCounter("errs", ("stage",))
                self.events = LabeledCounter(
                    "ev", ("kind",), values={"kind": ("a", "b")})


        class User:
            def __init__(self):
                self.m = M()

            def bad_dynamic(self, s):
                self.m.errors.labels(stage=s).inc()

            def ok_dynamic_declared(self, k):
                self.m.events.labels(kind=k).inc()

            def ok_literal(self):
                self.m.errors.labels(stage="bind").inc()

            def bad_literal_outside_declared(self):
                self.m.events.labels(kind="zzz").inc()

            def ok_bucketed(self, s):
                self.m.errors.labels(stage=bounded_label(s, ("x",))).inc()

            def ok_literal_local(self, cond):
                v = "a" if cond else "b"
                self.m.errors.labels(stage=v).inc()
    """

    def test_sites_classified(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/sched/fix.py": self.FIXTURE})
        fs = MetricsHygieneRule().run(c)
        assert len(fs) == 2
        dynamic = [f for f in fs if "dynamic value" in f.message]
        outside = [f for f in fs if "not in the declared" in f.message]
        assert len(dynamic) == 1 and "stage=s" in dynamic[0].snippet
        assert len(outside) == 1 and "kind='zzz'" in outside[0].message

    def test_runtime_enforcement_matches_the_static_declaration(self):
        """values= is not documentation: labels() rejects undeclared
        values, so the static rule's 'declared set' assumption holds at
        runtime too."""
        from kubernetes_tpu.utils.metrics import LabeledCounter, bounded_label

        fam = LabeledCounter("x_total", ("kind",),
                             values={"kind": ("a", "b")})
        fam.labels(kind="a").inc()
        with pytest.raises(ValueError, match="declared value set"):
            fam.labels(kind="zzz")
        assert bounded_label("zzz", ("a", "b")) == "Other"
        assert bounded_label("a", ("a", "b")) == "a"

    def test_declarations_stay_in_lockstep_with_their_sources(self):
        """The literal value sets in utils/metrics.py mirror constants
        owned elsewhere — pin them together."""
        from kubernetes_tpu.controllers.nodelifecycle import ZONE_STATES
        from kubernetes_tpu.ops.scores import SCORE_STACK
        from kubernetes_tpu.ops.telemetry import CANONICAL_SHAPES
        from kubernetes_tpu.utils.metrics import Metrics

        m = Metrics()
        assert (m.score_priority_points.decl.values["priority"]
                == frozenset(SCORE_STACK))
        assert (m.feasibility_headroom.decl.values["shape"]
                == frozenset(s[0] for s in CANONICAL_SHAPES))
        assert (m.zone_health.decl.values["state"]
                == frozenset(ZONE_STATES))


# ---------------------------------------------------------------------------
# suppression / baseline mechanics
# ---------------------------------------------------------------------------


class TestBaselineMechanics:
    SRC = """
        def a(have):
            for x in set(have):
                print(x)

        def pad():
            return 1

        def b(have):
            for x in set(have):
                print(x)
    """

    def test_multiset_one_to_one_matching(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/sched/fix.py": self.SRC})
        fs = DeterminismRule().run(c)
        assert len(fs) == 2
        baseline = Baseline.from_findings(fs[:1])
        new, matched, stale = baseline.split(fs)
        # identical snippets: ONE is grandfathered, the second is new
        assert len(matched) == 1 and len(new) == 1 and stale == []

    def test_baseline_survives_line_shifts(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/sched/fix.py": self.SRC})
        fs = DeterminismRule().run(c)
        baseline = Baseline.from_findings(fs)
        # same file, findings pushed to different line numbers by edits
        # above them — keys match on (rule, path, snippet), not line
        shifted = corpus(tmp_path, {
            "kubernetes_tpu/sched/fix.py": "\n\n\n\n" + self.SRC})
        fs2 = DeterminismRule().run(shifted)
        assert {f.line for f in fs2} != {f.line for f in fs}
        new, matched, stale = baseline.split(fs2)
        assert new == [] and len(matched) == 2 and stale == []

    def test_path_filter_never_strands_out_of_path_entries(self, tmp_path):
        """A path-filtered run classifies the baseline over the WHOLE
        tree — out-of-path entries must neither surface as stale nor be
        dropped by a subsequent --update-baseline."""
        bug = """
            def f(have):
                for x in set(have):
                    print(x)
        """
        c = corpus(tmp_path, {"kubernetes_tpu/sched/a.py": bug,
                              "kubernetes_tpu/state/b.py": bug})
        baseline = Baseline.from_findings(DeterminismRule().run(c))
        assert len(baseline.entries) == 2
        report = run_analysis(corpus=c, rules=[DeterminismRule()],
                              baseline=baseline,
                              paths=("kubernetes_tpu/sched/",))
        assert report.ok()
        assert report.stale_baseline == []
        assert len(report.baselined) == 1  # only the in-path one reported

    def test_stale_entries_reported(self, tmp_path):
        c = corpus(tmp_path, {"kubernetes_tpu/sched/fix.py": """
            def clean():
                return 1
        """})
        baseline = Baseline([{"rule": "determinism",
                              "path": "kubernetes_tpu/sched/fix.py",
                              "snippet": "for x in set(gone):"}])
        report = run_analysis(corpus=c, rules=[DeterminismRule()],
                              baseline=baseline)
        assert report.ok()
        assert len(report.stale_baseline) == 1


# ---------------------------------------------------------------------------
# the live tree
# ---------------------------------------------------------------------------


class TestLiveTree:
    def test_whole_tree_is_clean_on_the_committed_baseline(self):
        """`python -m kubernetes_tpu.analysis` exits 0 — the tier-1 gate
        behind `make lint`."""
        from kubernetes_tpu.analysis.__main__ import main

        assert main([]) == 0

    def test_determinism_and_jit_purity_need_no_baseline_at_all(self):
        """The acceptance bar: these two rules are clean with an EMPTY
        baseline — every historical finding was fixed, not
        grandfathered."""
        report = run_analysis(rules=[DeterminismRule(), JitPurityRule()],
                              baseline=Baseline())
        assert report.new == [], [f.render() for f in report.new]
        assert report.baselined == []

    def test_committed_baseline_holds_no_determinism_or_purity_debt(self):
        baseline = Baseline.load()
        rules = {e["rule"] for e in baseline.entries}
        assert "determinism" not in rules
        assert "jit-purity" not in rules

    def test_static_lock_graph_covers_the_known_plane(self):
        """The statically-extracted graph sees the scheduler's real
        acquisition edges (the runtime-superset bridge lives in
        tests/test_racecheck.py, driven by live traffic)."""
        from kubernetes_tpu.analysis.lockgraph import static_lock_graph

        edges = static_lock_graph()
        assert ("Scheduler._mu", "SchedulingQueue._lock") in edges
        # and its reverse is absent: no inversion in the live tree
        assert ("SchedulingQueue._lock", "Scheduler._mu") not in edges
