"""Scheme/codec + watch broadcaster tests (apimachinery analog).

Reference semantics: runtime.Scheme + JSON serializer round-trips
(apimachinery/pkg/runtime), watch.Broadcaster fan-out (pkg/watch/mux.go),
watch-cache replay + 410 Gone (apiserver/pkg/storage/watch_cache.go).
"""

import pytest

from kubernetes_tpu.api import scheme
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.labels import LabelSelector, Requirement
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.runtime.watch import Broadcaster, TooOld


def rt(obj):
    return scheme.from_json(scheme.to_json(obj))


class TestCodec:
    def test_pod_round_trip_full(self):
        p = api.Pod(
            metadata=api.ObjectMeta(name="p", namespace="ns", labels={"a": "b"},
                                    annotations={"k": "v"}),
            spec=api.PodSpec(
                node_selector={"disk": "ssd"},
                tolerations=[api.Toleration(key="k", operator="Exists",
                                            effect="NoExecute",
                                            toleration_seconds=30)],
                priority=100,
                affinity=api.Affinity(
                    node_affinity=api.NodeAffinity(
                        required=api.NodeSelector([api.NodeSelectorTerm(
                            match_expressions=[Requirement("zone", "In", ("z1",))])]),
                        preferred=[api.PreferredSchedulingTerm(
                            weight=5, preference=api.NodeSelectorTerm(
                                match_expressions=[Requirement("gpu", "Exists")]))]),
                    pod_anti_affinity=api.PodAntiAffinity(required=[
                        api.PodAffinityTerm(
                            label_selector=LabelSelector(match_labels={"app": "x"}),
                            topology_key="kubernetes.io/hostname")])),
                containers=[api.Container(
                    resources=api.ResourceRequirements(
                        requests=api.resource_list(cpu="250m", memory="64Mi")),
                    ports=[api.ContainerPort(container_port=80, host_port=80)])],
                volumes=[api.Volume(name="v", source_kind="GCEPersistentDisk",
                                    source_id="pd-1")]),
        )
        p2 = rt(p)
        assert p2.metadata.name == "p" and p2.metadata.namespace == "ns"
        assert p2.spec.tolerations[0].toleration_seconds == 30
        req = p2.spec.affinity.node_affinity.required
        assert req.node_selector_terms[0].match_expressions[0].values == ("z1",)
        assert p2.spec.affinity.pod_anti_affinity.required[0].topology_key \
            == "kubernetes.io/hostname"
        assert api.get_resource_request(p2) == api.get_resource_request(p)
        assert p2.spec.volumes[0].source_kind == "GCEPersistentDisk"

    def test_node_round_trip(self):
        n = api.Node(
            metadata=api.ObjectMeta(name="n1", labels={api.LABEL_ZONE: "z"}),
            spec=api.NodeSpec(unschedulable=True,
                              taints=[api.Taint("k", "v", api.NO_EXECUTE)]),
            status=api.NodeStatus(
                allocatable=api.resource_list(cpu="4", memory="8Gi", pods=110),
                conditions=[api.NodeCondition(api.NODE_READY, api.COND_FALSE)],
                images=[api.ContainerImage(names=["img:1"], size_bytes=1 << 20)]))
        n2 = rt(n)
        assert n2.spec.unschedulable is True
        assert n2.spec.taints[0] == api.Taint("k", "v", api.NO_EXECUTE)
        assert n2.status.allocatable == n.status.allocatable
        assert n2.status.images[0].size_bytes == 1 << 20

    def test_workload_kinds_round_trip(self):
        sel = LabelSelector(match_labels={"app": "w"})
        tmpl = api.PodTemplateSpec(metadata=api.ObjectMeta(labels={"app": "w"}),
                                   spec=api.PodSpec(containers=[api.Container()]))
        objs = [
            api.Deployment(spec=api.DeploymentSpec(replicas=3, selector=sel,
                                                   template=tmpl)),
            api.ReplicaSet(spec=api.ReplicaSetSpec(replicas=2, selector=sel,
                                                   template=tmpl)),
            api.StatefulSet(spec=api.StatefulSetSpec(replicas=2, selector=sel)),
            api.DaemonSet(spec=api.DaemonSetSpec(selector=sel, template=tmpl)),
            api.Job(spec=api.JobSpec(parallelism=2, completions=4, selector=sel,
                                     template=tmpl)),
            api.CronJob(spec=api.CronJobSpec(schedule="*/5 * * * *")),
            api.PodDisruptionBudget(spec=api.PodDisruptionBudgetSpec(
                selector=sel, min_available=1)),
            api.Service(spec=api.ServiceSpec(selector={"app": "w"},
                                             ports=[api.ServicePort(port=80,
                                                                    target_port=8080)])),
            api.Endpoints(subsets=[api.EndpointSubset(
                addresses=[api.EndpointAddress(ip="10.0.0.1", node_name="n1")],
                ports=[api.EndpointPort(port=8080)])]),
            api.Namespace(metadata=api.ObjectMeta(name="ns1")),
            api.ResourceQuota(spec=api.ResourceQuotaSpec(hard={"pods": 10})),
            api.PriorityClass(metadata=api.ObjectMeta(name="high"), value=1000),
            api.EventObject(reason="Scheduled", message="ok",
                            involved_kind="Pod", involved_name="p"),
        ]
        for o in objs:
            o2 = rt(o)
            assert type(o2) is type(o)
            assert scheme.kind_of(o2) == scheme.kind_of(o)
        d2 = rt(objs[0])
        assert d2.spec.template.metadata.labels == {"app": "w"}
        assert d2.spec.selector.match_labels == {"app": "w"}

    def test_compat_selector_properties(self):
        # scheduler-side views preserved after the spec/status restructure
        assert api.Service(selector={"a": "b"}).selector == {"a": "b"}
        assert api.ReplicationController(selector={"a": "b"}).selector == {"a": "b"}
        sel = LabelSelector(match_labels={"a": "b"})
        assert api.ReplicaSet(selector=sel).selector is sel
        pdb = api.PodDisruptionBudget(selector=sel, disruptions_allowed=2)
        assert pdb.disruptions_allowed == 2 and pdb.selector is sel

    def test_plural_registry(self):
        assert scheme.kind_for_plural("pods") == "Pod"
        assert scheme.plural_for_kind("ReplicaSet") == "replicasets"
        assert not scheme.is_namespaced("Node")
        assert scheme.is_namespaced("Pod")

    def test_decode_unknown_kind(self):
        with pytest.raises(ValueError):
            scheme.decode_object({"kind": "Nope"})


class TestBroadcaster:
    def test_fanout_and_kind_filter(self):
        store = ObjectStore()
        b = Broadcaster(store)
        w_all = b.watch()
        w_pods = b.watch(kind="pods")
        store.create("pods", api.Pod(metadata=api.ObjectMeta(name="p1")))
        store.create("nodes", api.Node(metadata=api.ObjectMeta(name="n1")))
        evs = [w_all.next(timeout=1), w_all.next(timeout=1)]
        assert [e.kind for e in evs] == ["pods", "nodes"]
        ev = w_pods.next(timeout=1)
        assert ev.kind == "pods" and ev.obj.metadata.name == "p1"
        assert w_pods.next(timeout=0.01) is None

    def test_replay_from_rv(self):
        store = ObjectStore()
        b = Broadcaster(store)
        store.create("pods", api.Pod(metadata=api.ObjectMeta(name="p1")))
        rv1 = store.latest_resource_version
        store.create("pods", api.Pod(metadata=api.ObjectMeta(name="p2")))
        w = b.watch(kind="pods", since_rv=rv1)
        ev = w.next(timeout=1)
        assert ev.obj.metadata.name == "p2"

    def test_too_old(self):
        store = ObjectStore()
        b = Broadcaster(store, window=2)
        for i in range(5):
            store.create("pods", api.Pod(metadata=api.ObjectMeta(name=f"p{i}")))
        with pytest.raises(TooOld):
            b.watch(since_rv=1)

    def test_stop(self):
        store = ObjectStore()
        b = Broadcaster(store)
        w = b.watch()
        w.stop()
        store.create("pods", api.Pod(metadata=api.ObjectMeta(name="p1")))
        assert w.next(timeout=0.01) is None


class TestSelectorParse:
    """labels.Parse string syntax (apimachinery/pkg/labels/selector.go)."""

    def test_forms(self):
        from kubernetes_tpu.api.labels import Selector

        s = Selector.parse("a=1, b!=2, c in (x, y), d notin (z), e, !f")
        assert s.matches({"a": "1", "c": "y", "e": "ok"})
        assert not s.matches({"a": "1", "c": "y"})  # e missing
        assert not s.matches({"a": "1", "c": "y", "e": "ok", "f": "no"})
        assert not s.matches({"a": "1", "c": "q", "e": "ok"})
        assert not s.matches({"a": "1", "b": "2", "c": "x", "e": "ok"})
        assert Selector.parse("").matches({"anything": "at-all"})
        assert Selector.parse("k==v").matches({"k": "v"})

    def test_malformed(self):
        import pytest

        from kubernetes_tpu.api.labels import Selector

        for bad in ("k in (", "!k=v", "=v", "a=1,,b=2"):
            with pytest.raises(ValueError):
                Selector.parse(bad)
