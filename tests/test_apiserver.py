"""Integration tests: HTTP apiserver + client runtime.

Analog of the reference's test/integration pattern (framework/
master_utils.go startMasterOrDie behind httptest.Server): a real server
over a real store, real clients, no mocks. The capstone runs the actual
Scheduler against the server through RemoteStore — the in-process analog
of test/integration/scheduler/.
"""

import threading
import time

import pytest

from kubernetes_tpu.api import scheme
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client import (EventRecorder, LeaderElector, RESTClient,
                                   RemoteStore)
from kubernetes_tpu.client.rest import APIStatusError
from kubernetes_tpu.client.workqueue import (ItemExponentialFailureRateLimiter,
                                             RateLimitingQueue, WorkQueue)
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.server import (APIServer, AdmissionChain, RBACAuthorizer,
                                   TokenAuthenticator)
from kubernetes_tpu.server.auth import PolicyRule, RoleBinding, UserInfo


@pytest.fixture()
def server():
    store = ObjectStore()
    srv = APIServer(store, admission=AdmissionChain()).start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(server):
    return RESTClient(server.url)


def mkpod(name, ns="default", node="", cpu="100m"):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns, labels={"app": "w"}),
        spec=api.PodSpec(node_name=node, containers=[api.Container(
            resources=api.ResourceRequirements(
                requests=api.resource_list(cpu=cpu, memory="64Mi")))]))


def mknode(name, cpu="4"):
    return api.Node(
        metadata=api.ObjectMeta(name=name,
                                labels={api.LABEL_HOSTNAME: name}),
        status=api.NodeStatus(
            allocatable=api.resource_list(cpu=cpu, memory="8Gi", pods=110),
            conditions=[api.NodeCondition(api.NODE_READY, api.COND_TRUE)]))


class TestRESTCrud:
    def test_create_get_list_update_delete(self, client):
        client.create("pods", mkpod("p1"))
        got = client.get("pods", "default", "p1")
        assert got.metadata.name == "p1"
        assert got.metadata.resource_version > 0
        items, rv = client.list("pods")
        assert len(items) == 1 and rv >= got.metadata.resource_version
        got.spec.node_selector = {"disk": "ssd"}
        updated = client.update("pods", got)
        assert updated.spec.node_selector == {"disk": "ssd"}
        client.delete("pods", "default", "p1")
        with pytest.raises(APIStatusError) as ei:
            client.get("pods", "default", "p1")
        assert ei.value.code == 404

    def test_conflict_on_stale_rv(self, client):
        client.create("pods", mkpod("p1"))
        a = client.get("pods", "default", "p1")
        b = client.get("pods", "default", "p1")
        client.update("pods", a)
        with pytest.raises(APIStatusError) as ei:
            client.update("pods", b)
        assert ei.value.code == 409

    def test_duplicate_create_409(self, client):
        client.create("pods", mkpod("p1"))
        with pytest.raises(APIStatusError) as ei:
            client.create("pods", mkpod("p1"))
        assert ei.value.code == 409

    def test_label_and_field_selectors(self, client):
        client.create("pods", mkpod("p1", node="n1"))
        p2 = mkpod("p2")
        p2.metadata.labels = {"app": "other"}
        client.create("pods", p2)
        items, _ = client.list("pods", label_selector={"app": "w"})
        assert [p.metadata.name for p in items] == ["p1"]
        items, _ = client.list("pods", field_selector={"spec.nodeName": "n1"})
        assert [p.metadata.name for p in items] == ["p1"]

    def test_cluster_scoped_nodes(self, client):
        client.create("nodes", mknode("n1"))
        got = client.get("nodes", None, "n1")
        assert got.metadata.name == "n1"
        items, _ = client.list("nodes")
        assert len(items) == 1

    def test_patch_merge(self, client):
        client.create("pods", mkpod("p1"))
        out = client.patch("pods", "default", "p1",
                           {"metadata": {"labels": {"extra": "1"}}})
        assert out.metadata.labels == {"app": "w", "extra": "1"}

    def test_binding_subresource(self, client):
        client.create("pods", mkpod("p1"))
        client.bind("default", "p1", "n1")
        assert client.get("pods", "default", "p1").spec.node_name == "n1"
        with pytest.raises(APIStatusError) as ei:
            client.bind("default", "p1", "n2")
        assert ei.value.code == 409

    def test_status_subresource_keeps_spec(self, client):
        client.create("pods", mkpod("p1"))
        cur = client.get("pods", "default", "p1")
        cur.status.phase = "Running"
        out = client.update_status("pods", cur)
        assert out.status.phase == "Running"
        assert out.spec.containers  # spec preserved

    def test_eviction_respects_pdb(self, client):
        from kubernetes_tpu.api.labels import LabelSelector
        client.create("pods", mkpod("p1"))
        client.create("poddisruptionbudgets", api.PodDisruptionBudget(
            metadata=api.ObjectMeta(name="pdb"),
            selector=LabelSelector(match_labels={"app": "w"}),
            disruptions_allowed=0))
        with pytest.raises(APIStatusError) as ei:
            client.evict("default", "p1")
        assert ei.value.code == 429

    def test_healthz_version_metrics(self, server, client):
        import urllib.request
        assert urllib.request.urlopen(server.url + "/healthz").read() == b"ok"
        v = client.request("GET", "/version")
        assert v["minor"] == "11"
        client.create("pods", mkpod("px"))
        text = urllib.request.urlopen(server.url + "/metrics").read().decode()
        assert 'apiserver_request_count{verb="create",resource="pods"}' in text


class TestWatch:
    def test_watch_stream(self, server, client):
        seen = []
        done = threading.Event()

        def watch():
            for etype, obj in client.watch("pods", resource_version=0,
                                           timeout_seconds=5):
                seen.append((etype, obj.metadata.name))
                if len(seen) >= 2:
                    done.set()
                    return

        t = threading.Thread(target=watch, daemon=True)
        t.start()
        time.sleep(0.2)
        client.create("pods", mkpod("p1"))
        client.create("nodes", mknode("n1"))  # filtered out
        client.delete("pods", "default", "p1")
        assert done.wait(5)
        assert seen == [("ADDED", "p1"), ("DELETED", "p1")]

    def test_watch_410_on_too_old(self, server, client):
        server.broadcaster._window = 2
        for i in range(6):
            client.create("pods", mkpod(f"p{i}"))
        with pytest.raises(APIStatusError) as ei:
            for _ in client.watch("pods", resource_version=1, timeout_seconds=2):
                pass
        assert ei.value.code == 410


class TestAuth:
    def make(self):
        store = ObjectStore()
        authn = TokenAuthenticator({
            "admin-token": UserInfo("admin", ("system:masters",)),
            "view-token": UserInfo("viewer", ())}, allow_anonymous=False)
        authz = RBACAuthorizer([
            RoleBinding("system:masters", [PolicyRule(["*"], ["*"])]),
            RoleBinding("viewer", [PolicyRule(["get", "list", "watch"], ["*"])])])
        return APIServer(store, authenticator=authn, authorizer=authz).start()

    def test_authn_authz(self):
        srv = self.make()
        try:
            admin = RESTClient(srv.url, token="admin-token")
            view = RESTClient(srv.url, token="view-token")
            anon = RESTClient(srv.url)
            bad = RESTClient(srv.url, token="wrong")
            admin.create("pods", mkpod("p1"))
            assert view.get("pods", "default", "p1").metadata.name == "p1"
            with pytest.raises(APIStatusError) as ei:
                view.create("pods", mkpod("p2"))
            assert ei.value.code == 403
            with pytest.raises(APIStatusError) as ei:
                anon.list("pods")
            assert ei.value.code == 401
            with pytest.raises(APIStatusError) as ei:
                bad.list("pods")
            assert ei.value.code == 401
        finally:
            srv.stop()


class TestAdmission:
    def make(self):
        store = ObjectStore()
        # the ServiceAccount plugin requires the pod's SA to exist; in a
        # full stack the SA controller provides it per namespace
        store.create("serviceaccounts", api.ServiceAccount(
            metadata=api.ObjectMeta(name="default", namespace="default")))
        srv = APIServer(store, admission=AdmissionChain.default()).start()
        return srv, RESTClient(srv.url)

    def test_namespace_lifecycle(self):
        srv, client = self.make()
        try:
            with pytest.raises(APIStatusError) as ei:
                client.create("pods", mkpod("p1", ns="missing"))
            assert ei.value.code == 403
            client.create("namespaces", api.Namespace(
                metadata=api.ObjectMeta(name="made")))
            client.create("serviceaccounts", api.ServiceAccount(
                metadata=api.ObjectMeta(name="default", namespace="made")))
            client.create("pods", mkpod("p1", ns="made"))
        finally:
            srv.stop()

    def test_priority_resolution_and_default_tolerations(self):
        srv, client = self.make()
        try:
            client.create("priorityclasses", api.PriorityClass(
                metadata=api.ObjectMeta(name="high"), value=1000))
            p = mkpod("p1")
            p.spec.priority_class_name = "high"
            out = client.create("pods", p)
            assert out.spec.priority == 1000
            keys = {t.key for t in out.spec.tolerations}
            assert "node.kubernetes.io/not-ready" in keys
            assert "node.kubernetes.io/unreachable" in keys
        finally:
            srv.stop()

    def test_resource_quota(self):
        srv, client = self.make()
        try:
            client.create("resourcequotas", api.ResourceQuota(
                metadata=api.ObjectMeta(name="q"),
                spec=api.ResourceQuotaSpec(hard={"pods": 1})))
            client.create("pods", mkpod("p1"))
            with pytest.raises(APIStatusError) as ei:
                client.create("pods", mkpod("p2"))
            assert ei.value.code == 403
        finally:
            srv.stop()

    def test_node_restriction(self):
        store = ObjectStore()
        authn = TokenAuthenticator(
            {"kubelet-n1": UserInfo("system:node:n1", ("system:nodes",))})
        srv = APIServer(store, authenticator=authn,
                        admission=AdmissionChain.default()).start()
        try:
            RESTClient(srv.url).create("nodes", mknode("n1"))
            RESTClient(srv.url).create("nodes", mknode("n2"))
            kubelet = RESTClient(srv.url, token="kubelet-n1")
            n1 = kubelet.get("nodes", None, "n1")
            kubelet.update("nodes", n1)  # own node: allowed
            n2 = kubelet.get("nodes", None, "n2")
            with pytest.raises(APIStatusError) as ei:
                kubelet.update("nodes", n2)
            assert ei.value.code == 403
        finally:
            srv.stop()


class TestWorkqueue:
    def test_dedup(self):
        q = WorkQueue()
        q.add("a")
        q.add("a")
        assert len(q) == 1
        item = q.get(timeout=1)
        q.add("a")  # while processing: goes dirty, not queued
        assert len(q) == 0
        q.done(item)
        assert len(q) == 1

    def test_rate_limited_retry(self):
        rl = ItemExponentialFailureRateLimiter(base_delay=0.01, max_delay=1.0)
        assert rl.when("x") == 0.01
        assert rl.when("x") == 0.02
        rl.forget("x")
        assert rl.when("x") == 0.01

    def test_delaying(self):
        q = RateLimitingQueue()
        q.add_after("later", 0.05)
        assert q.get(timeout=0.02) is None
        got = q.get(timeout=2)
        assert got == "later"
        q.shut_down()


class TestLeaderElection:
    def test_single_leader_and_failover(self, server, client):
        store = RemoteStore(client)
        # the INVARIANTS under any scheduling jitter: (1) never two
        # leaders at once, (2) the standby takes over once the holder
        # stops renewing. Asserting "b has not acquired yet after N ms"
        # flakes under a loaded suite — a starved renewal thread makes
        # b's acquisition legitimate, not a bug.
        a = LeaderElector(store, "a", lease_duration=2.0, retry_period=0.05)
        b = LeaderElector(store, "b", lease_duration=2.0, retry_period=0.05)
        a_started = threading.Event()
        b_started = threading.Event()
        a.on_started_leading = a_started.set
        b.on_started_leading = b_started.set
        a.start()
        assert a_started.wait(10)
        b.start()
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline:
            assert not (a.is_leader and b.is_leader)  # never co-leaders
            time.sleep(0.02)
        a.stop()  # a stops renewing; b takes over after expiry
        assert b_started.wait(20)
        rec = store.get("leases", "default", "kube-scheduler")
        assert rec.holder_identity == "b"
        assert rec.leader_transitions >= 1
        b.stop()
        store.stop()


class TestEventRecorder:
    def test_aggregation(self, server, client):
        store = ObjectStore()
        rec = EventRecorder(store, "scheduler")
        pod = mkpod("p1")
        rec.event(pod, "Normal", "Scheduled", "bound to n1")
        rec.event(pod, "Normal", "Scheduled", "bound to n1")
        evs = store.list("events")
        assert len(evs) == 1 and evs[0].count == 2


class TestSelectorValidation:
    """Client input must produce 400s, not 500s, and field selectors on
    kinds lacking the field must match nothing (round-1 advisor
    finding)."""

    def test_malformed_label_selector_is_400(self, server, client):
        client.create("nodes", mknode("n1"))
        # a bare key is VALID set-based syntax (Exists) — labels.Parse
        # accepts it; only genuinely malformed input is a client error
        data = client.request("GET", "/api/v1/nodes",
                              query="labelSelector=some-absent-key")
        assert data["items"] == []
        with pytest.raises(APIStatusError) as ei:
            client.request("GET", "/api/v1/nodes",
                           query="labelSelector=k%20in%20(")
        assert ei.value.code == 400

    def test_nodename_selector_on_non_pods_matches_nothing(self, server,
                                                           client):
        client.create("nodes", mknode("n1"))
        data = client.request("GET", "/api/v1/nodes",
                              query="fieldSelector=spec.nodeName=n1")
        assert data["items"] == []

    def test_unknown_field_selector_is_400(self, server, client):
        with pytest.raises(APIStatusError) as ei:
            client.request("GET", "/api/v1/nodes",
                           query="fieldSelector=status.bogus=1")
        assert ei.value.code == 400


class TestRemoteStoreUpdateSemantics:
    def test_update_without_expect_rv_is_last_writer_wins(self, server,
                                                          client):
        """RemoteStore.update(expect_rv=None) must not 409 on mirror
        staleness — ObjectStore's drop-in contract is last-writer-wins
        (round-1 advisor finding: status writers swallow Conflict and
        silently dropped updates under churn)."""
        store = RemoteStore(client)
        store.mirror("nodes")
        store.wait_for_sync()
        client.create("nodes", mknode("n1"))
        deadline = time.monotonic() + 5
        while store.get("nodes", "default", "n1") is None \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        stale = store.get("nodes", "default", "n1")
        # another writer bumps the server-side rv past the mirror's copy
        fresh, _ = client.list("nodes")
        fresh[0].metadata.labels["x"] = "y"
        client.update("nodes", fresh[0])
        # stale-rv write with expect_rv=None must still land
        stale.status.volumes_in_use = ["pv9"]
        store.update("nodes", stale)
        got, _ = client.list("nodes")
        assert got[0].status.volumes_in_use == ["pv9"]
        store.stop()


class TestFlowControlAndDiscovery:
    def test_max_in_flight_429(self):
        """filters/maxinflight.go: requests beyond the bound get 429."""
        import threading as _t

        store = ObjectStore()
        srv = APIServer(store, admission=AdmissionChain(),
                        max_in_flight=1).start()
        release = _t.Event()
        try:
            client = RESTClient(srv.url)
            client.create("nodes", mknode("n1"))
            # occupy the single slot with a slow list via a store hook
            orig_list = store.list

            def slow_list(kind, namespace=None):
                if kind == "nodes":
                    release.wait(5)
                return orig_list(kind, namespace)

            store.list = slow_list
            t = _t.Thread(target=lambda: client.list("nodes"))
            t.start()
            time.sleep(0.2)  # let the slow request take the slot
            with pytest.raises(APIStatusError) as ei:
                client.list("nodes")
            assert ei.value.code == 429
            release.set()
            t.join()
            # slot free again: request succeeds
            items, _ = client.list("nodes")
            assert len(items) == 1
        finally:
            release.set()
            srv.stop()

    def test_resource_discovery(self, server, client):
        core = client.request("GET", "/api/v1")
        names = {r["name"] for r in core["resources"]}
        assert "pods" in names and "nodes" in names
        assert core["kind"] == "APIResourceList"
        apps = client.request("GET", "/apis/apps/v1")
        assert {"deployments", "replicasets"} <= \
            {r["name"] for r in apps["resources"]}

    def test_audit_policy_none_disables_sink(self):
        events = []
        store = ObjectStore()
        srv = APIServer(store, admission=AdmissionChain(),
                        audit_sink=events.append,
                        audit_policy="None").start()
        try:
            RESTClient(srv.url).create("nodes", mknode("n1"))
            assert events == []
        finally:
            srv.stop()


class TestSchedulerOverHTTP:
    """The real scheduler driving placements through the HTTP apiserver —
    the reference's test/integration/scheduler shape."""

    def test_schedule_pods_end_to_end(self, server, client):
        from kubernetes_tpu.sched.scheduler import Scheduler
        for i in range(4):
            client.create("nodes", mknode(f"n{i}"))
        store = RemoteStore(client)
        for k in ("pods", "nodes", "services", "replicationcontrollers",
                  "replicasets", "statefulsets", "poddisruptionbudgets"):
            store.mirror(k)
        store.wait_for_sync()
        sched = Scheduler(store, wave_size=16)
        for i in range(8):
            client.create("pods", mkpod(f"p{i}"))
        deadline = time.monotonic() + 30
        placed = 0
        while placed < 8 and time.monotonic() < deadline:
            placed += sched.run_once()
        sched.wait_for_binds()
        assert placed == 8
        bound, _ = client.list("pods")
        nodes_used = {p.spec.node_name for p in bound}
        assert all(p.spec.node_name for p in bound)
        assert len(nodes_used) == 4  # spread over all nodes
        store.stop()

    def test_async_bind_overlaps_waves(self, server, client):
        """The bind pipeline (reference scheduler.go:491 `go sched.bind`):
        with a slow bind POST, wall time must stay well under the serial
        sum and the in-flight high-water mark must exceed 1 — binds of
        wave N overlap each other and wave N+1."""
        from kubernetes_tpu.sched.scheduler import Scheduler
        for i in range(4):
            client.create("nodes", mknode(f"n{i}"))
        store = RemoteStore(client)
        for k in ("pods", "nodes", "services", "replicationcontrollers",
                  "replicasets", "statefulsets", "poddisruptionbudgets"):
            store.mirror(k)
        store.wait_for_sync()
        sched = Scheduler(store, wave_size=4)
        assert sched._bind_pool is not None  # REST store -> async binds
        orig_bind = store.bind

        def slow_bind(pod, node):
            time.sleep(0.05)
            return orig_bind(pod, node)

        store.bind = slow_bind
        for i in range(16):
            client.create("pods", mkpod(f"p{i}"))
        deadline = time.monotonic() + 30
        t0 = time.monotonic()
        placed = 0
        while placed < 16 and time.monotonic() < deadline:
            placed += sched.run_once(timeout=0.2)
        sched.wait_for_binds()
        wall = time.monotonic() - t0
        assert placed == 16
        bound, _ = client.list("pods")
        assert sum(1 for p in bound if p.spec.node_name) == 16
        assert sched.bind_overlap_hwm > 1
        assert wall < 16 * 0.05 + 0.5, f"binds serialized: {wall:.2f}s"
        store.stop()


class TestListChunking:
    """APIListChunking (?limit/?continue, 1.11 beta): deterministic
    pages, strict-after resumption, pager reassembly."""

    def test_pages_and_continue(self, server, client):
        for i in range(7):
            client.create("configmaps", api.ConfigMap(
                metadata=api.ObjectMeta(name=f"cm{i:02d}"), data={}))
        page1 = client.request("GET", "/api/v1/namespaces/default/configmaps",
                               query="limit=3")
        assert len(page1["items"]) == 3
        cont = page1["metadata"]["continue"]
        assert cont
        page2 = client.request("GET", "/api/v1/namespaces/default/configmaps",
                               query=f"limit=3&continue={cont}")
        names = [i["metadata"]["name"] for i in page1["items"] + page2["items"]]
        assert names == [f"cm{i:02d}" for i in range(6)]
        # last page has no continue
        cont2 = page2["metadata"]["continue"]
        page3 = client.request("GET", "/api/v1/namespaces/default/configmaps",
                               query=f"limit=3&continue={cont2}")
        assert len(page3["items"]) == 1
        assert "continue" not in page3["metadata"]

    def test_pager_reassembles_and_bad_token_400(self, server, client):
        for i in range(5):
            client.create("configmaps", api.ConfigMap(
                metadata=api.ObjectMeta(name=f"p{i}"), data={}))
        items, rv = client.list_paged("configmaps", "default", page_size=2)
        assert [o.metadata.name for o in items] == [f"p{i}" for i in range(5)]
        assert rv > 0
        with pytest.raises(APIStatusError) as ei:
            client.request("GET", "/api/v1/namespaces/default/configmaps",
                           query="limit=2&continue=%25%25not-b64")
        assert ei.value.code == 400


class TestDeleteCollection:
    def test_selector_scoped_server_side_delete(self, server, client):
        for i, app in enumerate(["a", "a", "b"]):
            p = mkpod(f"p{i}")
            p.metadata.labels = {"app": app}
            client.create("pods", p)
        client.delete_collection("pods", "default", label_selector="app=a")
        left = [p.metadata.name for p in server.store.list("pods")]
        assert left == ["p2"]
        # no selector = everything in the namespace
        client.delete_collection("pods", "default")
        assert server.store.list("pods") == []

    def test_deletecollection_is_its_own_rbac_verb(self):
        store = ObjectStore()
        authn = TokenAuthenticator({
            "t": UserInfo("bob", ())}, allow_anonymous=False)
        # bob may delete single objects but NOT deletecollection
        authz = RBACAuthorizer([
            RoleBinding("bob", [PolicyRule(["get", "list", "delete",
                                            "create"], ["*"])])])
        srv = APIServer(store, authenticator=authn, authorizer=authz,
                        admission=AdmissionChain()).start()
        try:
            c = RESTClient(srv.url, token="t")
            c.create("pods", mkpod("p1"))
            with pytest.raises(APIStatusError) as ei:
                c.delete_collection("pods", "default")
            assert ei.value.code == 403
            c.delete("pods", "default", "p1")  # single delete still fine
        finally:
            srv.stop()

    def test_finalizers_still_gate(self, server, client):
        p = mkpod("fin")
        p.metadata.finalizers = ["example.com/protect"]
        client.create("pods", p)
        client.delete_collection("pods", "default")
        # marked, not removed: deletion waits on the finalizer
        left = server.store.get("pods", "default", "fin")
        assert left is not None
        assert left.metadata.deletion_timestamp is not None


class TestServiceAllocation:
    """Service REST allocators (ipallocator/portallocator analogs)."""

    def mksvc(self, name, type="ClusterIP", cluster_ip="", node_port=0):
        return api.Service(
            metadata=api.ObjectMeta(name=name),
            spec=api.ServiceSpec(
                selector={"app": name}, type=type, cluster_ip=cluster_ip,
                ports=[api.ServicePort(port=80, node_port=node_port)]))

    def test_cluster_ip_assigned_and_unique(self, client):
        client.create("services", self.mksvc("a"))
        client.create("services", self.mksvc("b"))
        a = client.get("services", "default", "a")
        b = client.get("services", "default", "b")
        assert a.spec.cluster_ip.startswith("10.0.0.")
        assert b.spec.cluster_ip.startswith("10.0.0.")
        assert a.spec.cluster_ip != b.spec.cluster_ip
        # explicit collision is a 422 (ErrAllocated)
        with pytest.raises(APIStatusError) as ei:
            client.create("services", self.mksvc(
                "c", cluster_ip=a.spec.cluster_ip))
        assert ei.value.code == 422
        # headless stays None; ExternalName gets nothing
        client.create("services", self.mksvc("hl", cluster_ip="None"))
        assert client.get("services", "default",
                          "hl").spec.cluster_ip == "None"
        ext = self.mksvc("ext", type="ExternalName")
        ext.spec.external_name = "db.example.com"
        client.create("services", ext)
        assert client.get("services", "default",
                          "ext").spec.cluster_ip == ""

    def test_node_ports_assigned_and_unique(self, client):
        client.create("services", self.mksvc("np1", type="NodePort"))
        np1 = client.get("services", "default", "np1")
        port = np1.spec.ports[0].node_port
        assert 30000 <= port <= 32767
        with pytest.raises(APIStatusError) as ei:
            client.create("services", self.mksvc("np2", type="NodePort",
                                                 node_port=port))
        assert ei.value.code == 422
        # update switching type to NodePort allocates too
        client.create("services", self.mksvc("later"))
        svc = client.get("services", "default", "later")
        svc.spec.type = "NodePort"
        client.update("services", svc)
        got = client.get("services", "default", "later")
        assert got.spec.ports[0].node_port >= 30000
        assert got.spec.ports[0].node_port != port


class TestServiceTypeChangeReleasesNodePort:
    def test_nodeport_cleared_on_clusterip_downgrade(self, client):
        svc = api.Service(
            metadata=api.ObjectMeta(name="np"),
            spec=api.ServiceSpec(selector={"a": "b"}, type="NodePort",
                                 ports=[api.ServicePort(port=80)]))
        client.create("services", svc)
        got = client.get("services", "default", "np")
        port = got.spec.ports[0].node_port
        assert port >= 30000
        got.spec.type = "ClusterIP"
        client.update("services", got)
        got = client.get("services", "default", "np")
        assert got.spec.ports[0].node_port == 0
        # the released port is immediately reusable
        other = api.Service(
            metadata=api.ObjectMeta(name="np2"),
            spec=api.ServiceSpec(selector={"c": "d"}, type="NodePort",
                                 ports=[api.ServicePort(port=81,
                                                        node_port=port)]))
        client.create("services", other)

    def test_copied_uid_still_collides(self, client):
        a = api.Service(metadata=api.ObjectMeta(name="a"),
                        spec=api.ServiceSpec(selector={"x": "y"},
                                             ports=[api.ServicePort(port=80)]))
        client.create("services", a)
        live = client.get("services", "default", "a")
        clone = api.Service(
            metadata=api.ObjectMeta(name="b", uid=live.metadata.uid),
            spec=api.ServiceSpec(selector={"x": "y"},
                                 cluster_ip=live.spec.cluster_ip,
                                 ports=[api.ServicePort(port=80)]))
        with pytest.raises(APIStatusError) as ei:
            client.create("services", clone)
        assert ei.value.code == 422


class TestWatchSelector:
    def test_watch_with_selector_translates_transitions(self, server,
                                                        client):
        seen = []
        done = threading.Event()

        def watch():
            import urllib.request
            url = (server.url + "/api/v1/pods?watch=true"
                   "&labelSelector=tier%3Dgold&timeoutSeconds=6"
                   "&resourceVersion=0")
            import json as _json
            with urllib.request.urlopen(url, timeout=10) as resp:
                for raw in resp:
                    line = raw.strip()
                    if not line:
                        continue
                    ev = _json.loads(line)
                    seen.append((ev["type"],
                                 ev["object"]["metadata"]["name"]))
                    if len(seen) >= 3:
                        done.set()
                        return

        gold = mkpod("gold")
        gold.metadata.labels = {"tier": "gold"}
        client.create("pods", gold)  # matches: initial ADDED
        t = threading.Thread(target=watch, daemon=True)
        t.start()
        time.sleep(0.3)
        client.create("pods", mkpod("plain"))  # non-matching: dropped
        live = client.get("pods", "default", "plain")
        live.metadata.labels = {"tier": "gold"}
        client.update("pods", live)  # enters selector -> ADDED
        live = client.get("pods", "default", "plain")
        live.metadata.labels = {}
        client.update("pods", live)  # leaves selector -> DELETED
        assert done.wait(8), seen
        assert seen == [("ADDED", "gold"), ("ADDED", "plain"),
                        ("DELETED", "plain")]


class TestScaleSubresource:
    def _server(self):
        from kubernetes_tpu.server.admission import AdmissionChain
        store = ObjectStore()
        srv = APIServer(store, admission=AdmissionChain()).start()
        return store, srv

    def test_deployment_scale_get_put(self):
        from kubernetes_tpu.api.labels import LabelSelector
        store, srv = self._server()
        try:
            client = RESTClient(srv.url)
            dep = api.Deployment(
                metadata=api.ObjectMeta(name="web"),
                spec=api.DeploymentSpec(
                    replicas=3,
                    selector=LabelSelector(match_labels={"app": "web"}),
                    template=api.PodTemplateSpec(
                        metadata=api.ObjectMeta(labels={"app": "web"}),
                        spec=api.PodSpec(containers=[api.Container()]))))
            client.create("deployments", dep)
            sc = client.get_scale("deployments", "default", "web")
            assert sc["kind"] == "Scale"
            assert sc["spec"]["replicas"] == 3
            assert sc["status"]["selector"] == "app=web"
            client.update_scale("deployments", "default", "web", 5)
            got = client.get("deployments", "default", "web")
            assert got.spec.replicas == 5
            # kind without a scale mapping: 404, not a crash
            store.create("pods", api.Pod(
                metadata=api.ObjectMeta(name="p"),
                spec=api.PodSpec(containers=[api.Container()])))
            with pytest.raises(APIStatusError) as ei:
                client.get_scale("pods", "default", "p")
            assert ei.value.code == 404
        finally:
            srv.stop()

    def test_scale_validates_replicas(self):
        from kubernetes_tpu.api.labels import LabelSelector
        store, srv = self._server()
        try:
            client = RESTClient(srv.url)
            rs = api.ReplicaSet(
                metadata=api.ObjectMeta(name="rs"),
                spec=api.ReplicaSetSpec(
                    replicas=1,
                    selector=LabelSelector(match_labels={"a": "b"}),
                    template=api.PodTemplateSpec(
                        metadata=api.ObjectMeta(labels={"a": "b"}),
                        spec=api.PodSpec(containers=[api.Container()]))))
            client.create("replicasets", rs)
            with pytest.raises(APIStatusError) as ei:
                client.update_scale("replicasets", "default", "rs", -2)
            assert ei.value.code == 422
        finally:
            srv.stop()
