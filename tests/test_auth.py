"""Authn/authz depth: cluster PKI, SA JWTs, RBAC from API objects, and
the kubeadm TLS-bootstrap join flow.

Reference behaviors covered: x509 CommonNameUserConversion
(apiserver authentication/request/x509/x509.go:76), SA token validation
(pkg/serviceaccount/jwt.go), RBAC object evaluation
(plugin/pkg/auth/authorizer/rbac/rbac.go:74), node authorizer +
NodeRestriction, CSR signer issuing real certs
(pkg/controller/certificates/signer/)."""

import time

import pytest

# the PKI layer needs the optional cryptography package; without it this
# module must SKIP, not break collection for every marker-filtered run
pytest.importorskip("cryptography")

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.rest import APIStatusError, RESTClient
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.server import pki
from kubernetes_tpu.server import serviceaccount as sat
from kubernetes_tpu.server.auth import (ANONYMOUS, AuthenticatorChain,
                                        RBACAuthorizer, UserInfo)


class TestPKI:
    def test_csr_sign_and_verify(self):
        ca = pki.new_cluster_ca()
        key, csr = pki.make_csr("system:node:n1", ("system:nodes",))
        cert = ca.sign_csr(csr)
        got = ca.verify_client_cert(cert)
        assert got == ("system:node:n1", ["system:nodes"])

    def test_foreign_ca_rejected(self):
        ca1, ca2 = pki.new_cluster_ca(), pki.new_cluster_ca()
        _, csr = pki.make_csr("mallory")
        cert = ca2.sign_csr(csr)
        assert ca1.verify_client_cert(cert) is None

    def test_garbage_rejected(self):
        ca = pki.new_cluster_ca()
        assert ca.verify_client_cert("not a pem") is None

    def test_ensure_cluster_ca_is_stable(self):
        store = ObjectStore()
        a = pki.ensure_cluster_ca(store)
        b = pki.ensure_cluster_ca(store)
        assert a.ca_cert_pem == b.ca_cert_pem
        assert a.sa_signing_key == b.sa_signing_key


class TestServiceAccountTokens:
    def test_mint_verify_and_revoke(self):
        store = ObjectStore()
        sa = api.ServiceAccount(metadata=api.ObjectMeta(name="builder"))
        store.create("serviceaccounts", sa)
        store.create("secrets", api.Secret(
            metadata=api.ObjectMeta(name="builder-token")))
        tok = sat.mint("k", "default", "builder", sa.metadata.uid,
                       "builder-token")
        got = sat.verify("k", tok, store)
        assert got is not None
        name, groups, ns = got
        assert name == "system:serviceaccount:default:builder"
        assert "system:serviceaccounts" in groups and ns == "default"
        # wrong key
        assert sat.verify("other", tok, store) is None
        # deleting the Secret revokes
        store.delete("secrets", "default", "builder-token")
        assert sat.verify("k", tok, store) is None

    def test_recreated_sa_revokes(self):
        store = ObjectStore()
        sa = api.ServiceAccount(metadata=api.ObjectMeta(name="b"))
        store.create("serviceaccounts", sa)
        store.create("secrets", api.Secret(
            metadata=api.ObjectMeta(name="b-token")))
        tok = sat.mint("k", "default", "b", sa.metadata.uid, "b-token")
        store.delete("serviceaccounts", "default", "b")
        store.create("serviceaccounts", api.ServiceAccount(
            metadata=api.ObjectMeta(name="b")))
        assert sat.verify("k", tok, store) is None  # uid mismatch

    def test_controller_mints_verifiable_tokens(self):
        from kubernetes_tpu.controllers.serviceaccount import \
            ServiceAccountController

        store = ObjectStore()
        ctrl = ServiceAccountController(store)
        store.create("serviceaccounts", api.ServiceAccount(
            metadata=api.ObjectMeta(name="app")))
        ctrl.sync_all()
        sec = store.get("secrets", "default", "app-token")
        assert sec is not None
        ca = pki.ensure_cluster_ca(store)
        got = sat.verify(ca.sa_signing_key, sec.data["token"], store)
        assert got is not None
        assert got[0] == "system:serviceaccount:default:app"


class TestRBACFromObjects:
    def _server(self):
        store = ObjectStore()
        ca = pki.ensure_cluster_ca(store)
        authn = AuthenticatorChain(
            tokens={"admin-token": UserInfo("admin", ("system:masters",)),
                    "alice-token": UserInfo("alice", ("devs",))},
            store=store, ca=ca)
        authz = RBACAuthorizer(
            bindings=__import__(
                "kubernetes_tpu.server.auth", fromlist=["x"]
            ).cluster_admin_bindings(["system:masters"]),
            store=store)
        from kubernetes_tpu.server import APIServer

        srv = APIServer(store, authenticator=authn, authorizer=authz).start()
        return store, srv

    def test_role_binding_grants_at_runtime(self):
        store, srv = self._server()
        try:
            admin = RESTClient(srv.url, token="admin-token")
            alice = RESTClient(srv.url, token="alice-token")
            with pytest.raises(APIStatusError) as ei:
                alice.list("pods", "default")
            assert ei.value.code == 403
            # grant via SERVED API objects — no restart, no constructor
            admin.create("roles", api.Role(
                metadata=api.ObjectMeta(name="pod-reader",
                                        namespace="default"),
                rules=[api.RBACPolicyRule(verbs=["get", "list"],
                                          api_groups=[""],
                                          resources=["pods"])]))
            admin.create("rolebindings", api.RoleBinding(
                metadata=api.ObjectMeta(name="read-pods",
                                        namespace="default"),
                subjects=[api.RBACSubject(kind="Group", name="devs")],
                role_ref=api.RoleRef(kind="Role", name="pod-reader")))
            assert alice.list("pods", "default")[0] == []
            # namespaced: the same verb in another namespace still 403s
            with pytest.raises(APIStatusError) as ei:
                alice.list("pods", "other")
            assert ei.value.code == 403
            # and writes were never granted
            with pytest.raises(APIStatusError) as ei:
                alice.create("pods", api.Pod(
                    metadata=api.ObjectMeta(name="p")))
            assert ei.value.code == 403
            # revocation is live too
            admin.delete("rolebindings", "default", "read-pods")
            with pytest.raises(APIStatusError) as ei:
                alice.list("pods", "default")
            assert ei.value.code == 403
        finally:
            srv.stop()

    def test_resource_names_and_nonresource(self):
        authz = RBACAuthorizer(store=ObjectStore())
        store = authz._store
        store.create("clusterroles", api.ClusterRole(
            metadata=api.ObjectMeta(name="one-cm"),
            rules=[api.RBACPolicyRule(verbs=["get"], api_groups=[""],
                                      resources=["configmaps"],
                                      resource_names=["the-one"]),
                   api.RBACPolicyRule(verbs=["get"],
                                      non_resource_urls=["/healthz",
                                                         "/apis/*"])]))
        store.create("clusterrolebindings", api.ClusterRoleBinding(
            metadata=api.ObjectMeta(name="b"),
            subjects=[api.RBACSubject(kind="User", name="bob")],
            role_ref=api.RoleRef(kind="ClusterRole", name="one-cm")))
        bob = UserInfo("bob")
        assert authz.authorize(bob, "get", "configmaps", name="the-one")
        assert not authz.authorize(bob, "get", "configmaps", name="other")
        # resourceNames never match a collection request
        assert not authz.authorize(bob, "list", "configmaps")
        assert authz.authorize(bob, "get", "/healthz")
        assert authz.authorize(bob, "get", "/apis/apps/v1")
        assert not authz.authorize(bob, "get", "/metrics")

    def test_service_account_subject(self):
        authz = RBACAuthorizer(store=ObjectStore())
        store = authz._store
        store.create("clusterroles", api.ClusterRole(
            metadata=api.ObjectMeta(name="r"),
            rules=[api.RBACPolicyRule(verbs=["list"], api_groups=[""],
                                      resources=["nodes"])]))
        store.create("clusterrolebindings", api.ClusterRoleBinding(
            metadata=api.ObjectMeta(name="b"),
            subjects=[api.RBACSubject(kind="ServiceAccount", name="app",
                                      namespace="ci")],
            role_ref=api.RoleRef(kind="ClusterRole", name="r")))
        sa_user = UserInfo("system:serviceaccount:ci:app",
                           ("system:serviceaccounts",))
        assert authz.authorize(sa_user, "list", "nodes")
        other = UserInfo("system:serviceaccount:ci:other")
        assert not authz.authorize(other, "list", "nodes")


class TestSubresourceAuthz:
    def test_create_pods_does_not_imply_exec(self):
        """verbs=[create], resources=[pods] must NOT authorize
        pods/exec — subresources are their own RBAC attribute."""
        authz = RBACAuthorizer(store=ObjectStore())
        store = authz._store
        store.create("clusterroles", api.ClusterRole(
            metadata=api.ObjectMeta(name="deployer"),
            rules=[api.RBACPolicyRule(verbs=["create", "get"],
                                      api_groups=[""],
                                      resources=["pods"])]))
        store.create("clusterrolebindings", api.ClusterRoleBinding(
            metadata=api.ObjectMeta(name="b"),
            subjects=[api.RBACSubject(kind="User", name="dev")],
            role_ref=api.RoleRef(kind="ClusterRole", name="deployer")))
        dev = UserInfo("dev")
        assert authz.authorize(dev, "create", "pods")
        assert not authz.authorize(dev, "create", "pods/exec")
        assert not authz.authorize(dev, "get", "pods/log")
        # explicit subresource grant works
        store.create("clusterroles", api.ClusterRole(
            metadata=api.ObjectMeta(name="execer"),
            rules=[api.RBACPolicyRule(verbs=["create"], api_groups=[""],
                                      resources=["pods/exec"])]))
        store.create("clusterrolebindings", api.ClusterRoleBinding(
            metadata=api.ObjectMeta(name="b2"),
            subjects=[api.RBACSubject(kind="User", name="dev")],
            role_ref=api.RoleRef(kind="ClusterRole", name="execer")))
        assert authz.authorize(dev, "create", "pods/exec")

    def test_recreated_sa_gets_fresh_token(self):
        """Deleting + recreating an SA re-mints the token Secret for the
        new uid instead of keeping a permanently-invalid one."""
        from kubernetes_tpu.controllers.serviceaccount import \
            ServiceAccountController

        store = ObjectStore()
        ctrl = ServiceAccountController(store)
        store.create("serviceaccounts", api.ServiceAccount(
            metadata=api.ObjectMeta(name="app")))
        ctrl.sync_all()
        old = store.get("secrets", "default", "app-token").data["token"]
        store.delete("serviceaccounts", "default", "app")
        store.create("serviceaccounts", api.ServiceAccount(
            metadata=api.ObjectMeta(name="app")))
        ctrl.sync_all()
        new = store.get("secrets", "default", "app-token").data["token"]
        assert new != old
        ca = pki.ensure_cluster_ca(store)
        assert sat.verify(ca.sa_signing_key, new, store) is not None
        assert sat.verify(ca.sa_signing_key, old, store) is None


class TestAuthenticatorChain:
    def test_bad_bearer_is_401_even_with_anonymous(self):
        chain = AuthenticatorChain(tokens={}, allow_anonymous=True)
        assert chain.authenticate("Bearer nope") is None
        assert chain.authenticate(None) is ANONYMOUS

    def test_sa_jwt_and_tls_peer(self):
        store = ObjectStore()
        ca = pki.ensure_cluster_ca(store)
        chain = AuthenticatorChain(store=store, ca=ca)
        sa = api.ServiceAccount(metadata=api.ObjectMeta(name="app"))
        store.create("serviceaccounts", sa)
        store.create("secrets", api.Secret(
            metadata=api.ObjectMeta(name="app-token")))
        tok = sat.mint(ca.sa_signing_key, "default", "app",
                       sa.metadata.uid, "app-token")
        user = chain.authenticate(f"Bearer {tok}")
        assert user.name == "system:serviceaccount:default:app"
        # x509 identity arrives as the VERIFIED TLS peer subject (the
        # server extracts it from the handshake, never from a header)
        user = chain.authenticate_request({}, peer=("jane", ["ops"]))
        assert user.name == "jane" and "ops" in user.groups
        # a bad bearer is 401 even when a valid peer cert is present
        # (presented-credential-wins, like the reference's union chain)
        assert chain.authenticate_request(
            {"Authorization": "Bearer nope"}, peer=("jane", ["ops"])) is None

    def test_tls_handshake_rejects_foreign_and_keyless_certs(self):
        """The possession/trust checks the header path used to do by
        hand are now the TLS handshake's job: a cert from a foreign CA
        or a cert without its private key cannot complete a handshake."""
        from kubernetes_tpu.server import APIServer

        store = ObjectStore()
        ca = pki.ensure_cluster_ca(store)
        authn = AuthenticatorChain(tokens={}, store=store, ca=ca,
                                   allow_anonymous=False)
        srv = APIServer(store, authenticator=authn,
                        authorizer=RBACAuthorizer(store=store),
                        tls=ca).start()
        try:
            key, csr = pki.make_csr("jane", ("ops",))
            cert = ca.sign_csr(csr)
            good = RESTClient(srv.url, ca_cert_pem=ca.ca_cert_pem,
                              client_cert_pem=cert, client_key_pem=key)
            with pytest.raises(APIStatusError) as ei:
                good.list("clusterroles", None)
            assert ei.value.code == 403  # authenticated, not authorized
            # foreign CA cert: the handshake itself fails
            ca2 = pki.new_cluster_ca()
            key2, csr2 = pki.make_csr("mallory", ("ops",))
            bad = RESTClient(srv.url, ca_cert_pem=ca.ca_cert_pem,
                             client_cert_pem=ca2.sign_csr(csr2),
                             client_key_pem=key2)
            with pytest.raises(Exception) as ei:
                bad.list("clusterroles", None)
            assert not isinstance(ei.value, APIStatusError)
            # no client cert at all: 401 (anonymous disabled)
            anon = RESTClient(srv.url, ca_cert_pem=ca.ca_cert_pem)
            with pytest.raises(APIStatusError) as ei:
                anon.list("clusterroles", None)
            assert ei.value.code == 401
            # a client that does not trust the server's CA refuses to
            # talk to it (server verification direction)
            untrusting = RESTClient(srv.url,
                                    ca_cert_pem=ca2.ca_cert_pem)
            with pytest.raises(Exception) as ei:
                untrusting.list("clusterroles", None)
            assert not isinstance(ei.value, APIStatusError)
        finally:
            srv.stop()


class TestKubeadmSecureJoin:
    def test_join_bootstraps_kubelet_identity(self):
        """The verdict's 'done' bar: kubeadm init --secure serves HTTPS,
        join discovers the CA (cluster-info), obtains a kubelet
        credential via CSR with only the bootstrap token, and connects
        over mTLS; the kubelet's writes pass NodeRestriction under its
        own identity."""
        from kubernetes_tpu.cli.kubeadm import Cluster, join_with_csr

        cluster = Cluster(secure=True)
        cluster.store.create("namespaces", api.Namespace(
            metadata=api.ObjectMeta(name="default"),
            status=api.NamespaceStatus(phase="Active")))
        cluster.start()
        try:
            assert cluster.url.startswith("https://")
            key, cert, ca_pem = join_with_csr(cluster.url, "n1",
                                              cluster.bootstrap_token)
            assert "BEGIN CERTIFICATE" in cert
            assert ca_pem == cluster.ca.ca_cert_pem  # cluster-info TOFU
            kubelet = RESTClient(cluster.url, client_cert_pem=cert,
                                 client_key_pem=key, ca_cert_pem=ca_pem)
            # the node registers itself and heartbeats its own status
            kubelet.create("nodes", api.Node(
                metadata=api.ObjectMeta(name="n1", namespace="")))
            n1 = kubelet.get("nodes", "", "n1")
            assert n1.metadata.name == "n1"
            # another node's object is fenced off (NodeRestriction)
            admin = RESTClient(cluster.url, token=cluster.admin_token,
                               ca_cert_pem=ca_pem)
            admin.create("nodes", api.Node(
                metadata=api.ObjectMeta(name="n2", namespace="")))
            n2 = admin.get("nodes", "", "n2")
            with pytest.raises(APIStatusError) as ei:
                kubelet.update("nodes", n2)
            assert ei.value.code == 403
            # and the kubelet cannot touch RBAC at all
            with pytest.raises(APIStatusError) as ei:
                kubelet.list("clusterroles", None)
            assert ei.value.code == 403
            # nor sweep secrets — and NEVER the CA material in
            # kube-system (that would be a cluster-admin escalation)
            with pytest.raises(APIStatusError) as ei:
                kubelet.list("secrets", "default")
            assert ei.value.code == 403
            with pytest.raises(APIStatusError) as ei:
                kubelet.get("secrets", "kube-system", "cluster-ca")
            assert ei.value.code == 403
            # a stolen PUBLIC cert without the key is useless: the TLS
            # stack cannot present it without the key, so the thief is
            # system:anonymous — allowed only the cluster-info ConfigMap
            # (anonymous stays enabled for CA discovery, like the
            # reference's default) and denied everything else by RBAC
            thief = RESTClient(cluster.url, ca_cert_pem=ca_pem)
            with pytest.raises(APIStatusError) as ei:
                thief.get("nodes", "", "n1")
            assert ei.value.code == 403
            assert thief.get("configmaps", "kube-public",
                             "cluster-info").data["ca.crt"] == ca_pem
            # a re-join after restart works (fresh CSR name + key)
            key2, cert2, _ = join_with_csr(cluster.url, "n1",
                                           cluster.bootstrap_token)
            kubelet2 = RESTClient(cluster.url, client_cert_pem=cert2,
                                  client_key_pem=key2, ca_cert_pem=ca_pem)
            assert kubelet2.get("nodes", "", "n1").metadata.name == "n1"
            # the bootstrap token alone can NOT write nodes
            boot = RESTClient(cluster.url, token=cluster.bootstrap_token,
                              ca_cert_pem=ca_pem)
            with pytest.raises(APIStatusError) as ei:
                boot.create("nodes", api.Node(
                    metadata=api.ObjectMeta(name="n3", namespace="")))
            assert ei.value.code == 403
        finally:
            cluster.stop()


class TestCertRotation:
    def test_kubelet_rotates_before_expiry(self):
        """client-go util/certificate analog: past the rotation
        deadline the manager submits a fresh CSR under its CURRENT
        identity, the approver+signer issue a new cert, and the swapped
        credential keeps working over mTLS."""
        from kubernetes_tpu.cli.kubeadm import Cluster, join_with_csr
        from kubernetes_tpu.client.certmanager import (CertificateManager,
                                                       rest_submitter)

        cluster = Cluster(secure=True)
        cluster.store.create("namespaces", api.Namespace(
            metadata=api.ObjectMeta(name="default"),
            status=api.NamespaceStatus(phase="Active")))
        cluster.start()
        try:
            key, cert, ca_pem = join_with_csr(cluster.url, "n1",
                                              cluster.bootstrap_token)
            now = [time.time()]
            mgr = CertificateManager(
                "system:node:n1", ("system:nodes",), key, cert,
                submit=rest_submitter(cluster.url, ca_pem),
                clock=lambda: now[0])
            swapped = []
            mgr.on_rotate(lambda k, c: swapped.append(c))
            # inside the validity window: no rotation
            assert mgr.maybe_rotate() is False
            assert mgr.rotations == 0
            # jump past 80% of the cert's lifetime
            now[0] = mgr.rotation_deadline() + 1
            assert mgr.maybe_rotate() is True
            assert mgr.rotations == 1 and len(swapped) == 1
            new_key, new_cert = mgr.current()
            assert new_cert != cert and new_key != key
            # the ROTATED identity authenticates and still passes
            # NodeRestriction as system:node:n1
            kubelet = RESTClient(cluster.url, client_cert_pem=new_cert,
                                 client_key_pem=new_key,
                                 ca_cert_pem=ca_pem)
            kubelet.create("nodes", api.Node(
                metadata=api.ObjectMeta(name="n1", namespace="")))
            assert kubelet.get("nodes", "", "n1").metadata.name == "n1"
        finally:
            cluster.stop()

    def test_node_cannot_mint_another_nodes_cert(self):
        """sarapprove isSelfNodeClientCert: the CSR subject must name
        the REQUESTOR — n1 asking for system:node:n2 is never
        auto-approved."""
        from kubernetes_tpu.cli.kubeadm import Cluster, join_with_csr
        from kubernetes_tpu.server import pki

        cluster = Cluster(secure=True)
        cluster.store.create("namespaces", api.Namespace(
            metadata=api.ObjectMeta(name="default"),
            status=api.NamespaceStatus(phase="Active")))
        cluster.start()
        try:
            key, cert, ca_pem = join_with_csr(cluster.url, "n1",
                                              cluster.bootstrap_token)
            n1 = RESTClient(cluster.url, client_cert_pem=cert,
                            client_key_pem=key, ca_cert_pem=ca_pem)
            _key2, csr_pem = pki.make_csr("system:node:n2",
                                          ("system:nodes",))
            n1.create("certificatesigningrequests",
                      api.CertificateSigningRequest(
                          metadata=api.ObjectMeta(name="evil-csr",
                                                  namespace=""),
                          spec=api.CertificateSigningRequestSpec(
                              request=csr_pem,
                              usages=["digital signature",
                                      "key encipherment",
                                      "client auth"])))
            deadline = time.time() + 2.0
            while time.time() < deadline:
                got = n1.get("certificatesigningrequests", "", "evil-csr")
                assert not got.status.certificate, \
                    "impersonation CSR was signed!"
                assert not got.approved
                time.sleep(0.1)
        finally:
            cluster.stop()

    def test_node_cannot_self_approve_csr(self):
        """The rotation grant is CREATE-only: a node writing its own
        Approved condition (or rewriting spec.username) must be 403'd —
        update rights on CSRs would let any kubelet mint arbitrary
        identities through the signer."""
        from kubernetes_tpu.cli.kubeadm import Cluster, join_with_csr
        from kubernetes_tpu.server import pki

        cluster = Cluster(secure=True)
        cluster.store.create("namespaces", api.Namespace(
            metadata=api.ObjectMeta(name="default"),
            status=api.NamespaceStatus(phase="Active")))
        cluster.start()
        try:
            key, cert, ca_pem = join_with_csr(cluster.url, "n1",
                                              cluster.bootstrap_token)
            n1 = RESTClient(cluster.url, client_cert_pem=cert,
                            client_key_pem=key, ca_cert_pem=ca_pem)
            _k, csr_pem = pki.make_csr("admin", ("system:masters",))
            n1.create("certificatesigningrequests",
                      api.CertificateSigningRequest(
                          metadata=api.ObjectMeta(name="esc-csr",
                                                  namespace=""),
                          spec=api.CertificateSigningRequestSpec(
                              request=csr_pem,
                              usages=["digital signature",
                                      "key encipherment",
                                      "client auth"])))
            got = n1.get("certificatesigningrequests", "", "esc-csr")
            got.status.conditions = [("Approved", "self-approved!")]
            with pytest.raises(APIStatusError) as ei:
                n1.update("certificatesigningrequests", got)
            assert ei.value.code == 403
            # and the approver never signs a masters subject
            time.sleep(0.5)
            got = n1.get("certificatesigningrequests", "", "esc-csr")
            assert not got.approved and not got.status.certificate
        finally:
            cluster.stop()
