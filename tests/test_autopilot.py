"""Scheduler autopilot: offline trainer + gated auto-promotion (ISSUE 16).

Property groups:

  1. LEDGER ROTATION — the round ledger rotates to <path>.1 before
     exceeding its byte cap (counter-visible), 0 disables, and the
     dataset loader reads the rotated generation oldest-first.
  2. DATASET — ledger JSONL streams into dense feature/outcome
     matrices tolerant of unknown keys, mixed schema versions,
     recordless rounds, and torn lines (the ignore-unknown-keys
     ledger contract, exercised).
  3. TRAINER — the ridge fit boosts the priority whose contribution
     share correlates with round quality (bounded by `step`),
     introduces zero-base priorities only on positive evidence, fails
     loudly below the evidence floor, and emits candidates through the
     store watch path. The policy-gradient seam stays a seam.
  4. REPLAY CI — the storm trace-replay gate passes the static
     defaults and shares its SLO constants with bench.py bitwise.
  5. PROMOTION PIPELINE E2E — a trainer-emitted candidate passes the
     shadow + replay gates and goes live with ZERO recompiles
     (cache-size asserted); a seeded regression candidate is rejected
     at the shadow gate; force-promoted anyway, the regression watch
     auto-rolls-back and restores the prior live vector — every
     transition ledgered (kind "autopilot"), metered, and served at
     /debug/autopilot. Candidate deletion mid-gating aborts cleanly.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from helpers import make_node, make_pod
from kubernetes_tpu.api import types as api
from kubernetes_tpu.autopilot import (AutopilotConfig, AutopilotController,
                                      OUTCOMES, workload_profiles_path)
from kubernetes_tpu.autopilot.dataset import (FEATURES, build_dataset,
                                              load_dataset, load_records,
                                              round_quality)
from kubernetes_tpu.autopilot.replay import (STORM_PRIORITY, STORM_SLO_P99,
                                             run_replay)
from kubernetes_tpu.autopilot.trainer import (PolicyGradientTrainer,
                                              RidgeTrainer, emit_candidate)
from kubernetes_tpu.plugins.registry import default_profile
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.utils import faultpoints, tracing

pytestmark = pytest.mark.autopilot

# replay CI shape used throughout: matches the live test cluster (3
# 8-core nodes, wave 8) so a promotion adds zero jit entries, with SLO
# headroom for contended CI hosts
_REPLAY_KW = dict(replay_nodes=3, replay_wave=8, replay_slo_scale=4.0)


@pytest.fixture(autouse=True)
def _tracing_off():
    tracing.disable()
    yield
    tracing.disable()


def _profile(name, weights, role="candidate"):
    return api.WeightProfile(
        metadata=api.ObjectMeta(name=name),
        spec=api.WeightProfileSpec(weights=weights, role=role))


def _skewed_cluster():
    """3 identical nodes at strictly distinct usage (6/3/0 cores of 8):
    LeastRequested-family defaults pick n2, MostRequested strictly
    prefers n0 — flips are strict, margins ~4 score units."""
    rec = tracing.enable()
    store = ObjectStore()
    sched = Scheduler(store, wave_size=8)
    for i in range(3):
        store.create("nodes", make_node(f"n{i}", cpu="8"))
    for i in range(6):
        p = make_pod(f"pre0-{i}", cpu="1")
        p.spec.node_name = "n0"
        store.create("pods", p)
    for i in range(3):
        p = make_pod(f"pre1-{i}", cpu="1")
        p.spec.node_name = "n1"
        store.create("pods", p)
    return rec, store, sched


def _controller(sched, store, **over):
    kw = dict(min_shadow_pods=3, watch_rounds=2, watch_margin_floor=1.0,
              **_REPLAY_KW)
    kw.update(over)
    return AutopilotController(sched, store=store,
                               config=AutopilotConfig(**kw))


def _run_rounds(store, sched, n, tag):
    for i in range(n):
        store.create("pods", make_pod(f"{tag}-{i}", cpu="100m"))
        assert sched.schedule_pending() == 1


def _round_rec(rid, util, frag, breakdown, version="static", **extra):
    """A synthetic v2 round-ledger record with a scores aggregate."""
    total = float(sum(breakdown.values()))
    rec = {"v": 2, "round": rid, "kind": "round", "placed": 8,
           "pending": 0, "wall_s": 0.01, "weights_version": version,
           "scores": {"min": total, "max": total, "mean": total,
                      "breakdown": dict(breakdown),
                      "margin": {"min": 1.0, "mean": 2.0, "max": 4.0}},
           "telemetry": {"util": {"cpu": util}, "frag": {"cpu": frag}}}
    rec.update(extra)
    return rec


# ---------------------------------------------------------------------------
# 1. ledger rotation


class TestLedgerRotation:
    def test_rotates_with_counter(self, tmp_path):
        path = str(tmp_path / "rounds.jsonl")
        rec = tracing.FlightRecorder(ledger_path=path,
                                     ledger_max_bytes=400)
        for i in range(20):
            rec.append_record("autopilot", state="shadowing",
                              profile=f"cand-{i:04d}")
        assert rec.ledger_rotations >= 1
        assert (tmp_path / "rounds.jsonl.1").exists()
        # every surviving line in BOTH generations still parses
        for p in (path + ".1", path):
            for line in open(p):
                assert json.loads(line)["kind"] == "autopilot"
        # the live file respects the cap (rotation happens BEFORE the
        # write that would exceed it)
        import os

        assert os.path.getsize(path) <= 400

    def test_zero_cap_disables_rotation(self, tmp_path):
        path = str(tmp_path / "rounds.jsonl")
        rec = tracing.FlightRecorder(ledger_path=path, ledger_max_bytes=0)
        for i in range(50):
            rec.append_record("autopilot", state="x", profile="p")
        assert rec.ledger_rotations == 0
        assert not (tmp_path / "rounds.jsonl.1").exists()
        assert rec.ledger_records == 50

    def test_loader_reads_rotated_generation_first(self, tmp_path):
        path = str(tmp_path / "rounds.jsonl")
        with open(path + ".1", "w") as f:
            f.write(json.dumps({"v": 2, "round": 1}) + "\n")
        with open(path, "w") as f:
            f.write(json.dumps({"v": 2, "round": 2}) + "\n")
        records, skipped = load_records(path)
        assert [r["round"] for r in records] == [1, 2]
        assert skipped == 0


# ---------------------------------------------------------------------------
# 2. dataset robustness


class TestDatasetRobustness:
    def test_unknown_keys_mixed_versions_torn_lines(self, tmp_path):
        path = str(tmp_path / "rounds.jsonl")
        rows = [
            _round_rec(1, 0.5, 0.2, {"LeastRequested": 8.0}),
            # unknown keys ride along untouched (the ledger contract)
            _round_rec(2, 0.6, 0.1, {"LeastRequested": 9.0},
                       version="cand@3", future_key={"x": 1}),
            # a v99 record with a scores aggregate still trains
            _round_rec(3, 0.4, 0.3, {"BalancedAllocation": 2.0}, v=99),
            # recordless rounds / transition records are skipped
            {"v": 2, "round": 4, "kind": "autopilot", "state": "promoted"},
            {"v": 1, "round": 5, "placed": 3},
        ]
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
            f.write("[1, 2, 3]\n")        # decodable, not a record
            f.write('{"torn": "lin')      # a crash mid-write
        ds = load_dataset(path)
        assert len(ds) == 3
        assert ds.features.shape == (3, len(FEATURES))
        assert ds.skipped == 4  # 2 recordless + 1 non-dict + 1 torn
        assert ds.versions[1] == "cand@3"
        assert set(ds.active_priorities()) == {"LeastRequested",
                                               "BalancedAllocation"}

    def test_missing_file_is_empty_dataset(self, tmp_path):
        ds = load_dataset(str(tmp_path / "nope.jsonl"))
        assert len(ds) == 0
        assert ds.skipped == 0

    def test_round_quality_prefers_packed_decisive_rounds(self):
        good = _round_rec(1, 0.9, 0.1, {"LeastRequested": 8.0})
        bad = _round_rec(2, 0.2, 0.8, {"LeastRequested": 8.0})
        assert round_quality(good) > round_quality(bad)


# ---------------------------------------------------------------------------
# 3. trainer


def _planted_records(n=16, prio="LeastRequested", anti="BalancedAllocation",
                     invert=False):
    """Rounds where `prio`'s contribution share tracks round quality
    (utilization) and `anti`'s anti-tracks it — the signal a fit must
    recover. invert=True flips the correlation."""
    out = []
    for i in range(n):
        share = i / (n - 1)
        util = 0.2 + 0.6 * ((1 - share) if invert else share)
        out.append(_round_rec(i, util, 0.2,
                              {prio: 1.0 + 9.0 * share,
                               anti: 1.0 + 9.0 * (1 - share)}))
    return out


class TestRidgeTrainer:
    def _base(self):
        return default_profile(None).weights()

    def test_boosts_correlated_priority_bounded_by_step(self):
        trainer = RidgeTrainer(self._base(), step=0.5)
        out = trainer.fit(build_dataset(_planted_records()))
        # LeastRequested (base 1.0) moves up, BalancedAllocation down,
        # each by at most `step` of its base
        assert 1.0 < out["LeastRequested"] <= 1.5
        assert 0.5 <= out["BalancedAllocation"] < 1.0
        # priorities with no evidence keep their base weight
        assert out["PreferAvoid"] == 10000.0

    def test_zero_base_priority_needs_positive_evidence(self):
        # MostRequested has base weight 0; positive correlation
        # introduces it, negative correlation must NOT (negative
        # evidence about an inactive plane keeps it off)
        up = RidgeTrainer(self._base()).fit(build_dataset(
            _planted_records(prio="MostRequested")))
        assert up.get("MostRequested", 0.0) > 0.0
        down = RidgeTrainer(self._base()).fit(build_dataset(
            _planted_records(prio="MostRequested", invert=True)))
        assert "MostRequested" not in down

    def test_evidence_floor_and_no_signal_errors(self):
        trainer = RidgeTrainer(self._base(), min_rounds=4)
        with pytest.raises(ValueError, match="scored rounds"):
            trainer.fit(build_dataset(_planted_records(n=3)))
        # rounds whose breakdowns carry no tunable contribution
        blank = [_round_rec(i, 0.5, 0.2, {"HostExtra": 5.0})
                 for i in range(8)]
        with pytest.raises(ValueError, match="no tunable"):
            trainer.fit(build_dataset(blank))

    def test_policy_gradient_is_a_seam(self):
        with pytest.raises(NotImplementedError, match="policy-gradient"):
            PolicyGradientTrainer(self._base()).fit(
                build_dataset(_planted_records()))

    def test_train_faultpoint(self):
        trainer = RidgeTrainer(self._base())
        with faultpoints.injected("autopilot.train", "raise"):
            with pytest.raises(faultpoints.FaultInjected):
                trainer.fit(build_dataset(_planted_records()))
        assert faultpoints.hits("autopilot.train") == 1

    def test_emit_candidate_through_store_watch_path(self):
        store = ObjectStore()
        sched = Scheduler(store, wave_size=8)
        try:
            emit_candidate(store, "trained", {"LeastRequested": 1.4})
            # the scheduler's informer loaded it — same path as an
            # operator-applied WeightProfile
            assert sched.weightbook.has_profile("trained")
            wp = store.get("weightprofiles", "default", "trained")
            assert wp.spec.role == api.WEIGHT_PROFILE_ROLE_CANDIDATE
            # a retrain supersedes in place (and re-demotes to candidate)
            wp.spec.role = api.WEIGHT_PROFILE_ROLE_LIVE
            store.update("weightprofiles", wp)
            emit_candidate(store, "trained", {"LeastRequested": 1.8})
            wp2 = store.get("weightprofiles", "default", "trained")
            assert wp2.spec.weights == {"LeastRequested": 1.8}
            assert wp2.spec.role == api.WEIGHT_PROFILE_ROLE_CANDIDATE
        finally:
            sched.close()


# ---------------------------------------------------------------------------
# 4. replay CI


class TestReplayCI:
    def test_baseline_replay_passes_gates(self):
        rep = run_replay(None, nodes=3, wave=8, slo_scale=4.0)
        assert rep.passed and not rep.failures
        assert rep.placed == rep.total > 0
        assert rep.version == "static"
        assert 0.0 < rep.util <= 1.0
        assert set(rep.p99) <= set(STORM_PRIORITY)
        json.dumps(rep.as_dict())  # /debug + CI output must serialize

    def test_storm_gates_shared_with_bench(self):
        # bench.py's storm harness and the promotion CI must gate on the
        # SAME objects — drift-proof by identity, not equality
        import bench

        assert bench.STORM_SLO_P99 is STORM_SLO_P99
        assert bench.STORM_PRIORITY is STORM_PRIORITY


# ---------------------------------------------------------------------------
# 5. promotion pipeline end to end


class TestPromotionPipeline:
    def test_trained_candidate_promoted_with_zero_recompiles(self):
        from kubernetes_tpu.ops.kernel import _schedule_round

        rec, store, sched = _skewed_cluster()
        try:
            ctl = _controller(sched, store)
            # offline half: fit on a planted ledger, emit the candidate
            # through the store watch path
            trained = RidgeTrainer(default_profile(None).weights()).fit(
                build_dataset(_planted_records()))
            assert trained["LeastRequested"] > 1.0
            emit_candidate(store, "trained", trained)
            assert ctl.start("trained") == "shadowing"
            # live traffic accumulates shadow evidence; the boosted
            # table agrees with production on this cluster (no flips)
            _run_rounds(store, sched, 3, "gate")
            cache0 = _schedule_round._cache_size()
            assert ctl.step() == "watching"
            assert ctl.outcome == "promoted"
            live = sched.weightbook.live_version()
            assert live.startswith("trained@")
            # THE acceptance bit: gates + promotion + replay CI added
            # zero jit entries — the swap is a traced value
            assert _schedule_round._cache_size() == cache0
            # clean watch window completes the run
            _run_rounds(store, sched, 2, "watch")
            assert ctl.state == "completed"
            assert _schedule_round._cache_size() == cache0
            # transitions ledgered + metered + reported
            states = [r["state"] for r in rec.ledger_rows()
                      if r.get("kind") == "autopilot"]
            assert states == ["shadowing", "replaying", "promoted",
                              "watching", "completed"]
            assert sched.metrics.autopilot_promotions.value(
                outcome="promoted") == 1
            assert ctl.reports["shadow"]["flip_rate"] <= 0.25
            assert ctl.reports["replay"]["candidate"]["passed"] is True
            # post-promotion rounds carry the candidate's version
            placed = [r for r in rec.ledger_rows() if r.get("placed")]
            assert placed[-1]["weights_version"] == live
        finally:
            sched.close()

    def test_regression_candidate_rejected_at_shadow_gate(self):
        rec, store, sched = _skewed_cluster()
        try:
            ctl = _controller(sched, store)
            # MostRequested flips EVERY placement on the skewed cluster.
            # ImageLocality (inert: no images) rides along so the gating
            # set this test compiles ({MostRequested, ImageLocality})
            # stays disjoint from the {MostRequested} set test_shadow's
            # promote-compiles-once assertion expects to compile fresh —
            # the jit cache is process-global across test files.
            emit_candidate(store, "packer",
                           {"MostRequested": 5.0, "ImageLocality": 0.5})
            ctl.start("packer")
            _run_rounds(store, sched, 4, "gate")
            assert ctl.step() == "rejected_shadow"
            assert ctl.reports["shadow"]["flip_rate"] == 1.0
            # nothing promoted, pre-compile gating dropped
            assert sched.weightbook.live_version() == "static"
            assert "gating" not in \
                sched.weightbook.index()["profiles"]["packer"]
            assert sched.metrics.autopilot_promotions.value(
                outcome="rejected_shadow") == 1
        finally:
            sched.close()

    def test_force_promoted_regression_auto_rolled_back(self):
        rec, store, sched = _skewed_cluster()
        try:
            # a prior live profile proves rollback restores IT, not
            # just the static defaults
            store.create("weightprofiles",
                         _profile("good", {"LeastRequested": 2.0,
                                           "PreferAvoid": 10000.0},
                                  role="live"))
            prior = sched.weightbook.live_version()
            assert prior.startswith("good@")
            ctl = _controller(sched, store)
            # near-zero weights collapse decision margins (~0.002 vs
            # the ~4.0 the watch floor of 1.0 expects)
            emit_candidate(store, "tiny", {"LeastRequested": 0.001})
            ctl.start("tiny", force=True)
            assert ctl.step() == "watching"
            assert sched.weightbook.live_version().startswith("tiny@")
            # first watched round breaches the margin floor -> the
            # observer demotes IN MEMORY before the next round
            _run_rounds(store, sched, 1, "breach")
            assert ctl.state == "rolled_back"
            assert sched.weightbook.live_version() == prior
            reason = ctl.history[-1]["reason"]
            assert "margin" in reason
            # the next round is decided (and ledgered) by the restored
            # vector
            _run_rounds(store, sched, 1, "after")
            placed = [r for r in rec.ledger_rows() if r.get("placed")]
            assert placed[-1]["weights_version"] == prior
            # step() reconciles the store object the observer could not
            # touch (deadlock-free rollback is in-memory only)
            ctl.step()
            assert store.get("weightprofiles", "default",
                             "tiny").spec.role == \
                api.WEIGHT_PROFILE_ROLE_CANDIDATE
            states = [r["state"] for r in rec.ledger_rows()
                      if r.get("kind") == "autopilot"]
            assert states == ["shadowing", "promoted", "watching",
                              "rolled_back"]
            assert sched.metrics.autopilot_promotions.value(
                outcome="promoted") == 1
            assert sched.metrics.autopilot_promotions.value(
                outcome="rolled_back") == 1
        finally:
            sched.close()

    def test_candidate_deleted_mid_gating_aborts(self):
        rec, store, sched = _skewed_cluster()
        try:
            ctl = _controller(sched, store)
            emit_candidate(store, "ghost", {"LeastRequested": 1.2})
            ctl.start("ghost")
            _run_rounds(store, sched, 1, "gate")
            store.delete("weightprofiles", "default", "ghost")
            assert ctl.step() == "aborted"
            assert "deleted" in ctl.history[-1]["reason"]
            assert sched.weightbook.live_version() == "static"
            assert sched.metrics.autopilot_promotions.value(
                outcome="aborted") == 1
            # the controller is reusable after a terminal state
            emit_candidate(store, "next", {"LeastRequested": 1.2})
            assert ctl.start("next") == "shadowing"
        finally:
            sched.close()

    def test_promote_faultpoint_aborts_cleanly(self):
        rec, store, sched = _skewed_cluster()
        try:
            ctl = _controller(sched, store)
            emit_candidate(store, "cand", {"LeastRequested": 1.2})
            ctl.start("cand", force=True)
            with faultpoints.injected("autopilot.promote", "raise"):
                assert ctl.step() == "aborted"
            # the most dangerous instant failed: nothing went live, the
            # gating flag was dropped
            assert sched.weightbook.live_version() == "static"
            assert "gating" not in \
                sched.weightbook.index()["profiles"]["cand"]
            assert sched.metrics.autopilot_promotions.value(
                outcome="aborted") == 1
        finally:
            sched.close()

    def test_outcomes_match_declared_metric_values(self):
        from kubernetes_tpu.utils.metrics import Metrics

        decl = Metrics().autopilot_promotions.decl
        assert set(decl.values["outcome"]) == set(OUTCOMES)

    def test_debug_autopilot_endpoint(self):
        from kubernetes_tpu.cli.kube_scheduler import HealthServer

        rec, store, sched = _skewed_cluster()
        hs = HealthServer(lambda: sched)
        try:
            def get(path):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{hs.port}{path}") as r:
                    return r.read().decode()

            # no controller attached yet -> 404, not a crash
            with pytest.raises(urllib.error.HTTPError) as ei:
                get("/debug/autopilot")
            assert ei.value.code == 404
            ctl = _controller(sched, store)
            emit_candidate(store, "cand", {"LeastRequested": 1.2})
            ctl.start("cand")
            status = json.loads(get("/debug/autopilot"))
            assert status["state"] == "shadowing"
            assert status["candidate"] == "cand"
            assert status["history"][0]["state"] == "shadowing"
            assert status["weights_version"] == "static"
            assert status["config"]["watch_rounds"] == 2
        finally:
            hs.stop()
            sched.close()


# ---------------------------------------------------------------------------
# 6. the checked-in per-workload weight table


class TestWorkloadProfiles:
    def test_table_loads_as_candidate_pool(self):
        rec, store, sched = _skewed_cluster()
        try:
            n = sched.weightbook.load_file(workload_profiles_path())
            assert n == 4
            idx = sched.weightbook.index()["profiles"]
            assert set(idx) == {"density", "trickle", "gang", "storm"}
            # all candidates: nothing goes live by checking in a file
            assert sched.weightbook.live_version() == "static"
            # each entry is a valid autopilot candidate: the controller
            # opens a gating window on one directly
            ctl = _controller(sched, store)
            assert ctl.start("density") == "shadowing"
            assert sched.weightbook.index()["profiles"]["density"][
                "gating"] is True
        finally:
            sched.close()

    def test_profiles_shape_density_vs_trickle(self):
        # the tables encode opposite packing intents; guard the file
        # against a refactor flattening them into one
        entries = {e["name"]: e["weights"] for e in
                   json.load(open(workload_profiles_path()))}
        assert entries["density"]["MostRequested"] > 0
        assert "LeastRequested" not in entries["density"]
        assert entries["trickle"]["LeastRequested"] >= 2
        assert "MostRequested" not in entries["trickle"]
        assert entries["gang"]["InterPodAffinity"] >= \
            max(v for k, v in entries["gang"].items()
                if k != "PreferAvoid" and k != "InterPodAffinity")
