"""Cluster-autoscaler tests: NodeGroup scale-up/scale-down with the
what-if computed on the device path (ops/simulate.py), min/max bounds +
cooldowns, cloud.resize chaos consistency, and the node add/delete ->
snapshot row lifecycle under snapshot.write faults.

Reference test model: cluster-autoscaler's static_autoscaler_test.go /
scale_test.go run RunOnce against a fake cloud provider with template
node groups — same shape here, against FakeCloud's NodeGroups."""

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.labels import LabelSelector
from kubernetes_tpu.cloud.provider import (LABEL_INSTANCE_TYPE, FakeCloud,
                                           NodeGroup, node_from_template)
from kubernetes_tpu.controllers import (ClusterAutoscaler, ControllerManager,
                                        ReplicaSetController)
from kubernetes_tpu.controllers.clusterautoscaler import pick_expansion
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.utils import faultpoints

from helpers import make_node, make_pod


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, d):
        self.t += d


def make_world(n_nodes=2, node_cpu="2", clock=None):
    clock = clock or FakeClock()
    store = ObjectStore()
    sched = Scheduler(store, wave_size=16, clock=clock)
    for i in range(n_nodes):
        store.create("nodes", make_node(f"n{i}", cpu=node_cpu))
    cloud = FakeCloud()
    cloud.joiner = lambda g, name: store.create(
        "nodes", node_from_template(g, name))
    return clock, store, sched, cloud


RS_SEL = LabelSelector(match_labels={"app": "w"})


def rs_template(cpu="1"):
    return api.PodTemplateSpec(
        metadata=api.ObjectMeta(labels={"app": "w"}),
        spec=api.PodSpec(containers=[api.Container(
            resources=api.ResourceRequirements(
                requests=api.resource_list(cpu=cpu, memory="64Mi")))]))


class TestScaleUp:
    def test_scale_up_e2e_device_verdict(self):
        """Unschedulable pods -> simulated group pick (device path) ->
        nodes added -> pods placed."""
        clock, store, sched, cloud = make_world(2, node_cpu="2")
        small = cloud.add_node_group("small", make_node("t-s", cpu="2"),
                                     max_size=4, price=1.0)
        big = cloud.add_node_group("big", make_node("t-b", cpu="8"),
                                   max_size=4, price=3.0)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock)
        for i in range(3):
            store.create("pods", make_pod(f"p{i}", cpu="3"))
        assert sched.schedule_pending() == 0
        assert len(sched.pending_unschedulable()) == 3
        r = ca.run_once()
        # only the big group can host a 3-cpu pod (small's template is 2)
        assert 1 <= r["scaled_up"] <= 3
        assert big.target_size == r["scaled_up"]
        assert small.target_size == 0
        # the verdict came from the device feasibility kernel: every
        # helped pod's chosen row is a VIRTUAL row (>= n_real) and no
        # real row was statically feasible for it
        v = ca.last_verdict
        assert v is not None and v.n_real == 2
        assert (v.chosen[:3] >= v.n_real).all()
        assert not v.feasible[:3, :v.n_real].any()
        # joined nodes carry the membership label the controller infers
        joined = [n for n in store.list("nodes")
                  if (n.metadata.labels or {}).get(LABEL_INSTANCE_TYPE) == "big"]
        assert len(joined) == big.target_size
        evs = [e for e in store.list("events")
               if e.reason == "TriggeredScaleUp"]
        assert len(evs) == 3  # one per helped pod
        clock.advance(2.0)  # clear the pods' failure backoff
        assert sched.schedule_pending() == 3
        assert sched.queue.pending_count() == 0
        bound = {p.spec.node_name for p in store.list("pods")}
        assert all(n.startswith("big-") for n in bound)

    def test_no_scale_up_when_pods_fit_nowhere(self):
        """A pod no template can host buys no machines."""
        clock, store, sched, cloud = make_world(1, node_cpu="1")
        grp = cloud.add_node_group("small", make_node("t", cpu="2"),
                                   max_size=4)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock)
        store.create("pods", make_pod("huge", cpu="64"))
        assert sched.schedule_pending() == 0
        r = ca.run_once()
        assert r["scaled_up"] == 0 and grp.target_size == 0

    def test_no_scale_up_for_pod_with_a_real_home(self):
        """A pod parked in the unschedulable map that a real node could
        statically host (it is merely backing off) must not trigger an
        expansion."""
        clock, store, sched, cloud = make_world(1, node_cpu="4")
        grp = cloud.add_node_group("g", make_node("t", cpu="4"), max_size=4)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock)
        # fill the node, then fail a same-size pod (full != infeasible:
        # the resource mask IS capacity-aware, so feasible stays False —
        # use a pod that fits the empty template AND the real node shape
        # once capacity frees: real node full -> not statically feasible
        # -> this pod legitimately triggers scale-up. The no-trigger case
        # needs a pod whose failure was transient: simulate by parking a
        # pod that DOES fit the live node.
        p = make_pod("fits", cpu="1")
        sched.queue.add(p)
        pod = sched.queue.pop_wave(16)[0]
        sched._park_with_backoff(pod)  # parked, but a real node fits it
        assert len(sched.pending_unschedulable()) == 1
        r = ca.run_once()
        assert r["scaled_up"] == 0 and grp.target_size == 0

    def test_pick_expansion_prefers_helped_then_price(self):
        a = NodeGroup("a", make_node("t"), price=5.0)
        b = NodeGroup("b", make_node("t"), price=1.0)
        # more pods helped wins regardless of price
        g, n = pick_expansion([(a, 4, 2), (b, 2, 1)])
        assert g.name == "a" and n == 2
        # equal help: cheapest total price wins
        g, n = pick_expansion([(a, 3, 1), (b, 3, 2)])
        assert g.name == "b"  # 5.0*1 > 1.0*2
        assert pick_expansion([(a, 0, 0)]) is None


class TestBoundsAndCooldown:
    def test_max_bound_clamps_and_cooldown_blocks(self):
        clock, store, sched, cloud = make_world(1, node_cpu="1")
        cloud.joiner = None  # instances boot but never register: pods
        # stay pending, so a second pass WOULD re-trigger without the
        # cooldown — exactly the double-scale-up hazard
        grp = cloud.add_node_group("g", make_node("t", cpu="8"),
                                   max_size=1)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock,
                               scale_up_cooldown=10.0)
        for i in range(5):
            store.create("pods", make_pod(f"p{i}", cpu="2"))
        assert sched.schedule_pending() == 0
        r = ca.run_once()
        # headroom clamps the what-if to ONE virtual row (max_size 1),
        # so the expansion is 1 even though 5 pods are pending
        assert r["scaled_up"] == 1 and grp.target_size == 1
        # immediately again: cooling down AND at max — no double buy
        assert ca.run_once()["scaled_up"] == 0
        assert grp.target_size == 1
        clock.advance(11.0)  # cooldown passed; headroom still 0
        assert ca.run_once()["scaled_up"] == 0
        assert grp.target_size == 1  # never exceeds max_size

    def test_min_bound_blocks_scale_down(self):
        clock, store, sched, cloud = make_world(0)
        grp = cloud.add_node_group("g", make_node("t", cpu="4"),
                                   min_size=1, max_size=4)
        cloud.increase_size("g", 1)  # one idle member at min_size
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock,
                               utilization_threshold=0.5)
        clock.advance(100.0)  # far past any cooldown
        r = ca.run_once()
        assert r["scaled_down"] == 0 and grp.target_size == 1
        assert len(store.list("nodes")) == 1
        # lowering the floor releases it
        grp.min_size = 0
        r = ca.run_once()
        assert r["scaled_down"] == 1 and grp.target_size == 0
        assert store.list("nodes") == []


class TestScaleDown:
    def test_scale_down_e2e_refit_cordon_drain_delete(self):
        """Underutilized node -> joint re-fit proof (gang plane) ->
        cordon -> drain -> delete_nodes -> no pod left Pending."""
        clock, store, sched, cloud = make_world(0)
        grp = cloud.add_node_group("small", make_node("t", cpu="4"),
                                   max_size=10)
        cloud.increase_size("small", 3)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock,
                               utilization_threshold=0.6)
        store.create("replicasets", api.ReplicaSet(
            metadata=api.ObjectMeta(name="rs1"),
            spec=api.ReplicaSetSpec(replicas=4, selector=RS_SEL,
                                    template=rs_template(cpu="1"))))
        rsc = ReplicaSetController(store)
        rsc.sync_all()
        assert sched.schedule_pending() == 4
        r = ca.run_once()
        assert r["scaled_down"] == 1
        removed = ca.last_scale_down
        assert removed is not None
        assert grp.target_size == 2
        assert removed not in cloud.instances_by_name
        assert store.get("nodes", "default", removed) is None
        assert [e.involved_name for e in store.list("events")
                if e.reason == "ScaleDown"] == [removed]
        # drained residents were deleted; the RS recreates, and the
        # refit proof guaranteed the remaining two nodes host everything
        rsc.sync_all()
        clock.advance(2.0)
        sched.schedule_pending()
        pods = store.list("pods")
        assert len(pods) == 4
        assert all(p.spec.node_name for p in pods), "pod left Pending"
        assert removed not in {p.spec.node_name for p in pods}
        assert sched.scrubber.scrub().clean

    def test_refit_failure_keeps_the_node(self):
        """Residents that cannot jointly re-fit pin the node: 2 nodes
        each half-full with pods that exactly fill one node — removing
        either strands a pod, so neither may be removed."""
        clock, store, sched, cloud = make_world(0)
        grp = cloud.add_node_group("g", make_node("t", cpu="4"),
                                   max_size=4)
        cloud.increase_size("g", 2)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock,
                               utilization_threshold=0.9)
        for i in range(2):
            store.create("pods", make_pod(f"p{i}", cpu="3",
                                          owner_uid="rs-x"))
        assert sched.schedule_pending() == 2
        r = ca.run_once()
        assert r["scaled_down"] == 0
        assert grp.target_size == 2 and len(store.list("nodes")) == 2
        assert all(not n.spec.unschedulable for n in store.list("nodes"))

    def test_bare_pod_pins_the_node(self):
        """A resident without a controller owner would be destroyed by
        the drain (nothing recreates it): the node is never a
        candidate, however idle."""
        clock, store, sched, cloud = make_world(0)
        grp = cloud.add_node_group("g", make_node("t", cpu="8"),
                                   max_size=4)
        cloud.increase_size("g", 2)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock,
                               utilization_threshold=0.9)
        store.create("pods", make_pod("bare", cpu="1"))  # no owner
        assert sched.schedule_pending() == 1
        clock.advance(100.0)
        # only the EMPTY node may go; the bare pod's node never
        for _ in range(3):
            ca.run_once()
            clock.advance(100.0)
        held = store.get("pods", "default", "bare")
        assert held is not None and held.spec.node_name
        assert len(store.list("nodes")) == 1

    def test_pdb_exhausted_pins_the_node(self):
        """Residents whose PDB has no disruptions left block the drain
        (the preemption path already honors PDBs; the drain must too)."""
        clock, store, sched, cloud = make_world(0)
        grp = cloud.add_node_group("g", make_node("t", cpu="8"),
                                   max_size=4)
        cloud.increase_size("g", 2)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock,
                               utilization_threshold=0.9)
        store.create("pods", make_pod("guarded", cpu="1",
                                      labels={"app": "w"},
                                      owner_uid="rs-x"))
        assert sched.schedule_pending() == 1
        store.create("poddisruptionbudgets", api.PodDisruptionBudget(
            metadata=api.ObjectMeta(name="pdb"),
            selector=RS_SEL, disruptions_allowed=0))
        clock.advance(100.0)
        for _ in range(3):
            ca.run_once()
            clock.advance(100.0)
        guarded = store.get("pods", "default", "guarded")
        assert guarded is not None and guarded.spec.node_name
        assert len(store.list("nodes")) == 1  # only the empty node went

    def test_late_binding_pod_aborts_the_drain(self):
        """The refit proof runs before the cordon lands: a pod bound to
        the candidate in that window was never proved to re-fit, so the
        drain must abort (uncordon) rather than orphan it onto a
        deleted node. The bind is injected exactly inside the window
        via the autoscaler.simulate fault point."""
        from kubernetes_tpu.controllers.clusterautoscaler import \
            ANN_SCALE_DOWN
        clock, store, sched, cloud = make_world(0)
        cloud.add_node_group("g", make_node("t", cpu="8"), max_size=4)
        cloud.increase_size("g", 1)
        gnode = store.list("nodes")[0].name
        # a big non-group node absorbs the refit so the proof passes
        store.create("nodes", make_node("spare", cpu="16"))
        store.create("pods", make_pod("resident", cpu="1",
                                      owner_uid="rs-x"))
        assert sched.schedule_pending() == 1
        # the resident landed somewhere; pin the test to the group node
        res = store.get("pods", "default", "resident")
        if res.spec.node_name != gnode:
            store.delete("pods", "default", "resident")
            p = make_pod("resident", cpu="1", owner_uid="rs-x")
            store.create("pods", p)
            store.bind(p, gnode)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock,
                               utilization_threshold=0.9)
        clock.advance(100.0)

        def bind_late(_payload):
            late = make_pod("latecomer", cpu="1", owner_uid="rs-y")
            store.create("pods", late)
            store.bind(late, gnode)

        faultpoints.activate("autoscaler.simulate", "corrupt",
                             fn=bind_late, times=1)
        r = ca.run_once()
        assert r["scaled_down"] == 0
        node = store.get("nodes", "default", gnode)
        assert node is not None, "node must not be deleted"
        assert not node.spec.unschedulable, "drain aborted: uncordoned"
        assert ANN_SCALE_DOWN not in (node.metadata.annotations or {})
        assert store.get("pods", "default", "latecomer") is not None
        assert store.get("pods", "default", "resident") is not None

    def test_resumed_drain_aborts_when_refit_no_longer_holds(self):
        """A drain interrupted mid-way resumes after restart; if the
        cluster meanwhile lost the spare capacity the proof relied on,
        the resume must UNCORDON instead of wedging the node cordoned
        forever (and shadowing every other candidate)."""
        from kubernetes_tpu.controllers.clusterautoscaler import \
            ANN_SCALE_DOWN
        clock, store, sched, cloud = make_world(0)
        cloud.add_node_group("g", make_node("t", cpu="8"), max_size=4)
        cloud.increase_size("g", 1)
        name = store.list("nodes")[0].name
        p = make_pod("resident", cpu="4", owner_uid="rs-x")
        store.create("pods", p)
        assert sched.schedule_pending() == 1
        # simulate a crash mid-drain: cordon + intent landed, pods not
        # yet deleted, and NO other node can host the resident now
        node = store.get("nodes", "default", name)
        node.spec.unschedulable = True
        node.metadata.annotations[ANN_SCALE_DOWN] = "true"
        store.update("nodes", node)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock)
        clock.advance(100.0)
        r = ca.run_once()
        assert r["scaled_down"] == 0
        node = store.get("nodes", "default", name)
        assert not node.spec.unschedulable, "abort uncordons"
        assert ANN_SCALE_DOWN not in (node.metadata.annotations or {})
        assert store.get("pods", "default", "resident") is not None

    def test_drain_intent_survives_restart(self):
        """The scale-down-in-progress annotation makes an interrupted
        drain resumable by a FRESH controller instance — a cordoned node
        must never be orphaned behind the foreign-cordon rule."""
        clock, store, sched, cloud = make_world(0)
        grp = cloud.add_node_group("g", make_node("t", cpu="4"),
                                   max_size=4)
        cloud.increase_size("g", 2)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock,
                               utilization_threshold=0.5)
        clock.advance(100.0)
        with faultpoints.injected("cloud.resize", "raise", times=1):
            assert ca.run_once()["scaled_down"] == 0
        assert sum(n.spec.unschedulable for n in store.list("nodes")) == 1
        # the process restarts: a new instance with empty in-memory state
        ca2 = ClusterAutoscaler(store, cloud, sched, clock=clock,
                                utilization_threshold=0.5)
        r = ca2.run_once()
        assert r["scaled_down"] == 1
        assert grp.target_size == 1 and len(store.list("nodes")) == 1
        # a cordon the autoscaler did NOT place stays hands-off
        survivor = store.list("nodes")[0]
        survivor.spec.unschedulable = True
        store.update("nodes", survivor)
        clock.advance(100.0)
        assert ca2.run_once()["scaled_down"] == 0
        assert len(store.list("nodes")) == 1


@pytest.mark.faults
@pytest.mark.autoscale
class TestResizeChaos:
    def test_scale_up_fault_no_double_scale_up(self):
        """A cloud.resize raise during increase_size mutates nothing;
        the group backs off (no immediate double attempt) and the next
        eligible pass performs the expansion exactly once; the snapshot
        stays scrubber-clean throughout."""
        clock, store, sched, cloud = make_world(2, node_cpu="2")
        big = cloud.add_node_group("big", make_node("t", cpu="8"),
                                   max_size=4)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock)
        for i in range(3):
            store.create("pods", make_pod(f"p{i}", cpu="3"))
        assert sched.schedule_pending() == 0
        with faultpoints.injected("cloud.resize", "raise"):
            r = ca.run_once()
        assert faultpoints.hits("cloud.resize") == 1
        assert r["scaled_up"] == 0
        assert big.target_size == 0 and not cloud.instances_by_name
        assert len(store.list("nodes")) == 2
        # fault cleared but the group is inside its failure backoff:
        # no second resize attempt (the no-double-scale-up guarantee)
        calls_before = len(cloud.calls)
        assert ca.run_once()["scaled_up"] == 0
        assert len(cloud.calls) == calls_before
        clock.advance(1.1)  # past the 1s initial backoff
        r = ca.run_once()
        assert r["scaled_up"] >= 1
        first_target = big.target_size
        assert first_target == r["scaled_up"] <= 3
        assert sched.scrubber.scrub().clean  # no orphan snapshot rows
        clock.advance(2.0)
        assert sched.schedule_pending() == 3
        assert big.target_size == first_target  # placed, no extra buy

    def test_scale_down_fault_leaves_cordoned_node_consistent(self):
        """delete_nodes failing AFTER cordon+drain must not orphan
        anything: the node object (and its snapshot row) survives,
        cordoned, and the drain completes after the backoff."""
        clock, store, sched, cloud = make_world(0)
        grp = cloud.add_node_group("g", make_node("t", cpu="4"),
                                   max_size=4)
        cloud.increase_size("g", 2)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock,
                               utilization_threshold=0.5)
        clock.advance(100.0)
        with faultpoints.injected("cloud.resize", "raise", times=1):
            r = ca.run_once()
        assert r["scaled_down"] == 0
        assert grp.target_size == 2  # cloud mutated nothing
        nodes = store.list("nodes")
        assert len(nodes) == 2
        cordoned = [n for n in nodes if n.spec.unschedulable]
        assert len(cordoned) == 1  # mid-drain, resumable
        assert sched.scrubber.scrub().clean  # row still backed by a Node
        # within the group backoff: no retry
        assert ca.run_once()["scaled_down"] == 0
        assert len(store.list("nodes")) == 2
        clock.advance(1.1)
        r = ca.run_once()
        assert r["scaled_down"] == 1 and grp.target_size == 1
        assert len(store.list("nodes")) == 1
        assert sched.scrubber.scrub().clean

    def test_simulation_fault_skips_the_pass(self):
        """A faulting device what-if must cost a skipped pass, never a
        resize on garbage data."""
        clock, store, sched, cloud = make_world(1, node_cpu="1")
        grp = cloud.add_node_group("g", make_node("t", cpu="8"),
                                   max_size=4)
        ca = ClusterAutoscaler(store, cloud, sched, clock=clock)
        store.create("pods", make_pod("p", cpu="2"))
        assert sched.schedule_pending() == 0
        with faultpoints.injected("autoscaler.simulate", "raise"):
            r = ca.run_once()
        assert r == {"scaled_up": 0, "scaled_down": 0}
        assert grp.target_size == 0
        r = ca.run_once()  # healthy pass proceeds
        assert r["scaled_up"] == 1


@pytest.mark.faults
class TestNodeRowLifecycle:
    def test_node_add_delete_rows_under_write_faults(self):
        """Satellite: _on_node_add/_on_node_delete drive snapshot row
        lifecycle under the snapshot.write fault point — the add flushes
        unschedulable pods (move_all_to_active) even when the row write
        was corrupted, the scrubber catches + repairs the divergence,
        and a delete leaves no ghost rows behind."""
        clock = FakeClock()
        store = ObjectStore()
        sched = Scheduler(store, wave_size=16, clock=clock)
        store.create("nodes", make_node("n0", cpu="1"))
        store.create("pods", make_pod("big", cpu="2"))
        assert sched.schedule_pending() == 0
        assert sched.queue.unschedulable_count() == 1
        with faultpoints.injected("snapshot.write", "corrupt"):
            store.create("nodes", make_node("n-new", cpu="4"))
        # move_all_to_active flushed the unschedulable map (the pod is
        # inside its backoff window, so it parks in the backoff area)
        assert sched.queue.unschedulable_count() == 0
        assert sched.queue.backoff_count() == 1
        # the corrupt write left a silently divergent row
        rep = sched.scrubber.scrub()
        assert not rep.clean
        assert any("n-new" == d.node for d in rep.divergences)
        assert rep.repaired == len(rep.divergences)
        assert sched.scrubber.scrub().clean
        clock.advance(1.1)
        assert sched.schedule_pending() == 1
        assert store.get("pods", "default", "big").spec.node_name == "n-new"
        # delete the node its pod lives on: row, pod rows, and any term
        # rows must die with it — scrubber-verified, no ghosts
        store.delete("nodes", "default", "n-new")
        assert "n-new" not in sched.snapshot.node_index
        rep = sched.scrubber.scrub()
        assert rep.clean, rep.summary()
        host_uids = {p.uid for ni in sched.cache.node_infos.values()
                     for p in ni.pods}
        for uid, slot in sched.snapshot.pod_slot.items():
            if sched.snapshot.ep_valid[slot]:
                assert uid in host_uids


class TestWiring:
    def test_manager_registers_autoscaler(self):
        store = ObjectStore()
        sched = Scheduler(store, wave_size=16)
        cloud = FakeCloud()
        cloud.add_node_group("g", make_node("t", cpu="4"))
        m = ControllerManager(store, controllers=[], cloud=cloud,
                              scheduler=sched)
        assert "cluster-autoscaler" in m.controllers
        # without node groups (or a scheduler) the controller is absent
        m2 = ControllerManager(store, controllers=[], cloud=FakeCloud(),
                               scheduler=sched)
        assert "cluster-autoscaler" not in m2.controllers
        m3 = ControllerManager(store, controllers=[], cloud=cloud)
        assert "cluster-autoscaler" not in m3.controllers

    def test_pending_pods_gauge_exported(self):
        """Satellite: scheduler_pending_pods{queue=...} tracks every
        queue area from the housekeeping step."""
        clock = FakeClock()
        store = ObjectStore()
        sched = Scheduler(store, wave_size=16, clock=clock)
        store.create("nodes", make_node("n0", cpu="1"))
        for i in range(2):
            store.create("pods", make_pod(f"big{i}", cpu="4"))
        sched.schedule_pending()
        g = sched.metrics.pending_pods
        assert g.value(queue="unschedulable") == 2
        assert g.value(queue="active") == 0
        assert g.value(queue="backoff") == 0
        assert g.value(queue="gang_waiting") == 0
        # a node event moves them to the backoff area; the next
        # housekeeping pass re-exports
        store.create("nodes", make_node("n1", cpu="8"))
        sched.schedule_pending()
        assert g.value(queue="unschedulable") == 0
        clock.advance(1.1)
        sched.schedule_pending()
        assert g.value(queue="backoff") == 0
        assert g.value(queue="unschedulable") == 0
        # the gauge registers in the exported series map
        series = sched.metrics.all_series()
        assert any(name.startswith("scheduler_pending_pods{")
                   for name in series)
        assert all(s.kind == "gauge" for name, s in series.items()
                   if name.startswith("scheduler_pending_pods{"))

    def test_fake_cloud_auto_ip_never_collides(self):
        """Satellite: delete-then-add must not re-issue a live IP (the
        old len+1 scheme did)."""
        cloud = FakeCloud()
        cloud.add_instance("a")
        cloud.add_instance("b")
        ip_b = cloud.instances_by_name["b"].addresses[0].address
        del cloud.instances_by_name["a"]
        cloud.add_instance("c")
        ip_c = cloud.instances_by_name["c"].addresses[0].address
        assert ip_c != ip_b
        ips = [i.addresses[0].address
               for i in cloud.instances_by_name.values()]
        assert len(ips) == len(set(ips))

    def test_kubectl_shows_cordoned_node(self):
        """Satellite: kubectl get nodes renders cordon state as
        Ready,SchedulingDisabled."""
        from kubernetes_tpu.cli.kubectl import _node_row
        node = make_node("n1")
        assert _node_row(node)[1] == "Ready"
        node.spec.unschedulable = True
        assert _node_row(node)[1] == "Ready,SchedulingDisabled"
