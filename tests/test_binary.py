"""Binary wire codec tests (the protobuf-role serializer,
api/binary.py): round-trips, list framing, HTTP content negotiation,
and the compactness property that justifies its existence."""

import json

import pytest

from kubernetes_tpu.api import binary, scheme
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.server.admission import AdmissionChain
from kubernetes_tpu.server.apiserver import APIServer

from helpers import make_node, make_pod


def rich_pod():
    from kubernetes_tpu.api import labels as lbl

    return make_pod(
        "p1", cpu="250m", memory="1Gi",
        labels={"app": "web", "tier": "frontend"},
        node_selector={"disk": "ssd"},
        tolerations=[api.Toleration(key="k", operator="Exists",
                                    effect=api.NO_SCHEDULE)],
        affinity=api.Affinity(node_affinity=api.NodeAffinity(
            required=api.NodeSelector([api.NodeSelectorTerm(
                match_expressions=[lbl.Requirement("zone", lbl.IN,
                                                   ("z1", "z2"))])]))),
        ports=[8080])


class TestRoundTrip:
    @pytest.mark.parametrize("obj", [
        rich_pod(),
        make_node("n1", labels={"a": "b"},
                  taints=[api.Taint("k", "v", api.NO_EXECUTE)]),
        api.Service(metadata=api.ObjectMeta(name="s"),
                    spec=api.ServiceSpec(selector={"app": "web"})),
    ])
    def test_object_roundtrip(self, obj):
        back = binary.loads(binary.dumps(obj))
        assert scheme.encode_object(back) == scheme.encode_object(obj)

    def test_custom_object_roundtrip(self):
        scheme.register("Widget", "widgets", api.CustomObject,
                        "example.com/v1")
        try:
            w = api.CustomObject(kind="Widget", api_version="example.com/v1",
                                 metadata=api.ObjectMeta(name="w"),
                                 spec={"nested": {"deep": [1, 2.5, "x",
                                                           None, True]}})
            back = binary.loads(binary.dumps(w))
            assert back.spec == w.spec
        finally:
            scheme.unregister("Widget")

    def test_list_roundtrip(self):
        pods = [rich_pod(), make_pod("p2", cpu="1")]
        items, rv = binary.loads_list(binary.dumps_list("Pod", pods, 42))
        assert rv == 42
        assert [scheme.encode_object(o) for o in items] == \
            [scheme.encode_object(o) for o in pods]

    def test_bad_frame_rejected(self):
        with pytest.raises(ValueError):
            binary.loads(b"nope" + b"\x00" * 8)


class TestCompactness:
    def test_smaller_than_json(self):
        pods = [rich_pod() for _ in range(50)]
        raw_json = json.dumps(
            [scheme.encode_object(p) for p in pods]).encode()
        raw_bin = binary.dumps_list("Pod", pods)
        assert len(raw_bin) < len(raw_json)


class TestHTTPNegotiation:
    @pytest.fixture()
    def server(self):
        srv = APIServer(ObjectStore(), admission=AdmissionChain()).start()
        yield srv
        srv.stop()

    def test_binary_client_end_to_end(self, server):
        plain = RESTClient(server.url)
        bclient = RESTClient(server.url, binary=True)
        plain.create("nodes", make_node("n1"))
        plain.create("pods", rich_pod())
        # binary get
        pod = bclient.get("pods", "default", "p1")
        assert pod.metadata.labels["app"] == "web"
        assert pod.spec.containers[0].resources.requests["cpu"] == 250
        # binary list
        items, rv = bclient.list("pods")
        assert len(items) == 1 and rv > 0
        # a plain client is unaffected by the server capability
        items2, _ = plain.list("pods")
        assert scheme.encode_object(items2[0]) == scheme.encode_object(pod)

    def test_response_content_type(self, server):
        import urllib.request

        RESTClient(server.url).create("nodes", make_node("n1"))
        req = urllib.request.Request(f"{server.url}/api/v1/nodes")
        req.add_header("Accept", binary.CONTENT_TYPE)
        with urllib.request.urlopen(req) as resp:
            assert resp.headers["Content-Type"] == binary.CONTENT_TYPE
            body = resp.read()
        items, _ = binary.loads_list(body)
        assert items[0].metadata.name == "n1"
