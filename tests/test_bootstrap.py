"""Long-tail control-plane surface: new admission plugins, the TTL
controller, HA endpoint reconciliation, and kubeadm-lite bootstrap.
"""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.server.admission import (AdmissionChain, AdmissionError,
                                             LimitRanger, PodNodeSelector,
                                             ServiceAccountAdmission)

from helpers import make_node, make_pod


def mkpod(name, cpu=None, **kw):
    return make_pod(name, cpu=cpu, **kw)


class TestLimitRanger:
    def _store(self):
        store = ObjectStore()
        store.create("limitranges", api.LimitRange(
            metadata=api.ObjectMeta(name="lr"),
            spec=api.LimitRangeSpec(limits=[api.LimitRangeItem(
                type="Container",
                default_request={"cpu": 200},
                min={"cpu": 100}, max={"cpu": 2000})])))
        return store, LimitRanger()

    def test_defaults_applied(self):
        store, lr = self._store()
        pod = mkpod("p")  # no cpu request
        lr.admit("create", "pods", pod, None, None, store)
        assert pod.spec.containers[0].resources.requests["cpu"] == 200

    def test_min_max_enforced(self):
        store, lr = self._store()
        small = mkpod("s", cpu="50m")
        with pytest.raises(AdmissionError):
            lr.admit("create", "pods", small, None, None, store)
        big = mkpod("b", cpu="3")
        with pytest.raises(AdmissionError):
            lr.admit("create", "pods", big, None, None, store)
        ok = mkpod("ok", cpu="1")
        lr.admit("create", "pods", ok, None, None, store)


class TestLimitRangerLimits:
    def test_default_limits_applied_and_enforced(self):
        store = ObjectStore()
        store.create("limitranges", api.LimitRange(
            metadata=api.ObjectMeta(name="lr"),
            spec=api.LimitRangeSpec(limits=[api.LimitRangeItem(
                type="Container", default={"cpu": 500},
                max={"cpu": 2000})])))
        lr = LimitRanger()
        pod = mkpod("p")
        lr.admit("create", "pods", pod, None, None, store)
        c = pod.spec.containers[0]
        assert c.resources.limits["cpu"] == 500
        assert c.resources.requests["cpu"] == 500  # falls back to default
        over = mkpod("o", cpu="1")
        over.spec.containers[0].resources.limits = {"cpu": 5000}
        with pytest.raises(AdmissionError):
            lr.admit("create", "pods", over, None, None, store)


class TestQuantityDecoding:
    def test_quota_cpu_keys_decode_to_milli(self):
        from kubernetes_tpu.api import scheme

        rq = scheme.decode("ResourceQuota", {
            "metadata": {"name": "q"},
            "spec": {"hard": {"requests.cpu": "500m", "cpu": "2",
                              "requests.memory": "1Gi", "pods": 5}}})
        assert rq.spec.hard["requests.cpu"] == 500
        assert rq.spec.hard["cpu"] == 2000
        assert rq.spec.hard["requests.memory"] == 1 << 30
        assert rq.spec.hard["pods"] == 5


class TestServiceAccountAdmission:
    def test_defaults_and_requires_sa(self):
        store = ObjectStore()
        sa = ServiceAccountAdmission()
        pod = mkpod("p")
        with pytest.raises(AdmissionError):
            sa.admit("create", "pods", pod, None, None, store)
        store.create("serviceaccounts", api.ServiceAccount(
            metadata=api.ObjectMeta(name="default", namespace="default")))
        sa.admit("create", "pods", pod, None, None, store)
        assert pod.spec.service_account_name == "default"


class TestPodNodeSelector:
    def test_namespace_selector_merged(self):
        store = ObjectStore()
        store.create("namespaces", api.Namespace(
            metadata=api.ObjectMeta(
                name="default",
                annotations={
                    "scheduler.alpha.kubernetes.io/node-selector":
                        "pool=batch"})))
        pns = PodNodeSelector()
        pod = mkpod("p")
        pns.admit("create", "pods", pod, None, None, store)
        assert pod.spec.node_selector["pool"] == "batch"
        conflicting = mkpod("q", node_selector={"pool": "web"})
        with pytest.raises(AdmissionError):
            pns.admit("create", "pods", conflicting, None, None, store)


class TestTTLController:
    def test_ttl_scales_with_cluster_size(self):
        from kubernetes_tpu.controllers.ttl import (TTL_ANNOTATION,
                                                    TTLController,
                                                    ttl_for_size)

        assert ttl_for_size(10) == 0
        assert ttl_for_size(400) == 15
        assert ttl_for_size(900) == 30
        assert ttl_for_size(4000) == 60
        assert ttl_for_size(9000) == 300
        store = ObjectStore()
        ctrl = TTLController(store)
        for i in range(3):
            store.create("nodes", make_node(f"n{i}"))
        ctrl.sync_all()
        for n in store.list("nodes"):
            assert n.metadata.annotations[TTL_ANNOTATION] == "0"

    def test_in_manager_roster(self):
        from kubernetes_tpu.controllers.manager import DEFAULT_CONTROLLERS
        from kubernetes_tpu.controllers.ttl import TTLController

        assert TTLController in DEFAULT_CONTROLLERS


class TestEndpointReconciler:
    def test_two_replicas_publish_and_prune(self):
        from kubernetes_tpu.server.reconciler import EndpointReconciler

        store = ObjectStore()
        now = [1000.0]
        a = EndpointReconciler(store, "10.0.0.1:6443", 6443, ttl=15,
                               clock=lambda: now[0])
        b = EndpointReconciler(store, "10.0.0.2:6443", 6443, ttl=15,
                               clock=lambda: now[0])
        a.reconcile()
        b.reconcile()
        ep = store.get("endpoints", "default", "kubernetes")
        ips = {addr.ip for addr in ep.subsets[0].addresses}
        assert ips == {"10.0.0.1:6443", "10.0.0.2:6443"}
        # replica a dies (stops refreshing); b's reconcile prunes it
        now[0] += 20
        b.reconcile()
        ep = store.get("endpoints", "default", "kubernetes")
        ips = {addr.ip for addr in ep.subsets[0].addresses}
        assert ips == {"10.0.0.2:6443"}

    def test_clean_shutdown_removes_address(self):
        from kubernetes_tpu.server.apiserver import APIServer
        from kubernetes_tpu.server.admission import AdmissionChain

        store = ObjectStore()
        srv = APIServer(store, admission=AdmissionChain(),
                        reconcile_endpoints=True).start()
        ep = store.get("endpoints", "default", "kubernetes")
        assert ep is not None and len(ep.subsets[0].addresses) == 1
        srv.stop()
        ep = store.get("endpoints", "default", "kubernetes")
        assert ep.subsets[0].addresses == []


class TestKubeadm:
    def test_init_boots_a_working_cluster(self, tmp_path):
        """kubeadm init analog: one call stands up apiserver +
        controllers + scheduler on the durable store; a deployment
        applied via kubectl ends up with scheduled pods."""
        import io

        from kubernetes_tpu.cli import kubeadm, kubectl

        cluster = kubeadm.Cluster(data_dir=str(tmp_path / "kv"),
                                  hollow_nodes=3)
        kubeadm.ensure_bootstrap_objects(cluster.store)
        cluster.start()
        try:
            assert cluster.wait_ready(timeout=15)
            manifest = tmp_path / "dep.yaml"
            manifest.write_text("""\
kind: Deployment
apiVersion: apps/v1
metadata:
  name: web
spec:
  replicas: 3
  selector:
    matchLabels: {app: web}
  template:
    metadata:
      labels: {app: web}
    spec:
      containers:
      - name: c
        resources:
          requests: {cpu: 100m, memory: 64Mi}
""")
            out = io.StringIO()
            rc = kubectl.main(["--server", cluster.url, "apply", "-f",
                               str(manifest)], out=out)
            assert rc == 0, out.getvalue()
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                pods = [p for p in cluster.store.list("pods")
                        if (p.metadata.labels or {}).get("app") == "web"]
                if len(pods) == 3 and all(p.spec.node_name for p in pods):
                    break
                time.sleep(0.1)
            else:
                raise AssertionError(
                    f"pods never scheduled: "
                    f"{[(p.metadata.name, p.spec.node_name) for p in pods]}")
        finally:
            cluster.stop()

    def test_cli_smoke(self, tmp_path):
        from kubernetes_tpu.cli import kubeadm

        rc = kubeadm.main(["init", "--once",
                           "--data-dir", str(tmp_path / "kv")])
        assert rc == 0


class TestCertificates:
    def test_kubelet_csr_approved_and_signed(self):
        from kubernetes_tpu.controllers.certificates import (
            CSRApprovingController, CSRSigningController)

        store = ObjectStore()
        approver, signer = CSRApprovingController(store), \
            CSRSigningController(store)
        store.create("certificatesigningrequests",
                     api.CertificateSigningRequest(
                         metadata=api.ObjectMeta(name="node-csr-n1"),
                         spec=api.CertificateSigningRequestSpec(
                             request="csr-bytes",
                             username="system:node:n1",
                             groups=["system:nodes"],
                             usages=["digital signature",
                                     "key encipherment", "client auth"])))
        approver.sync_all()
        signer.sync_all()
        csr = store.get("certificatesigningrequests", "default",
                        "node-csr-n1")
        assert csr.approved and csr.status.certificate.startswith(
            "cert:system:node:n1:")

    def test_non_node_csr_not_auto_approved(self):
        from kubernetes_tpu.controllers.certificates import (
            CSRApprovingController, CSRSigningController)

        store = ObjectStore()
        approver, signer = CSRApprovingController(store), \
            CSRSigningController(store)
        store.create("certificatesigningrequests",
                     api.CertificateSigningRequest(
                         metadata=api.ObjectMeta(name="user-csr"),
                         spec=api.CertificateSigningRequestSpec(
                             request="x", username="alice",
                             usages=["client auth"])))
        approver.sync_all()
        signer.sync_all()
        csr = store.get("certificatesigningrequests", "default", "user-csr")
        assert not csr.approved and csr.status.certificate == ""

    def test_in_manager_roster(self):
        from kubernetes_tpu.controllers.certificates import (
            CSRApprovingController, CSRSigningController)
        from kubernetes_tpu.controllers.manager import DEFAULT_CONTROLLERS

        assert CSRApprovingController in DEFAULT_CONTROLLERS
        assert CSRSigningController in DEFAULT_CONTROLLERS
