"""Bootstrap-token machinery + ClusterRole aggregation.

Reference: pkg/controller/bootstrap/ (BootstrapSigner, TokenCleaner),
plugin/pkg/auth/authenticator/token/bootstrap/, and
pkg/controller/clusterroleaggregation/. The headline property: a joiner
holding a bootstrap token VERIFIES the CA bundle it discovers (signed
cluster-info) instead of trusting first use."""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.labels import LabelSelector
from kubernetes_tpu.controllers import bootstrap as bt
from kubernetes_tpu.controllers.clusterroleaggregation import \
    ClusterRoleAggregationController
from kubernetes_tpu.runtime.store import ObjectStore


class TestBootstrapTokens:
    def test_lookup_validates_and_expires(self):
        store = ObjectStore()
        tid, tsec, wire = bt.new_bootstrap_token()
        store.create("secrets", bt.make_token_secret(tid, tsec,
                                                     ttl_seconds=3600))
        assert bt.lookup_token(store, wire) is not None
        assert bt.lookup_token(store, f"{tid}.WRONG") is None
        assert bt.lookup_token(store, "garbage") is None
        # expired token is dead even before the cleaner removes it
        tid2, tsec2, wire2 = bt.new_bootstrap_token()
        sec2 = bt.make_token_secret(tid2, tsec2)
        sec2.data["expiration"] = str(time.time() - 1)
        store.create("secrets", sec2)
        assert bt.lookup_token(store, wire2) is None

    def test_authenticator_resolves_bootstrap_secret(self):
        from kubernetes_tpu.server import pki
        from kubernetes_tpu.server.auth import AuthenticatorChain

        store = ObjectStore()
        ca = pki.ensure_cluster_ca(store)
        tid, tsec, wire = bt.new_bootstrap_token()
        store.create("secrets", bt.make_token_secret(tid, tsec))
        chain = AuthenticatorChain(store=store, ca=ca)
        user = chain.authenticate(f"Bearer {wire}")
        assert user is not None
        assert user.name == f"system:bootstrap:{tid}"
        assert "system:bootstrappers" in user.groups
        # deleting the Secret revokes the token live
        store.delete("secrets", bt.TOKEN_NAMESPACE,
                     bt.TOKEN_SECRET_PREFIX + tid)
        assert chain.authenticate(f"Bearer {wire}") is None

    def test_token_cleaner_removes_expired(self):
        store = ObjectStore()
        now = [1000.0]
        cleaner = bt.TokenCleanerController(store, clock=lambda: now[0])
        tid, tsec, _ = bt.new_bootstrap_token()
        sec = bt.make_token_secret(tid, tsec)
        sec.data["expiration"] = str(1500.0)
        store.create("secrets", sec)
        cleaner.resync()
        cleaner.sync_all()
        assert store.get("secrets", bt.TOKEN_NAMESPACE,
                         bt.TOKEN_SECRET_PREFIX + tid) is not None
        now[0] = 2000.0
        cleaner.resync()
        cleaner.sync_all()
        assert store.get("secrets", bt.TOKEN_NAMESPACE,
                         bt.TOKEN_SECRET_PREFIX + tid) is None


class TestBootstrapSigner:
    def test_signatures_track_tokens(self):
        store = ObjectStore()
        store.create("configmaps", api.ConfigMap(
            metadata=api.ObjectMeta(name="cluster-info",
                                    namespace="kube-public"),
            data={"ca.crt": "PEM-BYTES"}))
        tid, tsec, wire = bt.new_bootstrap_token()
        store.create("secrets", bt.make_token_secret(tid, tsec))
        signer = bt.BootstrapSignerController(store)
        signer.resync()
        signer.sync_all()
        info = store.get("configmaps", "kube-public", "cluster-info")
        assert bt.verify_cluster_info(info, wire) == "PEM-BYTES"
        # a different token cannot verify
        _, _, other = bt.new_bootstrap_token()
        assert bt.verify_cluster_info(info, other) is None
        # token deleted -> signature dropped on the next pass
        store.delete("secrets", bt.TOKEN_NAMESPACE,
                     bt.TOKEN_SECRET_PREFIX + tid)
        signer.resync()
        signer.sync_all()
        info = store.get("configmaps", "kube-public", "cluster-info")
        assert bt.verify_cluster_info(info, wire) is None

    def test_join_verifies_discovery_and_rejects_forgery(self):
        """End to end: kubeadm join discovers + VERIFIES the CA through
        its bootstrap token; a tampered cluster-info is rejected."""
        from kubernetes_tpu.cli import kubeadm

        cluster = kubeadm.Cluster(secure=True, reconcile_endpoints=False)
        kubeadm.ensure_bootstrap_objects(cluster.store)
        cluster.start()
        try:
            ca = kubeadm.fetch_cluster_ca(cluster.url,
                                          token=cluster.bootstrap_token)
            assert ca == cluster.ca.ca_cert_pem
            # an attacker WITHOUT the token secret cannot produce a
            # verifying cluster-info: a forged/unknown token fails
            # loudly instead of falling back to trust-on-first-use
            # (the wire-level MITM case is the pure-function test
            # above — a store write already implies RBAC was bypassed,
            # and the signer correctly re-signs legitimate CA rotations)
            with pytest.raises(RuntimeError, match="verification FAILED"):
                kubeadm.fetch_cluster_ca(cluster.url,
                                         token="aaaaaa.0123456789abcdef")
        finally:
            cluster.stop()


class TestClusterRoleAggregation:
    def test_union_maintained(self):
        store = ObjectStore()
        ctrl = ClusterRoleAggregationController(store)
        store.create("clusterroles", api.ClusterRole(
            metadata=api.ObjectMeta(name="admin"),
            aggregation_selectors=[LabelSelector(
                match_labels={"rbac.example.com/aggregate-to-admin":
                              "true"})]))
        store.create("clusterroles", api.ClusterRole(
            metadata=api.ObjectMeta(
                name="crd-frag",
                labels={"rbac.example.com/aggregate-to-admin": "true"}),
            rules=[api.RBACPolicyRule(verbs=["get"], api_groups=[""],
                                      resources=["widgets"])]))
        ctrl.sync_all()
        admin = store.get("clusterroles", "", "admin")
        assert any("widgets" in (r.resources or [])
                   for r in admin.rules), admin.rules
        # fragment removed -> rules shrink back
        store.delete("clusterroles", "", "crd-frag")
        ctrl.sync_all()
        admin = store.get("clusterroles", "", "admin")
        assert admin.rules == []


class TestKubeadmTokenCLI:
    """kubeadm token create/list/delete + reset + version
    (cmd/kubeadm/app/cmd/token.go, reset.go)."""

    def _cluster(self):
        from kubernetes_tpu.server import APIServer

        store = ObjectStore()
        srv = APIServer(store).start()
        return store, srv

    def _kubeadm(self, *argv):
        import contextlib
        import io

        from kubernetes_tpu.cli.kubeadm import main

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = main(list(argv))
        return rc, buf.getvalue()

    def test_token_create_list_delete(self):
        store, srv = self._cluster()
        try:
            rc, out = self._kubeadm("token", "create", "--server", srv.url)
            assert rc == 0
            wire = out.strip()
            tid, _, tsec = wire.partition(".")
            assert len(tid) == 6 and len(tsec) == 16
            # the created secret is a real bootstrap token the
            # authenticator resolves
            assert bt.lookup_token(store, wire) is not None
            rc, out = self._kubeadm("token", "list", "--server", srv.url)
            assert rc == 0 and tid in out and "authentication" in out
            # the secret itself never leaks through list
            assert tsec not in out
            rc, out = self._kubeadm("token", "delete", wire,
                                    "--server", srv.url)
            assert rc == 0
            assert bt.lookup_token(store, wire) is None
        finally:
            srv.stop()

    def test_token_create_respects_ttl_zero(self):
        store, srv = self._cluster()
        try:
            rc, out = self._kubeadm("token", "create", "--server", srv.url,
                                    "--ttl", "0")
            assert rc == 0
            sec = store.get("secrets", bt.TOKEN_NAMESPACE,
                            bt.TOKEN_SECRET_PREFIX
                            + out.strip().split(".")[0])
            assert "expiration" not in sec.data  # never expires
        finally:
            srv.stop()

    def test_reset_wipes_data_dir(self, tmp_path):
        d = tmp_path / "cluster"
        d.mkdir()
        (d / "wal").write_bytes(b"x")
        (d / "snapshot").write_bytes(b"y")
        rc, _ = self._kubeadm("reset", "--data-dir", str(d))
        assert rc == 1  # refuses without --force
        assert d.exists()
        rc, out = self._kubeadm("reset", "--data-dir", str(d), "--force")
        assert rc == 0 and not d.exists()

    def test_reset_refuses_non_cluster_dir(self, tmp_path):
        d = tmp_path / "home"
        d.mkdir()
        (d / "precious.txt").write_text("do not delete")
        rc, _ = self._kubeadm("reset", "--data-dir", str(d), "--force")
        assert rc == 1 and (d / "precious.txt").exists()

    def test_version(self):
        from kubernetes_tpu.cli.kubeadm import CLUSTER_VERSION

        rc, out = self._kubeadm("version")
        assert rc == 0 and CLUSTER_VERSION in out
