"""Chaos-campaign suite (kubernetes_tpu/chaos/): the cluster-invariant
checker's mutation coverage, the fault-point registry drift guard, the
KTPU_FAULTPOINTS parse hardening, a fixed-seed campaign smoke, and the
deliberately-broken-build catch-and-shrink acceptance.

The mutation tests are the checker's own chaos tier: each seeds ONE
canonical bug class directly into a live scheduler's state (a lost pod,
a double-booked pod, a cache double-bind, a split gang) and asserts the
NAMED invariant fires with the offender in its digest. The
eventually-consistent invariants (conservation, gang_atomic) use
two-consecutive-checks hysteresis — those tests call check() twice and
assert the first pass stays quiet (a transient must not fire).
"""

import pathlib
import re

import pytest

from kubernetes_tpu.chaos.campaign import (FaultSpec, env_string, replay,
                                           run_campaign, sample_schedule,
                                           shrink)
from kubernetes_tpu.chaos.invariants import (INVARIANTS, InvariantChecker,
                                             InvariantViolation)
from kubernetes_tpu.ops.encoding import Caps
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.utils import faultpoints

from helpers import make_node, make_pod

pytestmark = pytest.mark.campaign


@pytest.fixture(autouse=True)
def _clean_faultpoints():
    faultpoints.reset()
    yield
    faultpoints.reset()


# -- KTPU_FAULTPOINTS parse hardening (utils/faultpoints.parse) --------------

class TestParse:
    def test_valid_tokens(self):
        out = faultpoints.parse(
            "kernel.wave=raise,bind.post=latency:0.05:3,queue.shed=drop::2")
        assert out == [("kernel.wave", "raise", 0.0, None),
                       ("bind.post", "latency", 0.05, 3),
                       ("queue.shed", "drop", 0.0, 2)]

    def test_empty_mode_defaults_to_raise(self):
        assert faultpoints.parse("kernel.wave=") == [
            ("kernel.wave", "raise", 0.0, None)]

    def test_blank_and_whitespace_tokens_skipped(self):
        assert faultpoints.parse(" , kernel.wave=raise ,") == [
            ("kernel.wave", "raise", 0.0, None)]

    @pytest.mark.parametrize("spec,fragment", [
        ("kernel.wav=raise", "unknown fault point"),
        ("kernel.wave=explode", "unknown mode"),
        ("kernel.wave", "malformed token"),
        ("kernel.wave=latency:fast", "non-numeric arg"),
        ("kernel.wave=latency:-1", "negative arg"),
        ("kernel.wave=raise::1.5", "non-integer times"),
        ("kernel.wave=raise::-2", "negative times"),
        ("kernel.wave=raise:0:1:9", "too many fields"),
    ])
    def test_malformed_tokens_raise_naming_the_token(self, spec, fragment):
        with pytest.raises(ValueError) as ei:
            faultpoints.parse(spec)
        msg = str(ei.value)
        assert fragment in msg
        # the offending token is quoted in the message so a typoed
        # multi-token spec points at the right entry
        assert spec.split(",")[0].split("=")[0] in msg

    def test_activate_spec_is_all_or_nothing(self):
        with pytest.raises(ValueError):
            faultpoints.activate_spec("kernel.wave=raise,bogus.point=drop")
        assert not faultpoints.active()

    def test_activate_spec_arms_with_budget(self):
        faultpoints.activate_spec("queue.shed=drop::2")
        assert faultpoints.is_armed("queue.shed", "drop")
        assert faultpoints.fire("queue.shed") is True
        assert faultpoints.fire("queue.shed") is True
        assert faultpoints.fire("queue.shed") is False  # budget spent
        assert faultpoints.hits("queue.shed") == 2

    def test_lost_device_fault_matches_only_its_victim(self):
        """The payload-matching corrupt helper for device.lost: raises
        DeviceLost only while the armed device rides in the payload."""
        from kubernetes_tpu.sched.breaker import DeviceLost, lost_device_fault

        faultpoints.activate("device.lost", "corrupt",
                             fn=lost_device_fault("tpu:1"))
        assert faultpoints.fire("device.lost", payload=None) is False
        assert faultpoints.fire("device.lost", payload="tpu:0") is False
        with pytest.raises(DeviceLost):
            faultpoints.fire("device.lost", payload=("tpu:0", "tpu:1"))
        with pytest.raises(DeviceLost):
            faultpoints.fire("device.lost", payload="tpu:1")

    def test_poison_pod_fault_matches_only_its_victim(self):
        """The payload-matching corrupt helper for wave.poison: crashes
        only when the victim uid rides in the batch."""
        from kubernetes_tpu.state.featurize import poison_pod_fault

        victim = make_pod("victim", cpu="100m", memory="64Mi")
        victim.metadata.uid = "uid-victim"
        bystander = make_pod("bystander", cpu="100m", memory="64Mi")
        bystander.metadata.uid = "uid-bystander"
        faultpoints.activate("wave.poison", "corrupt", times=None,
                             fn=poison_pod_fault("uid-victim", "crash"))
        assert faultpoints.fire("wave.poison",
                                payload=([bystander], None)) is False
        with pytest.raises(Exception):
            faultpoints.fire("wave.poison",
                             payload=([bystander, victim], None))


# -- fault-point registry drift guard ----------------------------------------

class TestRegistryDriftGuard:
    # matches the literal first argument of every faultpoints.fire()
    # call; \s* spans a wrapped call's newline
    _FIRE = re.compile(r"""faultpoints\.fire\(\s*["']([a-z0-9_.]+)["']""")

    def _fire_sites(self):
        root = pathlib.Path(faultpoints.__file__).resolve().parents[1]
        sites = {}
        for path in sorted(root.rglob("*.py")):
            if path.name == "faultpoints.py":
                continue  # the registry itself
            for name in self._FIRE.findall(path.read_text()):
                sites.setdefault(name, []).append(
                    str(path.relative_to(root)))
        return sites

    def test_every_fire_site_is_registered(self):
        """A fire() call at a point name missing from the docstring
        registry means parse() would reject a valid reproducer spec."""
        sites = self._fire_sites()
        unregistered = set(sites) - faultpoints.registered_points()
        assert not unregistered, (
            f"fire() call sites not in the faultpoints registry "
            f"docstring: "
            f"{ {n: sites[n] for n in sorted(unregistered)} }")

    def test_every_registered_point_is_wired(self):
        """A registry entry with no fire() call site is dead
        documentation: campaigns would arm it and inject nothing."""
        sites = self._fire_sites()
        dead = faultpoints.registered_points() - set(sites)
        assert not dead, (
            f"registry docstring entries with no fire() call site in "
            f"the tree: {sorted(dead)}")

    def test_samplable_matrix_is_a_registry_subset(self):
        from kubernetes_tpu.chaos.campaign import SAMPLABLE
        points = {p for p, _ in SAMPLABLE}
        assert points <= faultpoints.registered_points()


# -- invariant-checker mutation tests ----------------------------------------

def _mk_world(n_nodes=2):
    store = ObjectStore()
    sched = Scheduler(store, wave_size=8, caps=Caps(M=16, P=8, LV=16))
    checker = InvariantChecker(metrics=sched.metrics, strict=False)
    sched.invariants = checker
    for i in range(n_nodes):
        store.create("nodes", make_node(f"n{i}", cpu="16", memory="32Gi"))
    return store, sched, checker


def _check(sched, checker):
    with sched._mu:
        return checker.check(sched)


def _gang_pod(name, gang, min_member, cpu="100m"):
    p = make_pod(name, cpu=cpu, memory="64Mi")
    p.metadata.annotations = {
        "pod-group.scheduling.k8s.io/name": gang,
        "pod-group.scheduling.k8s.io/min-available": str(min_member)}
    return p


class TestCheckerMutations:
    def test_clean_world_is_clean(self):
        store, sched, checker = _mk_world()
        try:
            for i in range(4):
                store.create("pods", make_pod(f"ok-{i}", cpu="100m",
                                              memory="64Mi"))
            sched.schedule_pending()
            assert not _check(sched, checker)
            assert checker.checks > 1  # schedule_pending checked too
        finally:
            sched.close()

    def test_lost_pod_fires_conservation_after_hysteresis(self):
        store, sched, checker = _mk_world()
        try:
            pod = make_pod("lost-1", cpu="100m", memory="64Mi")
            store.create("pods", pod)
            # the seeded bug: the pod vanishes from every queue area
            # while still Pending in the store
            sched.queue.delete(pod)
            assert not _check(sched, checker)  # transient: quiet
            vs = _check(sched, checker)        # persistent: fires
            assert [v.invariant for v in vs] == ["conservation"]
            assert pod.uid in vs[0].digest["lost"]
            assert "lost" in vs[0].detail
        finally:
            sched.close()

    def test_double_booked_pod_fires_conservation(self):
        store, sched, checker = _mk_world()
        try:
            pod = make_pod("dbl-1", cpu="100m", memory="64Mi")
            store.create("pods", pod)  # sits in the active area
            # the seeded bug: bound in the store but never removed from
            # the queue (a rollback that forgot to un-park)
            pod.spec.node_name = "n0"
            assert not _check(sched, checker)
            vs = _check(sched, checker)
            assert [v.invariant for v in vs] == ["conservation"]
            booked = vs[0].digest["double_booked"]
            assert any(pod.uid in b and "placed+" in b for b in booked)
        finally:
            sched.close()

    def test_cache_double_bind_fires_immediately(self):
        """double_bind has no hysteresis: the cache never legitimately
        holds one pod's capacity on two nodes, even transiently."""
        store, sched, checker = _mk_world()
        try:
            pod = make_pod("twice-1", cpu="100m", memory="64Mi")
            pod.spec.node_name = "n0"
            store.create("pods", pod)
            sched.cache.node_infos["n1"].pods.append(pod)
            vs = _check(sched, checker)
            assert [v.invariant for v in vs] == ["double_bind"]
            assert any(pod.uid in d for d in vs[0].digest["cache_dupes"])
        finally:
            sched.close()

    def test_split_gang_fires_gang_atomic_after_hysteresis(self):
        store, sched, checker = _mk_world()
        try:
            bound = _gang_pod("gs-0", "gsplit", 3)
            # the seeded bug: one member committed, the rest abandoned
            # (a partial gang commit without rollback)
            bound.spec.node_name = "n0"
            store.create("pods", bound)
            for i in (1, 2):
                store.create("pods", _gang_pod(f"gs-{i}", "gsplit", 3))
            assert not _check(sched, checker)
            vs = _check(sched, checker)
            assert [v.invariant for v in vs] == ["gang_atomic"]
            assert any("gsplit" in g and "(1/3)" in g
                       for g in vs[0].digest["partial_gangs"])
        finally:
            sched.close()

    def test_strict_raises_and_counts_the_metric(self):
        store, sched, checker = _mk_world()
        checker.strict = True
        try:
            pod = make_pod("lost-2", cpu="100m", memory="64Mi")
            store.create("pods", pod)
            sched.queue.delete(pod)
            _check(sched, checker)
            with pytest.raises(InvariantViolation) as ei:
                _check(sched, checker)
            assert ei.value.invariant in INVARIANTS
            assert sched.metrics.invariant_violations.value(
                invariant="conservation") >= 1
        finally:
            sched.close()


# -- schedule sampling + the fixed-seed smoke --------------------------------

class TestCampaign:
    def test_sampler_is_deterministic_and_env_expressible(self):
        import random
        a = [sample_schedule(random.Random(11)) for _ in range(20)]
        b = [sample_schedule(random.Random(11)) for _ in range(20)]
        assert a == b
        for specs in a:
            assert 2 <= len(specs) <= 4
            points = [s.point for s in specs]
            assert len(points) == len(set(points))
            # every sampled schedule round-trips through the env-string
            # grammar (the shrinker's reproducer form)
            parsed = faultpoints.parse(env_string(specs))
            assert [p[0] for p in parsed] == points

    def test_fixed_seed_smoke_runs_clean(self):
        """The tier-1 campaign smoke: a healthy build survives 8 seeded
        composed fault schedules with zero invariant violations, and
        the injector demonstrably fired."""
        res = run_campaign(seed=3, schedules=8)
        assert res.ok, [f.outcome.detail for f in res.findings]
        assert res.schedules == 8
        assert res.checks_total > 0
        assert res.injected_total > 0  # a dead injector must not pass

    def test_budget_stops_sampling_early(self):
        res = run_campaign(seed=5, schedules=50, budget_s=0.0)
        assert res.schedules < 50


# -- the deliberately-broken-build acceptance --------------------------------

def _disable_gang_rollback(sched):
    sched._gang_rollback_enabled = False


class TestBrokenBuildAcceptance:
    """ISSUE 17 acceptance: disable the gang-commit rollback (the
    scheduler's test hook), and the campaign machinery must catch the
    resulting partial-commit leak, shrink the schedule to a minimal
    reproducer, and re-trigger it from the env string alone — while the
    healthy build tolerates the identical schedule."""

    # snapshot.write=corrupt inflates a node row's allocatable; the
    # next heartbeat uploads it, the gang kernel over-proposes, the
    # exact host recheck fails mid-commit — rollback (when enabled)
    # cleans up; without it, assumed members leak
    SCHEDULE = [FaultSpec("snapshot.write", "corrupt", times=4, tick=0)]
    SEED = 7

    def test_catch_shrink_and_env_retrigger(self):
        broken = replay(self.SCHEDULE, self.SEED,
                        configure=_disable_gang_rollback)
        assert broken.violated
        assert broken.violation in ("conservation", "gang_atomic")
        assert broken.digest  # evidence captured at the violating round

        minimal, mo = shrink(self.SCHEDULE, self.SEED,
                             configure=_disable_gang_rollback)
        assert mo.violated
        assert len(minimal) == 1
        assert minimal[0].point == "snapshot.write"
        assert minimal[0].times == 1  # one corrupt write is enough
        assert minimal[0].tick == 0   # env-activation form is exact

        env = env_string(minimal)
        assert env == "snapshot.write=corrupt::1"
        again = replay((), self.SEED, env_spec=env,
                       configure=_disable_gang_rollback)
        assert again.violated  # the paste-able reproducer re-triggers
        assert again.injected.get("snapshot.write", 0) >= 1

    def test_healthy_build_tolerates_the_same_schedule(self):
        out = replay(self.SCHEDULE, self.SEED)
        assert not out.violated
        assert out.injected.get("snapshot.write", 0) >= 1
        assert out.checks > 0
