"""Chaos / fault-injection tier: kill control-plane components mid-churn
and assert the cluster converges.

Reference: test/e2e/chaosmonkey/chaosmonkey.go:34 (Do: run tests around a
disruption) and the upgrade suite test/e2e/upgrades/. The reference's
recovery story is structural — every component is a stateless cache over
etcd, so crash = restart + informer relist (SURVEY.md §5 failure
detection). These tests kill each component once under load and assert
exactly that story:

  * apiserver crash: clients see connection errors, the store ("etcd")
    keeps the state; a replacement server on the same port serves it and
    reflectors relist with NO lost or duplicated pods.
  * scheduler crash: a scheduler dies with pods assumed-but-unbound; a
    fresh scheduler rebuilds its cache from the store and places
    everything exactly once (the 30s assume TTL never leaks capacity
    because the cache died with its process).
  * kubelet crash: heartbeats stop mid-churn; nodelifecycle tains/evicts
    (the NoExecute path) and the scheduler re-places the evicted pods on
    surviving nodes.
  * leader crash: the lease holder dies WITHOUT releasing; the standby
    acquires after lease expiry (leaderelection.go renew/acquire).
  * GC crash: the collector dies between the owner's deletion and its
    sweep; a fresh collector rebuilds the uid-keyed graph from a relist
    and still collects the orphaned dependents.
"""

import random
import threading
import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.reflector import RemoteStore
from kubernetes_tpu.client.rest import RESTClient
from kubernetes_tpu.kubemark.hollow import HollowCluster, HollowNode
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.server import APIServer

from helpers import make_node, make_pod


def _mkpod(name):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, labels={"type": "chaos"}),
        spec=api.PodSpec(containers=[api.Container(
            resources=api.ResourceRequirements(
                requests=api.resource_list(cpu="100m", memory="128Mi")))]))


class TestApiserverCrash:
    def test_restart_mid_churn_relists_no_lost_pods(self):
        """Kill the apiserver while a remote scheduler and hollow nodes
        churn through it; restart on the same port; every created pod
        must end up bound exactly once and mirrors must converge to the
        store (the reflector relist path)."""
        store = ObjectStore()  # the "etcd": outlives the apiserver
        srv = APIServer(store).start()
        port = srv.port

        # control plane AND nodes connect as clients, like a real cluster
        sched_store = RemoteStore(RESTClient(srv.url))
        sched = Scheduler(sched_store)
        nodes = [HollowNode(sched_store, f"c-n{i}",
                            allocatable=api.resource_list(
                                cpu="8", memory="16Gi", pods=50))
                 for i in range(3)]

        stop = threading.Event()

        def sched_loop():
            while not stop.is_set():
                if sched.run_once(timeout=0.05) == 0:
                    stop.wait(0.01)

        t = threading.Thread(target=sched_loop, daemon=True)
        t.start()
        for n in nodes:
            n.run(period=0.05)

        created = 0
        for i in range(20):
            store.create("pods", _mkpod(f"pre-{i}"))
            created += 1
        # let some scheduling happen, then CRASH the server abruptly
        time.sleep(0.3)
        srv.httpd.shutdown()
        srv.httpd.server_close()

        # while the apiserver is down the store keeps accepting writes
        # (other replicas would, in an HA setup); clients just error
        for i in range(20):
            store.create("pods", _mkpod(f"down-{i}"))
            created += 1
        time.sleep(0.3)

        # replacement replica on the SAME port over the same store
        srv2 = APIServer(store, port=port).start()
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                bound = [p for p in store.list("pods")
                         if p.spec.node_name]
                if len(bound) == created:
                    break
                time.sleep(0.1)
            bound = [p for p in store.list("pods") if p.spec.node_name]
            assert len(bound) == created, \
                f"lost pods after apiserver crash: {len(bound)}/{created}"
            # no duplicate placements: uids unique, store never saw a
            # conflicting second bind (store.bind raises on rebind)
            assert len({p.uid for p in bound}) == created
            # the reflector mirror converged to the relisted state
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if len([p for p in sched_store.list("pods")
                        if p.spec.node_name]) == created:
                    break
                time.sleep(0.05)
            assert len([p for p in sched_store.list("pods")
                        if p.spec.node_name]) == created
        finally:
            stop.set()
            t.join(timeout=5)
            for n in nodes:
                n.stop()
            sched.close()
            sched_store.stop()
            srv2.stop()


class _CrashyStore(ObjectStore):
    """Store whose bind fails N times — models a scheduler dying between
    assume and bind (the bind RPC never lands)."""

    def __init__(self, fail_binds: int):
        super().__init__()
        self.fail_binds = fail_binds

    def bind(self, pod, node_name):
        if self.fail_binds > 0:
            self.fail_binds -= 1
            raise ConnectionError("scheduler crashed before bind landed")
        return super().bind(pod, node_name)


class TestSchedulerCrash:
    def test_fresh_scheduler_rebuilds_and_places_exactly_once(self):
        store = _CrashyStore(fail_binds=4)
        for i in range(4):
            store.create("nodes", make_node(f"n{i}", cpu="4"))
        for i in range(8):
            store.create("pods", make_pod(f"p{i}", cpu="1"))
        sched_a = Scheduler(store)
        placed_a = sched_a.schedule_pending()
        # the first binds "crashed": those pods were assumed by A then
        # rolled back/requeued; A dies here (no close, no drain — crash)
        del sched_a

        # B starts from nothing: informer relist rebuilds cache+snapshot
        sched_b = Scheduler(store)
        placed_b = sched_b.schedule_pending()
        bound = [p for p in store.list("pods") if p.spec.node_name]
        assert len(bound) == 8, (placed_a, placed_b, len(bound))
        # capacity respected after the rebuild: 4 nodes x 4 cpu, 8x1cpu
        per_node = {}
        for p in bound:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(v <= 4 for v in per_node.values()), per_node
        sched_b.close()


class TestKubeletCrash:
    def test_node_death_mid_churn_reschedules(self):
        """Kubelet stops heartbeating under churn; nodelifecycle taints
        NoExecute and evicts; the scheduler re-places evicted pods on
        surviving nodes."""
        from kubernetes_tpu.controllers.nodelifecycle import \
            NodeLifecycleController

        store = ObjectStore()
        now = [1000.0]
        clock = lambda: now[0]  # noqa: E731
        hc = HollowCluster(store, n_nodes=4, heartbeat_period=1.0,
                           clock=clock)
        sched = Scheduler(store, clock=clock)
        ctrl = NodeLifecycleController(store, clock=clock,
                                       grace_period=3.0,
                                       eviction_wait=1.0)
        hc.sync_once()
        hc.create_pods(12, prefix="churn-a")
        assert sched.schedule_pending() == 12
        hc.sync_once()

        # node hollow-0 dies (stop syncing/heartbeating it); the rest
        # keep heartbeating while churn continues
        dead = hc.nodes[0]
        victims = [p.metadata.name for p in store.list("pods")
                   if p.spec.node_name == "hollow-0"]
        assert victims, "no pods landed on the doomed node"
        rng = random.Random(7)
        for step in range(8):
            now[0] += 1.0
            for n in hc.nodes[1:]:
                n.kubelet.heartbeat(now[0])
                n.sync_once(now[0])
            ctrl.monitor(now[0])
            if step == 2:
                hc.churn(2, rng)          # deletions mid-disruption
                hc.create_pods(4, prefix="churn-b")
            sched.schedule_pending()

        node0 = store.get("nodes", "", "hollow-0") or \
            store.get("nodes", "default", "hollow-0")
        assert any(t.key == "node.kubernetes.io/unreachable"
                   for t in (node0.spec.taints or [])), \
            "dead node was never tainted"
        # every surviving pod is bound to a LIVE node; the dead node's
        # pods were evicted and replaced elsewhere
        for p in store.list("pods"):
            assert p.spec.node_name, f"{p.metadata.name} never re-placed"
            assert p.spec.node_name != "hollow-0", \
                f"{p.metadata.name} still on the dead node"
        sched.close()
        hc.stop()
        assert dead is hc.nodes[0]


class TestLeaderCrash:
    def test_standby_takes_over_after_lease_expiry(self):
        from kubernetes_tpu.client.leaderelection import LeaderElector

        store = ObjectStore()
        now = [0.0]
        clock = lambda: now[0]  # noqa: E731
        events = []
        a = LeaderElector(store, "sched-a", lease_duration=10.0,
                          clock=clock,
                          on_started_leading=lambda: events.append("a-up"))
        b = LeaderElector(store, "sched-b", lease_duration=10.0,
                          clock=clock,
                          on_started_leading=lambda: events.append("b-up"))
        assert a._try_acquire_or_renew(), "initial acquisition failed"
        assert not b._try_acquire_or_renew()
        # a CRASHES: no release, the lease just stops being renewed
        now[0] += 5.0
        assert not b._try_acquire_or_renew(), "lease stolen before expiry"
        now[0] += 6.0  # renew_time + lease_duration passed
        assert b._try_acquire_or_renew(), "standby failed to take over"
        rec = store.get("leases", "default", "kube-scheduler")
        assert rec.holder_identity == "sched-b"


class TestGCCrash:
    def test_fresh_collector_rebuilds_graph_and_collects(self):
        from kubernetes_tpu.controllers.garbagecollector import \
            GarbageCollector

        store = ObjectStore()
        owner = api.ReplicaSet(
            metadata=api.ObjectMeta(name="rs-1"),
            selector=api.LabelSelector(match_labels={"app": "x"}))
        store.create("replicasets", owner)
        for i in range(3):
            pod = make_pod(f"dep-{i}")
            pod.metadata.labels = {"app": "x"}
            pod.metadata.owner_references = [api.OwnerReference(
                kind="ReplicaSet", name="rs-1", uid=owner.metadata.uid,
                controller=True)]
            store.create("pods", pod)
        gc_a = GarbageCollector(store)
        gc_a.sync_monitors()
        gc_a.sweep()
        assert store.count("pods") == 3  # owner alive: nothing collected
        # owner deleted, then the collector CRASHES before sweeping
        store.delete("replicasets", "default", "rs-1")
        del gc_a

        gc_b = GarbageCollector(store)
        gc_b.sync_monitors()  # rebuild the uid-keyed graph from relist
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and store.count("pods"):
            gc_b.sweep()
            time.sleep(0.01)
        assert store.count("pods") == 0, \
            "orphaned dependents survived the GC restart"


class TestWatchdogWedgedDispatch:
    """ISSUE 11 satellite: breaker half-open probing while a
    watchdog-abandoned dispatch is still in flight. The probe must NOT
    dispatch at a runtime with a wedged wave outstanding — the wedge
    would eat the probe exactly like the abandoned wave — so the
    OPEN -> HALF_OPEN transition is deferred until the wedge clears."""

    def _fill(self, store):
        for i in range(4):
            store.create("nodes", make_node(f"wd-n{i}", cpu="8",
                                            memory="16Gi"))

    def test_probe_deferred_until_wedged_dispatch_returns(self):
        from kubernetes_tpu.utils import faultpoints

        # warm the round program in a deadline-free scheduler first so
        # the guarded scheduler's dispatch budget is the warm one (a
        # cold compile is not a hang and gets the scaled budget)
        s1 = ObjectStore()
        self._fill(s1)
        a = Scheduler(s1, wave_size=16)
        for i in range(4):
            s1.create("pods", make_pod(f"warm-{i}", cpu="100m",
                                       memory="64Mi"))
        assert a.schedule_pending() == 4

        store = ObjectStore()
        self._fill(store)
        sched = Scheduler(store, wave_size=16, wave_deadline_s=0.1,
                          breaker_cooldown=0.05)
        # ONE wedged dispatch: 1.2s hang vs the 0.1s deadline
        faultpoints.activate("kernel.hang", "latency", arg=1.2, times=1)
        for i in range(4):
            store.create("pods", make_pod(f"p-{i}", cpu="100m",
                                          memory="64Mi"))
        placed = sched.schedule_pending()
        assert placed == 4  # salvaged via the hostwave twin
        assert sched.breaker.state == "open"
        assert sched.watchdog.outstanding() == 1

        # cooldown elapsed AND the wedge still in flight: scheduling
        # continues degraded, the probe is NOT spent, the breaker
        # stays OPEN (allow() was never consulted)
        time.sleep(0.06)
        store.create("pods", make_pod("while-wedged", cpu="100m",
                                      memory="64Mi"))
        assert sched.schedule_pending() == 1
        assert sched.breaker.state == "open", \
            "probe dispatched at a runtime with a wedged wave in flight"
        assert sched.wave_path() == "vector"

        # the wedged thread returns: the next wave IS the probe, it
        # succeeds on the healthy runtime, and the breaker closes
        deadline = time.monotonic() + 3.0
        while sched.watchdog.outstanding() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sched.watchdog.outstanding() == 0
        store.create("pods", make_pod("after-heal", cpu="100m",
                                      memory="64Mi"))
        assert sched.schedule_pending() == 1
        assert sched.breaker.state == "closed"
        assert sched.wave_path() in ("xla", "pallas")

    def test_half_open_probe_failure_reopens_while_hang_mode_persists(self):
        from kubernetes_tpu.utils import faultpoints

        s1 = ObjectStore()
        self._fill(s1)
        a = Scheduler(s1, wave_size=16)
        for i in range(2):
            s1.create("pods", make_pod(f"warm2-{i}", cpu="100m",
                                       memory="64Mi"))
        assert a.schedule_pending() == 2

        store = ObjectStore()
        self._fill(store)
        sched = Scheduler(store, wave_size=16, wave_deadline_s=0.1,
                          breaker_cooldown=0.05)
        # EVERY dispatch hangs (a persistently wedged runtime): the
        # first trip opens; after each cooldown the probe hangs too,
        # is abandoned, and re-opens with a fresh cooldown — placement
        # never stops through it all
        faultpoints.activate("kernel.hang", "latency", arg=0.4)
        total = 0
        for i in range(3):
            store.create("pods", make_pod(f"w-{i}", cpu="100m",
                                          memory="64Mi"))
            total += sched.schedule_pending()
            time.sleep(0.45)  # wedge clears + cooldown elapses
        assert total == 3
        assert sched.breaker.state == "open"
        assert sched.breaker.trips >= 2  # initial trip + >=1 probe re-trip
        faultpoints.reset()
        assert sched.watchdog.drain(5.0)  # no orphan dispatch leaks out
