"""Cloud provider layer tests: fake cloud, service LB, routes, node
init, node IPAM.

Reference test model: pkg/controller/service/service_controller_test.go,
pkg/controller/route/route_controller_test.go,
pkg/controller/cloud/node_controller_test.go — all run against the fake
cloud, as here.
"""

from kubernetes_tpu.api import types as api
from kubernetes_tpu.cloud import FakeCloud
from kubernetes_tpu.controllers import (CloudNodeController, ControllerManager,
                                        NodeIpamController, RouteController,
                                        ServiceLBController)
from kubernetes_tpu.controllers.cloud_node import (CLOUD_TAINT,
                                                   LABEL_INSTANCE_TYPE,
                                                   LABEL_ZONE)
from kubernetes_tpu.controllers.nodeipam import CidrSet
from kubernetes_tpu.runtime.store import ObjectStore


def mknode(name, ready=True, taints=None):
    return api.Node(
        metadata=api.ObjectMeta(name=name),
        spec=api.NodeSpec(taints=taints or []),
        status=api.NodeStatus(conditions=[api.NodeCondition(
            api.NODE_READY, api.COND_TRUE if ready else api.COND_FALSE)]))


class TestServiceLB:
    def test_ensure_and_status_writeback(self):
        store = ObjectStore()
        store.create("nodes", mknode("n1"))
        store.create("nodes", mknode("n2", ready=False))
        cloud = FakeCloud()
        ctrl = ServiceLBController(store, cloud)
        store.create("services", api.Service(
            metadata=api.ObjectMeta(name="web"),
            spec=api.ServiceSpec(type="LoadBalancer",
                                 ports=[api.ServicePort(port=80)])))
        ctrl.sync_all()
        svc = store.get("services", "default", "web")
        assert svc.status.load_balancer.ingress[0].ip.startswith("203.0.113.")
        # only the ready node backs the LB
        assert cloud.balancers["default/web"][1] == ["n1"]

    def test_node_churn_updates_backends(self):
        store = ObjectStore()
        store.create("nodes", mknode("n1"))
        cloud = FakeCloud()
        ctrl = ServiceLBController(store, cloud)
        store.create("services", api.Service(
            metadata=api.ObjectMeta(name="web"),
            spec=api.ServiceSpec(type="LoadBalancer",
                                 ports=[api.ServicePort(port=80)])))
        ctrl.sync_all()
        store.create("nodes", mknode("n2"))
        ctrl.sync_all()
        assert cloud.balancers["default/web"][1] == ["n1", "n2"]

    def test_delete_and_type_change_tear_down(self):
        store = ObjectStore()
        store.create("nodes", mknode("n1"))
        cloud = FakeCloud()
        ctrl = ServiceLBController(store, cloud)
        for name in ("a", "b"):
            store.create("services", api.Service(
                metadata=api.ObjectMeta(name=name),
                spec=api.ServiceSpec(type="LoadBalancer",
                                     ports=[api.ServicePort(port=80)])))
        ctrl.sync_all()
        assert set(cloud.balancers) == {"default/a", "default/b"}
        store.delete("services", "default", "a")
        b = store.get("services", "default", "b")
        b.spec.type = "ClusterIP"
        store.update("services", b)
        ctrl.sync_all()
        assert cloud.balancers == {}
        assert store.get("services", "default",
                         "b").status.load_balancer.ingress == []

    def test_restarted_controller_tears_down_seeded_lb(self):
        store = ObjectStore()
        store.create("nodes", mknode("n1"))
        cloud = FakeCloud()
        first = ServiceLBController(store, cloud)
        store.create("services", api.Service(
            metadata=api.ObjectMeta(name="web"),
            spec=api.ServiceSpec(type="LoadBalancer",
                                 ports=[api.ServicePort(port=80)])))
        first.sync_all()
        first.stop()
        # failover: a fresh instance must learn the LB from persisted
        # status, then tear it down when the service goes away
        second = ServiceLBController(store, cloud)
        store.delete("services", "default", "web")
        second.sync_all()
        assert cloud.balancers == {}

    def test_lb_error_retries(self):
        store = ObjectStore()
        store.create("nodes", mknode("n1"))
        cloud = FakeCloud()
        cloud.fail_next["ensure-load-balancer"] = RuntimeError("quota")
        ctrl = ServiceLBController(store, cloud)
        store.create("services", api.Service(
            metadata=api.ObjectMeta(name="web"),
            spec=api.ServiceSpec(type="LoadBalancer",
                                 ports=[api.ServicePort(port=80)])))
        ctrl.sync_all()
        assert ctrl.sync_errors == 1
        import time
        time.sleep(0.1)  # rate-limited requeue lands
        ctrl.sync_all()
        assert "default/web" in cloud.balancers


class TestNodeIpam:
    def test_cidrset_allocates_disjoint_subnets(self):
        cs = CidrSet("10.244.0.0/16", 24)
        a, b = cs.allocate_next(), cs.allocate_next()
        assert a == "10.244.0.0/24" and b == "10.244.1.0/24"
        cs.release(a)
        assert cs.allocate_next() == a  # reused after release

    def test_controller_assigns_and_releases(self):
        store = ObjectStore()
        ipam = NodeIpamController(store, "10.244.0.0/16")
        store.create("nodes", mknode("n1"))
        store.create("nodes", mknode("n2"))
        ipam.sync_all()
        cidrs = {store.get("nodes", "default", n).spec.pod_cidr
                 for n in ("n1", "n2")}
        assert cidrs == {"10.244.0.0/24", "10.244.1.0/24"}
        store.delete("nodes", "default", "n2")
        store.create("nodes", mknode("n3"))
        ipam.sync_all()
        assert store.get("nodes", "default",
                         "n3").spec.pod_cidr == "10.244.1.0/24"

    def test_restart_occupies_existing(self):
        store = ObjectStore()
        n1 = mknode("n1")
        n1.spec.pod_cidr = "10.244.0.0/24"
        store.create("nodes", n1)
        ipam = NodeIpamController(store, "10.244.0.0/16")
        store.create("nodes", mknode("n2"))
        ipam.sync_all()
        assert store.get("nodes", "default",
                         "n2").spec.pod_cidr == "10.244.1.0/24"


class TestRouteController:
    def test_routes_follow_pod_cidrs(self):
        store = ObjectStore()
        cloud = FakeCloud()
        n1 = mknode("n1")
        n1.spec.pod_cidr = "10.244.0.0/24"
        store.create("nodes", n1)
        rc = RouteController(store, cloud)
        rc.sync_all()
        assert [(r.target_node, r.dest_cidr)
                for r in cloud.route_table.values()] == [("n1", "10.244.0.0/24")]
        # network condition cleared once routed
        node = store.get("nodes", "default", "n1")
        cond = next(c for c in node.status.conditions
                    if c.type == api.NODE_NETWORK_UNAVAILABLE)
        assert cond.status == api.COND_FALSE
        # node deletion removes the stale route
        store.delete("nodes", "default", "n1")
        rc.sync_all()
        assert cloud.route_table == {}


class TestRouteFailure:
    def test_failed_create_marks_node_unreachable(self):
        store = ObjectStore()
        cloud = FakeCloud()
        n1 = mknode("n1")
        n1.spec.pod_cidr = "10.244.0.0/24"
        store.create("nodes", n1)
        orig_create = cloud.create_route

        def always_fail(*a, **k):
            raise RuntimeError("cloud down")

        cloud.create_route = always_fail
        rc = RouteController(store, cloud)
        rc.sync_all()
        assert rc.sync_errors >= 1
        node = store.get("nodes", "default", "n1")
        cond = next(c for c in node.status.conditions
                    if c.type == api.NODE_NETWORK_UNAVAILABLE)
        assert cond.status == api.COND_TRUE  # scheduler must avoid it
        cloud.create_route = orig_create
        import time
        time.sleep(0.3)
        rc.sync_all()  # retry succeeds
        node = store.get("nodes", "default", "n1")
        cond = next(c for c in node.status.conditions
                    if c.type == api.NODE_NETWORK_UNAVAILABLE)
        assert cond.status == api.COND_FALSE


class TestCloudNode:
    def test_initializes_tainted_node(self):
        store = ObjectStore()
        cloud = FakeCloud()
        cloud.add_instance("n1", internal_ip="10.1.0.5", zone="us-x1",
                           region="us", instance_type="tpu-v5e-8")
        store.create("nodes", mknode(
            "n1", taints=[api.Taint(key=CLOUD_TAINT, effect="NoSchedule")]))
        cnc = CloudNodeController(store, cloud)
        cnc.sync_all()
        node = store.get("nodes", "default", "n1")
        assert not any(t.key == CLOUD_TAINT for t in node.spec.taints)
        assert node.spec.provider_id == "fake://n1"
        assert node.metadata.labels[LABEL_INSTANCE_TYPE] == "tpu-v5e-8"
        assert node.metadata.labels[LABEL_ZONE] == "us-x1"
        assert any(a.type == "InternalIP" and a.address == "10.1.0.5"
                   for a in node.status.addresses)

    def test_unknown_instance_retries(self):
        store = ObjectStore()
        cloud = FakeCloud()  # no instances registered
        store.create("nodes", mknode(
            "n1", taints=[api.Taint(key=CLOUD_TAINT, effect="NoSchedule")]))
        cnc = CloudNodeController(store, cloud)
        cnc.sync_all()
        assert cnc.sync_errors >= 1  # KeyError -> rate-limited retry
        node = store.get("nodes", "default", "n1")
        assert any(t.key == CLOUD_TAINT for t in node.spec.taints)


class TestManagerWiring:
    def test_cloud_controllers_join_the_roster(self):
        store = ObjectStore()
        cloud = FakeCloud()
        mgr = ControllerManager(store, cloud=cloud,
                                cluster_cidr="10.244.0.0/16")
        for name in ("service-lb", "route", "cloud-node", "nodeipam"):
            assert name in mgr.controllers
        # end to end through the manager: node -> cidr -> route -> LB
        cloud.add_instance("n1")
        store.create("nodes", mknode("n1"))
        store.create("services", api.Service(
            metadata=api.ObjectMeta(name="web"),
            spec=api.ServiceSpec(type="LoadBalancer",
                                 ports=[api.ServicePort(port=80)])))
        mgr.sync_all(rounds=2)
        node = store.get("nodes", "default", "n1")
        assert node.spec.pod_cidr
        assert cloud.route_table
        assert store.get("services", "default",
                         "web").status.load_balancer.ingress
