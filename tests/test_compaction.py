"""Resource-exhaustion survival: HBM budget governor, vocab & row
compaction, and capacity-fault (OOM) recovery (ISSUE 20).

The snapshot's shared interners are append-only between sweeps and its
row buckets only ever grow, so multi-day node/pod churn — fresh
hostnames, zone values, label values, images every generation — leaks
device memory until XLA throws RESOURCE_EXHAUSTED. These tests are the
acceptance proofs for the memory-governance plane:

  * churned vocabularies PLATEAU under the housekeeping compaction
    cadence (and demonstrably leak without it — the regression guard);
  * compaction is invisible to placement: the same pending batch
    places bit-identically with and without a forced sweep in between;
  * the golden-row scrubber finds zero divergence in a compacted
    snapshot, and per-row delta uploads re-engage after the
    compaction's full re-upload (single-device and 8-way mesh);
  * the HBM budget governor turns an over-budget grow into a demanded
    compaction instead of a backend throw;
  * a device.oom storm is classified as a CAPACITY fault: compacted
    and retried — never a breaker trip, never a mesh reform, never a
    pod conviction (the exact over-trigger matrix test_poison pins for
    input faults, applied to the third verdict class).

Runs single-device except the explicitly mesh-marked case.
"""

import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched import breaker as breaker_mod
from kubernetes_tpu.sched.breaker import (ResourceExhausted,
                                          is_capacity_error, oom_fault)
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.utils import faultpoints

from helpers import make_node, make_pod

pytestmark = pytest.mark.soak


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _world(n_nodes=8, clock=None, **kw):
    store = ObjectStore()
    for i in range(n_nodes):
        store.create("nodes", make_node(
            f"n{i}", cpu="32", memory="64Gi",
            labels={"kubernetes.io/hostname": f"n{i}",
                    api.LABEL_ZONE: f"z{i % 3}"}))
    if clock is not None:
        kw["clock"] = clock
    sched = Scheduler(store, wave_size=kw.pop("wave_size", 32), **kw)
    return store, sched


def _pods(store, n, prefix="p", labels=None):
    pods = []
    for i in range(n):
        p = make_pod(f"{prefix}{i}", cpu="100m", memory="128Mi",
                     labels=labels)
        store.create("pods", p)
        pods.append(p)
    return pods


def _placements(store):
    return sorted((p.metadata.name, p.spec.node_name)
                  for p in store.list("pods") if p.spec.node_name)


def _assert_capacity_never_convicts(sched):
    """The over-trigger matrix: a capacity fault must move NONE of the
    fault planes that device faults and input faults own."""
    assert sched.breaker.state == breaker_mod.CLOSED
    assert int(sched.metrics.device_path_trips.value) == 0
    assert int(sched.metrics.mesh_reforms.total()) == 0
    assert sched.poison_convictions == 0
    assert sched.queue.quarantine_count() == 0


def _churn_generation(store, sched, gen, n_nodes=4, n_pods=6):
    """One epoch of multi-day churn: every string is generation-fresh
    (hostnames, zone values, pod label values) — the vocab leak."""
    if gen:
        for p in store.list("pods"):
            if p.metadata.labels.get("rev") == f"r{gen - 1}":
                try:
                    store.delete("pods", "default", p.metadata.name)
                except KeyError:
                    pass
        for i in range(n_nodes):
            try:
                store.delete("nodes", "default", f"g{gen - 1}-n{i}")
            except KeyError:
                pass
    for i in range(n_nodes):
        name = f"g{gen}-n{i}"
        store.create("nodes", make_node(
            name, cpu="32", memory="64Gi",
            labels={"kubernetes.io/hostname": name,
                    api.LABEL_ZONE: f"zone-{gen}"}))
    for i in range(n_pods):
        store.create("pods", make_pod(
            f"g{gen}-p{i}", cpu="100m", memory="128Mi",
            labels={"rev": f"r{gen}", "app": f"app-{gen}"}))


# -- the vocab leak and its plateau (satellite a) ------------------------------


class TestVocabPlateau:
    GENS = 10

    def _run(self, compact_interval):
        clk = FakeClock()
        store = ObjectStore()
        sched = Scheduler(store, wave_size=16, clock=clk,
                          compact_interval=compact_interval)
        for gen in range(self.GENS):
            _churn_generation(store, sched, gen)
            clk.advance(60.0)
            sched._housekeep()
            sched.schedule_pending()
        sizes = dict(sched.snapshot.vocabs.sizes())
        sched.close()
        return sizes

    def test_churned_vocabs_plateau_under_cadence(self):
        """With the compaction cadence armed, generation churn leaves
        only the LIVE generation's strings interned (plus the one that
        arrived since the last sweep) — without it, every retired
        hostname/zone/label value is retained forever. The leaked run
        is the regression control: if interners ever learn to forget on
        their own, the control stops leaking and this test demands a
        look."""
        leaked = self._run(compact_interval=0.0)
        governed = self._run(compact_interval=50.0)
        # control: append-only interners retain all GENS generations
        assert leaked["zones"] >= self.GENS
        assert leaked["label_values"] >= self.GENS
        # governed: bounded by the ~2 generations alive between sweeps
        assert governed["zones"] <= 4, governed
        assert governed["label_values"] < leaked["label_values"] // 2
        assert governed["pod_label_keys"] <= leaked["pod_label_keys"]

    def test_removals_counter_gates_cadence(self):
        """The cadence only sweeps when churn actually retired rows —
        a static cluster pays nothing for an armed interval."""
        clk = FakeClock()
        store, sched = _world(clock=clk, compact_interval=10.0)
        _pods(store, 8)
        sched.schedule_pending()
        clk.advance(1000.0)
        sched._housekeep()
        assert sched.metrics.snapshot_compactions_total.total() == 0
        # retire one pod: the next elapsed cadence has work to do
        store.delete("pods", "default", "p0")
        clk.advance(20.0)
        sched._housekeep()
        assert sched.metrics.snapshot_compactions_total.total() == 1
        sched.close()


# -- compaction is invisible to placement --------------------------------------


class TestCompactionParity:
    def _run(self, compact_between):
        store, sched = _world(n_nodes=8)
        _pods(store, 16, prefix="warm-")
        sched.schedule_pending()
        # churn so the sweep has garbage to reclaim
        for i in range(8):
            store.delete("pods", "default", f"warm-{i}")
        sched._housekeep()
        if compact_between:
            summary = sched.scrubber.compact(force=True)
            assert summary is not None
        _pods(store, 12, prefix="batch-")
        sched.schedule_pending()
        out = _placements(store)
        sched.close()
        return out

    def test_placements_bit_equal_across_compaction(self):
        assert self._run(False) == self._run(True)

    def test_version_bump_invalidates_featurizer_cache(self):
        """The vocab generation leads the version tuple: a compacted
        vocabulary must never serve a featurize cache entry built
        against the old ids."""
        _, sched = _world()
        v0 = sched.snapshot.vocabs.version()
        sched.scrubber.compact(force=True)
        v1 = sched.snapshot.vocabs.version()
        assert v0 != v1
        assert v1[0] == v0[0] + 1
        sched.close()

    def test_hysteresis_resists_bucket_thrash(self):
        """Un-forced sweeps only shrink a bucket when the target is a
        full power-of-two rung below the live one — otherwise a
        grow/shrink cycle at a bucket boundary would mint a fresh jit
        cache entry per round."""
        store, sched = _world(n_nodes=8)
        _pods(store, 100)  # past the 64-row default: M grows to 128
        sched.schedule_pending()
        grown_m = sched.snapshot.caps.M
        assert grown_m > 64
        # retire a sliver — live rows stay well above half the bucket
        for i in range(10):
            store.delete("pods", "default", f"p{i}")
        sched._housekeep()
        summary = sched.scrubber.compact()
        assert summary is not None
        assert sched.snapshot.caps.M == grown_m, summary["shrunk"]
        # retire nearly everything: the rung is earned, the sweep takes it
        for i in range(10, 90):
            store.delete("pods", "default", f"p{i}")
        sched._housekeep()
        summary = sched.scrubber.compact()
        assert sched.snapshot.caps.M < grown_m, summary["shrunk"]
        sched.close()

    def test_staged_rows_defer_compaction(self):
        """Device kernels hold staged row indices mid-round: a sweep
        then would renumber them under the kernel. The request parks
        and the next housekeeping pass (rows unstaged) serves it."""
        store, sched = _world()
        p = make_pod("staged", cpu="100m", memory="128Mi")
        sched.snapshot.stage_pending([p])
        assert sched.snapshot.has_staged_rows()
        assert sched.scrubber.compact(force=True) is None
        assert sched.snapshot.compaction_requested
        sched.snapshot.unstage(p)
        assert sched.scrubber.maybe_compact() is not None
        assert not sched.snapshot.compaction_requested
        sched.close()


# -- the HBM budget governor ---------------------------------------------------


class TestGovernor:
    def test_over_budget_grow_demands_compaction(self):
        store, sched = _world()
        _pods(store, 8)
        sched.schedule_pending()
        assert sched.snapshot.hbm_headroom_bytes() is None  # unbudgeted
        sched.snapshot.hbm_budget_bytes = \
            sched.snapshot.projected_hbm_bytes() + 1
        assert sched.snapshot.hbm_headroom_bytes() > 0
        # push the pod bucket past its rung: the grow lands (never a
        # throw) but flags the governor
        _pods(store, int(sched.snapshot.caps.M), prefix="burst-")
        sched.schedule_pending()
        sched._housekeep()
        assert sched.metrics.snapshot_compactions_total.value(
            trigger="governor") >= 1
        sched.close()

    def test_headroom_gauge_exported(self):
        _, sched = _world(hbm_budget_bytes=1 << 30)
        sched.schedule_pending()
        sched.export_queue_gauges()
        head = sched.metrics.hbm_headroom_bytes.value
        assert 0 < head <= 1 << 30
        assert sched.metrics.snapshot_vocab_size.value(vocab="zones") >= 1
        sched.close()


# -- golden rows and delta uploads across a sweep (satellite d) ----------------


class TestCompactedSnapshotTransport:
    def _settled(self, **kw):
        store, sched = _world(n_nodes=8, **kw)
        _pods(store, 24)
        sched.schedule_pending()
        for i in range(12):  # garbage for the sweep
            store.delete("pods", "default", f"p{i}")
        sched._housekeep()
        return store, sched

    def _assert_cache_matches_fresh(self, snap, mesh=None):
        snap.to_device(mesh=mesh)
        got = {g: [np.asarray(a) for a in snap._device_cache[g]]
               for g in ("res", "topo", "pods", "terms")}
        snap._device_cache.clear()
        snap.to_device(mesh=mesh)
        for g, arrays in got.items():
            for i, (a, b) in enumerate(zip(arrays, snap._device_cache[g])):
                np.testing.assert_array_equal(
                    a, np.asarray(b),
                    err_msg=f"group {g} array {i} diverged after the "
                            f"post-compaction delta path")

    def test_scrub_finds_compacted_snapshot_clean(self):
        _, sched = self._settled()
        assert sched.scrubber.compact(force=True) is not None
        rep = sched.scrubber.scrub()
        assert rep.clean and rep.repaired == 0, rep.divergences
        sched.close()

    def test_delta_uploads_reengage_after_compaction(self):
        """A sweep swaps every array, so the first post-sweep upload
        must be FULL (stale dirty ranges against reallocated arrays
        would corrupt silently) — and the next row of churn must ride
        the cheap delta path again, bitwise-equal a fresh upload."""
        store, sched = self._settled()
        snap = sched.snapshot
        snap.to_device()
        assert sched.scrubber.compact(force=True) is not None
        before = snap.upload_bytes_total
        snap.to_device()
        # the sweep cleared _group_bytes with the stale cache, so the
        # footprint is only measurable after this (full) re-upload
        full = sum(snap._group_bytes.values())
        assert snap.upload_bytes_total - before >= full > 0
        # one bind of churn: delta engages
        node = snap.node_names[0]
        p = make_pod("delta-probe", cpu="100m", node_name=node)
        sched.cache.add_pod(p)
        snap.refresh_node_resources(sched.cache.node_infos[node])
        snap.add_pod(p)
        before = snap.upload_bytes_total
        snap.to_device()
        moved = snap.upload_bytes_total - before
        assert 0 < moved < full // 4, (moved, full)
        self._assert_cache_matches_fresh(snap)
        sched.close()

    @pytest.mark.mesh
    def test_compaction_parity_under_mesh(self):
        """The full re-upload and re-engaged deltas against an 8-way
        node-sharded device cache."""
        from kubernetes_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(8)
        store, sched = self._settled()
        snap = sched.snapshot
        snap.to_device(mesh=mesh)
        assert sched.scrubber.compact(force=True) is not None
        self._assert_cache_matches_fresh(snap, mesh=mesh)
        node = snap.node_names[0]
        p = make_pod("mesh-probe", cpu="100m", node_name=node)
        sched.cache.add_pod(p)
        snap.refresh_node_resources(sched.cache.node_infos[node])
        snap.add_pod(p)
        self._assert_cache_matches_fresh(snap, mesh=mesh)
        sched.close()


# -- capacity-fault classification (satellites b + c) --------------------------


class TestCapacityClassifier:
    def test_instances_and_markers(self):
        assert is_capacity_error(MemoryError("alloc"))
        assert is_capacity_error(ResourceExhausted("hbm"))
        assert is_capacity_error(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory while trying to "
            "allocate 2.1G"))
        assert is_capacity_error(RuntimeError("OOM when allocating"))
        assert not is_capacity_error(ValueError("bad shape"))
        assert not is_capacity_error(RuntimeError("device lost"))

    def test_sees_through_wrapping(self):
        try:
            try:
                raise MemoryError("backend alloc")
            except MemoryError as inner:
                raise RuntimeError("jit wrapper") from inner
        except RuntimeError as wrapped:
            assert is_capacity_error(wrapped)

    def test_cycle_guarded(self):
        a = RuntimeError("a")
        b = RuntimeError("b")
        a.__cause__, b.__cause__ = b, a
        assert not is_capacity_error(a)

    def test_raise_mode_fault_point_classifies(self):
        """KTPU_FAULTPOINTS='device.oom=raise' must land in the
        capacity class without a custom corrupt fn — the paste-able
        reproducer contract."""
        faultpoints.activate("device.oom", "raise", times=1)
        try:
            with pytest.raises(faultpoints.FaultInjected) as ei:
                faultpoints.fire("device.oom", payload=("TPU_0",))
            assert is_capacity_error(ei.value)
        finally:
            faultpoints.deactivate("device.oom")

    def test_oom_fault_corrupt_helper(self):
        fn = oom_fault()
        fn(None)  # unarmed dispatch: no-op, matching lost_device_fault
        with pytest.raises(ResourceExhausted):
            fn(("TPU_0",))


class TestCapacityRecovery:
    def test_device_oom_storm_never_convicts(self):
        """The mirror of test_poison's over-trigger matrix for the
        third verdict class: a device.oom burst mid-schedule ends with
        every pod placed, the breaker CLOSED, zero mesh reforms, zero
        convictions — and the compaction ladder visibly engaged."""
        store, sched = _world()
        _pods(store, 32)
        faultpoints.activate("device.oom", "raise", times=2)
        try:
            placed = sched.schedule_pending()
        finally:
            faultpoints.deactivate("device.oom")
        assert placed == 32
        _assert_capacity_never_convicts(sched)
        assert int(sched.metrics.capacity_faults.value) == 2
        assert sched.metrics.snapshot_compactions_total.value(
            trigger="oom") >= 1
        # the round that finally succeeded reset the strike ladder
        assert sched._capacity_strikes == 0
        sched.close()

    def test_memoryerror_at_featurize_is_capacity_not_poison(self):
        """featurize deliberately propagates MemoryError raw (it is an
        environment fault, not the pod's) — the scheduler must route it
        to the capacity ladder, never to a PodFeaturizeError
        conviction."""
        store, sched = _world()
        _pods(store, 16)
        orig = sched.featurizer.featurize
        state = {"raised": False}

        def flaky(pods, *a, **kw):
            if not state["raised"]:
                state["raised"] = True
                raise MemoryError("host arena exhausted featurizing")
            return orig(pods, *a, **kw)

        sched.featurizer.featurize = flaky
        placed = sched.schedule_pending()
        assert state["raised"] and placed == 16
        _assert_capacity_never_convicts(sched)
        assert int(sched.metrics.capacity_faults.value) >= 1
        sched.close()

    def test_breaker_charged_only_when_headroom_stays_negative(self):
        """Compaction that cannot restore headroom is the ONLY path
        from a capacity fault to the whole-path breaker — and even
        then the round degrades to the host twin and places."""
        store, sched = _world()
        _pods(store, 16)
        sched.snapshot.hbm_budget_bytes = 1  # unsatisfiable
        faultpoints.activate("device.oom", "raise", times=1)
        try:
            placed = sched.schedule_pending()
        finally:
            faultpoints.deactivate("device.oom")
        assert placed == 16
        assert sched.breaker.failures >= 1  # charged…
        assert int(sched.metrics.device_path_trips.value) == 0  # …not tripped
        assert sched.poison_convictions == 0
        assert int(sched.metrics.mesh_reforms.total()) == 0
        sched.close()

    def test_healthy_budget_keeps_breaker_unchanged(self):
        """With headroom restored by the sweep, the breaker sees the
        capacity fault not at all — consecutive-failure accounting
        belongs to genuine device faults."""
        store, sched = _world(hbm_budget_bytes=1 << 30)
        _pods(store, 16)
        faultpoints.activate("device.oom", "raise", times=1)
        try:
            placed = sched.schedule_pending()
        finally:
            faultpoints.deactivate("device.oom")
        assert placed == 16
        assert sched.breaker.failures == 0
        _assert_capacity_never_convicts(sched)
        sched.close()
