"""Controller tests: table-driven reconciliation checks per controller,
plus a cascade test through the manager (deployment -> replicaset ->
pods -> endpoints -> pdb status), mirroring the reference's controller
unit tests (pkg/controller/*/..._test.go patterns over fake clientsets).
"""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.labels import LabelSelector
from kubernetes_tpu.controllers import (ControllerManager, CronJobController,
                                        DaemonSetController,
                                        DeploymentController,
                                        DisruptionController,
                                        EndpointsController, GarbageCollector,
                                        JobController, NamespaceController,
                                        NodeLifecycleController,
                                        PersistentVolumeController,
                                        PodGCController, ReplicaSetController,
                                        ServiceAccountController,
                                        StatefulSetController)
from kubernetes_tpu.controllers.cronjob import cron_matches
from kubernetes_tpu.controllers.nodelifecycle import (HEARTBEAT_ANNOTATION,
                                                      TAINT_NOT_READY,
                                                      TAINT_UNREACHABLE)
from kubernetes_tpu.runtime.store import ObjectStore

SEL = LabelSelector(match_labels={"app": "w"})
TMPL = api.PodTemplateSpec(
    metadata=api.ObjectMeta(labels={"app": "w"}),
    spec=api.PodSpec(containers=[api.Container(
        resources=api.ResourceRequirements(
            requests=api.resource_list(cpu="100m", memory="64Mi")))]))


def mark_running(store, pod, ready=True):
    pod.status.phase = "Running"
    pod.status.conditions = [c for c in pod.status.conditions
                             if c[0] != "Ready"] + \
        [("Ready", "True" if ready else "False")]
    store.update("pods", pod)


def mknode(name, ready=True, hb=None):
    ann = {HEARTBEAT_ANNOTATION: str(hb)} if hb is not None else {}
    return api.Node(
        metadata=api.ObjectMeta(name=name, annotations=ann),
        status=api.NodeStatus(
            allocatable=api.resource_list(cpu="8", memory="16Gi", pods=110),
            conditions=[api.NodeCondition(
                api.NODE_READY, api.COND_TRUE if ready else api.COND_FALSE)]))


class TestReplicaSet:
    def test_scale_up_down_and_status(self):
        store = ObjectStore()
        ctrl = ReplicaSetController(store)
        rs = api.ReplicaSet(
            metadata=api.ObjectMeta(name="rs1"),
            spec=api.ReplicaSetSpec(replicas=3, selector=SEL, template=TMPL))
        store.create("replicasets", rs)
        ctrl.sync_all()
        pods = store.list("pods")
        assert len(pods) == 3
        assert all(p.metadata.owner_references[0].kind == "ReplicaSet"
                   for p in pods)
        for p in pods:
            mark_running(store, p)
        ctrl.sync_all()
        rs = store.get("replicasets", "default", "rs1")
        assert rs.status.replicas == 3 and rs.status.ready_replicas == 3
        rs.spec.replicas = 1
        store.update("replicasets", rs)
        ctrl.sync_all()
        assert len(store.list("pods")) == 1

    def test_prefers_not_ready_victims(self):
        store = ObjectStore()
        ctrl = ReplicaSetController(store)
        rs = api.ReplicaSet(
            metadata=api.ObjectMeta(name="rs1"),
            spec=api.ReplicaSetSpec(replicas=2, selector=SEL, template=TMPL))
        store.create("replicasets", rs)
        ctrl.sync_all()
        pods = store.list("pods")
        mark_running(store, pods[0], ready=True)
        mark_running(store, pods[1], ready=False)
        rs = store.get("replicasets", "default", "rs1")
        rs.spec.replicas = 1
        store.update("replicasets", rs)
        ctrl.sync_all()
        left = store.list("pods")
        assert len(left) == 1
        assert left[0].metadata.name == pods[0].metadata.name


class TestDeployment:
    def test_rollout_creates_rs_and_scales(self):
        store = ObjectStore()
        dep_ctrl = DeploymentController(store)
        rs_ctrl = ReplicaSetController(store)
        dep = api.Deployment(
            metadata=api.ObjectMeta(name="d1"),
            spec=api.DeploymentSpec(replicas=3, selector=SEL, template=TMPL))
        store.create("deployments", dep)
        dep_ctrl.sync_all()
        rss = store.list("replicasets")
        assert len(rss) == 1 and rss[0].spec.replicas == 3
        rs_ctrl.sync_all()
        assert len(store.list("pods")) == 3

    def test_rolling_update_replaces_rs(self):
        store = ObjectStore()
        dep_ctrl = DeploymentController(store)
        rs_ctrl = ReplicaSetController(store)
        dep = api.Deployment(
            metadata=api.ObjectMeta(name="d1"),
            spec=api.DeploymentSpec(replicas=2, selector=SEL, template=TMPL))
        store.create("deployments", dep)
        for _ in range(4):
            dep_ctrl.sync_all()
            rs_ctrl.sync_all()
            for p in store.list("pods"):
                if p.status.phase != "Running":
                    mark_running(store, p)
            rs_ctrl.sync_all()
        old_rs = store.list("replicasets")[0]
        # change the template -> new hash -> new RS
        import copy
        dep = store.get("deployments", "default", "d1")
        dep.spec.template = copy.deepcopy(TMPL)
        dep.spec.template.spec.containers[0].image = "v2"
        store.update("deployments", dep)
        for _ in range(10):
            dep_ctrl.sync_all()
            rs_ctrl.sync_all()
            for p in store.list("pods"):
                if p.status.phase != "Running":
                    mark_running(store, p)
            rs_ctrl.sync_all()
        rss = {r.metadata.name: r for r in store.list("replicasets")}
        assert len(rss) == 2
        new_rs = next(r for r in rss.values()
                      if r.metadata.name != old_rs.metadata.name)
        assert new_rs.spec.replicas == 2
        assert rss[old_rs.metadata.name].spec.replicas == 0
        dep = store.get("deployments", "default", "d1")
        assert dep.status.updated_replicas == 2


class TestStatefulSet:
    def test_ordered_creation(self):
        store = ObjectStore()
        ctrl = StatefulSetController(store)
        ss = api.StatefulSet(
            metadata=api.ObjectMeta(name="web"),
            spec=api.StatefulSetSpec(replicas=3, selector=SEL, template=TMPL))
        store.create("statefulsets", ss)
        ctrl.sync_all()
        pods = sorted(p.metadata.name for p in store.list("pods"))
        assert pods == ["web-0"]  # waits for readiness before web-1
        mark_running(store, store.get("pods", "default", "web-0"))
        ctrl.sync_all()
        assert sorted(p.metadata.name for p in store.list("pods")) == \
            ["web-0", "web-1"]
        mark_running(store, store.get("pods", "default", "web-1"))
        ctrl.sync_all()
        assert sorted(p.metadata.name for p in store.list("pods")) == \
            ["web-0", "web-1", "web-2"]

    def test_scale_down_from_top(self):
        store = ObjectStore()
        ctrl = StatefulSetController(store)
        ss = api.StatefulSet(
            metadata=api.ObjectMeta(name="web"),
            spec=api.StatefulSetSpec(replicas=2, selector=SEL, template=TMPL,
                                     pod_management_policy="Parallel"))
        store.create("statefulsets", ss)
        ctrl.sync_all()
        assert len(store.list("pods")) == 2
        ss = store.get("statefulsets", "default", "web")
        ss.spec.replicas = 1
        store.update("statefulsets", ss)
        ctrl.sync_all()
        assert [p.metadata.name for p in store.list("pods")] == ["web-0"]


class TestDaemonSet:
    def test_one_pod_per_eligible_node(self):
        store = ObjectStore()
        ctrl = DaemonSetController(store)
        store.create("nodes", mknode("n1"))
        store.create("nodes", mknode("n2"))
        bad = mknode("n3")
        bad.spec.unschedulable = True
        store.create("nodes", bad)
        ds = api.DaemonSet(
            metadata=api.ObjectMeta(name="agent"),
            spec=api.DaemonSetSpec(selector=SEL, template=TMPL))
        store.create("daemonsets", ds)
        ctrl.sync_all()
        pods = store.list("pods")
        assert sorted(p.spec.node_name for p in pods) == ["n1", "n2"]
        ds = store.get("daemonsets", "default", "agent")
        assert ds.status.desired_number_scheduled == 2
        # new node -> new daemon pod
        store.create("nodes", mknode("n4"))
        ctrl.sync_all()
        assert sorted(p.spec.node_name for p in store.list("pods")) == \
            ["n1", "n2", "n4"]


class TestJob:
    def test_run_to_completion(self):
        store = ObjectStore()
        ctrl = JobController(store)
        job = api.Job(metadata=api.ObjectMeta(name="j1"),
                      spec=api.JobSpec(parallelism=2, completions=3,
                                       selector=SEL, template=TMPL))
        store.create("jobs", job)
        ctrl.sync_all()
        pods = store.list("pods")
        assert len(pods) == 2  # parallelism bound
        for p in pods:
            p.status.phase = "Succeeded"
            store.update("pods", p)
        ctrl.sync_all()
        job = store.get("jobs", "default", "j1")
        assert job.status.succeeded == 2
        pods = [p for p in store.list("pods")
                if p.status.phase not in ("Succeeded", "Failed")]
        assert len(pods) == 1  # one remaining completion
        pods[0].status.phase = "Succeeded"
        store.update("pods", pods[0])
        ctrl.sync_all()
        job = store.get("jobs", "default", "j1")
        assert ("Complete", "True") in job.status.conditions

    def test_backoff_limit(self):
        store = ObjectStore()
        ctrl = JobController(store)
        job = api.Job(metadata=api.ObjectMeta(name="j1"),
                      spec=api.JobSpec(parallelism=1, completions=1,
                                       backoff_limit=0, template=TMPL))
        store.create("jobs", job)
        ctrl.sync_all()
        p = store.list("pods")[0]
        p.status.phase = "Failed"
        store.update("pods", p)
        ctrl.sync_all()
        job = store.get("jobs", "default", "j1")
        assert any(c[0] == "Failed" for c in job.status.conditions)


class TestCronJob:
    def test_cron_matching(self):
        # 2026-07-29 is a Wednesday (cron dow 3)
        t = time.mktime((2026, 7, 29, 10, 30, 0, 0, 0, 0)) - time.timezone
        assert cron_matches("* * * * *", t)
        assert cron_matches("30 10 * * *", t)
        assert cron_matches("*/15 * * * *", t)
        assert not cron_matches("31 10 * * *", t)
        assert cron_matches("30 10 29 7 *", t)
        assert cron_matches("* * * * 3", t)
        assert not cron_matches("* * * * 4", t)

    def test_spawns_job_once_per_minute(self):
        store = ObjectStore()
        now = [time.mktime((2026, 7, 29, 10, 30, 0, 0, 0, 0))]
        ctrl = CronJobController(store, clock=lambda: now[0])
        cj = api.CronJob(metadata=api.ObjectMeta(name="cj"),
                         spec=api.CronJobSpec(schedule="* * * * *",
                                              job_template=api.JobSpec(
                                                  template=TMPL)))
        store.create("cronjobs", cj)
        assert ctrl.tick() == 1
        assert ctrl.tick() == 0  # same minute: no duplicate
        now[0] += 60
        assert ctrl.tick() == 1
        assert len(store.list("jobs")) == 2

    def test_forbid_concurrency(self):
        store = ObjectStore()
        now = [time.mktime((2026, 7, 29, 10, 30, 0, 0, 0, 0))]
        ctrl = CronJobController(store, clock=lambda: now[0])
        cj = api.CronJob(metadata=api.ObjectMeta(name="cj"),
                         spec=api.CronJobSpec(schedule="* * * * *",
                                              concurrency_policy="Forbid",
                                              job_template=api.JobSpec(
                                                  template=TMPL)))
        store.create("cronjobs", cj)
        assert ctrl.tick() == 1
        now[0] += 60
        assert ctrl.tick() == 0  # previous job still active


class TestEndpoints:
    def test_ready_split_and_ports(self):
        store = ObjectStore()
        ctrl = EndpointsController(store)
        store.create("services", api.Service(
            metadata=api.ObjectMeta(name="svc"),
            spec=api.ServiceSpec(selector={"app": "w"},
                                 ports=[api.ServicePort(name="http", port=80,
                                                        target_port=8080)])))
        p1 = api.Pod(metadata=api.ObjectMeta(name="p1", labels={"app": "w"}),
                     spec=api.PodSpec(node_name="n1"))
        p2 = api.Pod(metadata=api.ObjectMeta(name="p2", labels={"app": "w"}),
                     spec=api.PodSpec(node_name="n2"))
        store.create("pods", p1)
        store.create("pods", p2)
        mark_running(store, store.get("pods", "default", "p1"), ready=True)
        mark_running(store, store.get("pods", "default", "p2"), ready=False)
        ctrl.sync_all()
        ep = store.get("endpoints", "default", "svc")
        assert len(ep.subsets[0].addresses) == 1
        assert len(ep.subsets[0].not_ready_addresses) == 1
        assert ep.subsets[0].ports[0].port == 8080


class TestNodeLifecycle:
    def test_unreachable_taint_and_eviction(self):
        store = ObjectStore()
        now = [1000.0]
        ctrl = NodeLifecycleController(store, clock=lambda: now[0],
                                       grace_period=40.0)

        def keep_alive(name):
            n = store.get("nodes", "default", name)
            n.metadata.annotations[HEARTBEAT_ANNOTATION] = str(now[0])
            store.update("nodes", n)

        store.create("nodes", mknode("n1", hb=now[0]))
        # a healthy peer in the same failure domain: a zone whose EVERY
        # node stops reporting is FullDisruption and suspends eviction
        # (the storm-control contract, tested in test_partition.py); the
        # toleration-seconds path needs a partially-healthy zone
        store.create("nodes", mknode("n2", hb=now[0]))
        pod = api.Pod(metadata=api.ObjectMeta(name="p1"),
                      spec=api.PodSpec(node_name="n1", tolerations=[
                          api.Toleration(key=TAINT_UNREACHABLE,
                                         operator="Exists",
                                         effect=api.NO_EXECUTE,
                                         toleration_seconds=30)]))
        store.create("pods", pod)
        ctrl.monitor()
        n = store.get("nodes", "default", "n1")
        assert not n.spec.taints  # healthy
        # n1's heartbeats stop; n2 keeps reporting
        now[0] += 100
        keep_alive("n2")
        ctrl.monitor()
        n = store.get("nodes", "default", "n1")
        assert any(c.type == api.NODE_READY and c.status == api.COND_UNKNOWN
                   for c in n.status.conditions)
        assert any(t.key == TAINT_UNREACHABLE for t in n.spec.taints)
        assert store.get("pods", "default", "p1") is not None  # tolerated
        now[0] += 31  # tolerationSeconds expired
        keep_alive("n2")
        ctrl.monitor()
        assert store.get("pods", "default", "p1") is None  # evicted

    def test_recovery_removes_taint(self):
        store = ObjectStore()
        now = [1000.0]
        ctrl = NodeLifecycleController(store, clock=lambda: now[0])
        store.create("nodes", mknode("n1", hb=now[0]))
        now[0] += 100
        ctrl.monitor()
        assert any(t.key == TAINT_UNREACHABLE for t in
                   store.get("nodes", "default", "n1").spec.taints)
        # kubelet comes back: fresh heartbeat + Ready=True
        n = store.get("nodes", "default", "n1")
        n.metadata.annotations[HEARTBEAT_ANNOTATION] = str(now[0])
        n.status.conditions = [api.NodeCondition(api.NODE_READY, api.COND_TRUE)]
        store.update("nodes", n)
        ctrl.monitor()
        assert not store.get("nodes", "default", "n1").spec.taints

    def test_not_ready_taint(self):
        store = ObjectStore()
        now = [1000.0]
        ctrl = NodeLifecycleController(store, clock=lambda: now[0])
        store.create("nodes", mknode("n1", ready=False, hb=now[0]))
        ctrl.monitor()
        taints = store.get("nodes", "default", "n1").spec.taints
        assert [t.key for t in taints] == [TAINT_NOT_READY]

    def test_swap_taints_preserves_other_effects(self):
        """Taints are matched by (key, effect): a user taint sharing the
        not-ready KEY under NoSchedule is neither dropped nor clobbered
        by the controller's NoExecute swap."""
        store = ObjectStore()
        now = [1000.0]
        ctrl = NodeLifecycleController(store, clock=lambda: now[0])
        node = mknode("n1", ready=False, hb=now[0])
        node.spec.taints = [
            api.Taint(key=TAINT_NOT_READY, effect=api.NO_SCHEDULE),
            api.Taint(key="user/custom", effect=api.NO_EXECUTE),
        ]
        store.create("nodes", node)
        ctrl.monitor()
        taints = store.get("nodes", "default", "n1").spec.taints
        assert (TAINT_NOT_READY, api.NO_SCHEDULE) in [
            (t.key, t.effect) for t in taints]
        assert ("user/custom", api.NO_EXECUTE) in [
            (t.key, t.effect) for t in taints]
        assert (TAINT_NOT_READY, api.NO_EXECUTE) in [
            (t.key, t.effect) for t in taints]
        # recovery drops ONLY the controller's NoExecute pair
        n = store.get("nodes", "default", "n1")
        n.metadata.annotations[HEARTBEAT_ANNOTATION] = str(now[0])
        n.status.conditions = [api.NodeCondition(api.NODE_READY,
                                                 api.COND_TRUE)]
        store.update("nodes", n)
        ctrl.monitor()
        taints = store.get("nodes", "default", "n1").spec.taints
        assert sorted((t.key, t.effect) for t in taints) == sorted([
            (TAINT_NOT_READY, api.NO_SCHEDULE),
            ("user/custom", api.NO_EXECUTE)])

    def test_swap_taints_effect_only_change_detected(self):
        """An effect-only difference counts as a change (the old key-only
        compare silently dropped it), and a steady state is idempotent —
        no store write churn from re-ordering."""
        node = mknode("n1")
        node.spec.taints = [
            api.Taint(key=TAINT_NOT_READY, effect=api.NO_SCHEDULE)]
        assert NodeLifecycleController._swap_taints(
            node, add=TAINT_NOT_READY, drop=TAINT_UNREACHABLE)
        assert sorted((t.key, t.effect) for t in node.spec.taints) == sorted([
            (TAINT_NOT_READY, api.NO_SCHEDULE),
            (TAINT_NOT_READY, api.NO_EXECUTE)])
        # second application: no change, regardless of list order
        assert not NodeLifecycleController._swap_taints(
            node, add=TAINT_NOT_READY, drop=TAINT_UNREACHABLE)


class TestDisruption:
    def test_pdb_status(self):
        store = ObjectStore()
        ctrl = DisruptionController(store)
        rs = api.ReplicaSet(
            metadata=api.ObjectMeta(name="rs1"),
            spec=api.ReplicaSetSpec(replicas=3, selector=SEL, template=TMPL))
        store.create("replicasets", rs)
        for i in range(3):
            p = api.Pod(
                metadata=api.ObjectMeta(
                    name=f"p{i}", labels={"app": "w"},
                    owner_references=[api.OwnerReference(
                        kind="ReplicaSet", name="rs1", uid=rs.metadata.uid,
                        controller=True)]),
                spec=api.PodSpec())
            store.create("pods", p)
            mark_running(store, store.get("pods", "default", f"p{i}"),
                         ready=(i < 2))
        store.create("poddisruptionbudgets", api.PodDisruptionBudget(
            metadata=api.ObjectMeta(name="pdb"),
            spec=api.PodDisruptionBudgetSpec(selector=SEL, min_available=1)))
        ctrl.sync_all()
        pdb = store.get("poddisruptionbudgets", "default", "pdb")
        assert pdb.status.expected_pods == 3
        assert pdb.status.current_healthy == 2
        assert pdb.status.desired_healthy == 1
        assert pdb.status.disruptions_allowed == 1


class TestNamespaceAndServiceAccount:
    def test_terminating_namespace_sweeps_content(self):
        store = ObjectStore()
        ctrl = NamespaceController(store)
        ns = api.Namespace(metadata=api.ObjectMeta(name="doomed"))
        store.create("namespaces", ns)
        store.create("pods", api.Pod(metadata=api.ObjectMeta(
            name="p1", namespace="doomed")))
        store.create("services", api.Service(metadata=api.ObjectMeta(
            name="s1", namespace="doomed")))
        ns.status.phase = "Terminating"
        store.update("namespaces", ns)
        ctrl.sync_all()
        assert store.list("pods", "doomed") == []
        assert store.list("services", "doomed") == []
        assert store.get("namespaces", "", "doomed") is None

    def test_default_serviceaccount(self):
        store = ObjectStore()
        ctrl = ServiceAccountController(store)
        store.create("namespaces", api.Namespace(
            metadata=api.ObjectMeta(name="team-a")))
        ctrl.sync_all()
        sa = store.get("serviceaccounts", "team-a", "default")
        assert sa is not None and sa.secrets == ["default-token"]


class TestGC:
    def test_podgc_orphans_and_terminated(self):
        store = ObjectStore()
        ctrl = PodGCController(store, terminated_threshold=1)
        store.create("nodes", mknode("n1"))
        for i, phase in enumerate(["Succeeded", "Failed", "Running"]):
            p = api.Pod(metadata=api.ObjectMeta(name=f"p{i}"),
                        spec=api.PodSpec(node_name="n1"))
            p.status.phase = phase
            store.create("pods", p)
        orphan = api.Pod(metadata=api.ObjectMeta(name="orphan"),
                         spec=api.PodSpec(node_name="gone-node"))
        store.create("pods", orphan)
        deleted = ctrl.gc()
        assert deleted == 2  # 1 excess terminated + 1 orphan
        names = {p.metadata.name for p in store.list("pods")}
        assert "orphan" not in names and "p2" in names

    def test_ownerref_gc(self):
        store = ObjectStore()
        gc = GarbageCollector(store)
        rs = api.ReplicaSet(metadata=api.ObjectMeta(name="rs1"),
                            spec=api.ReplicaSetSpec(selector=SEL))
        store.create("replicasets", rs)
        p = api.Pod(metadata=api.ObjectMeta(
            name="p1", owner_references=[api.OwnerReference(
                kind="ReplicaSet", name="rs1", uid=rs.metadata.uid,
                controller=True)]))
        store.create("pods", p)
        assert gc.sweep() == 0
        store.delete("replicasets", "default", "rs1")
        assert gc.sweep() == 1
        assert store.list("pods") == []


class TestPVBinding:
    def test_binds_smallest_sufficient_pv(self):
        store = ObjectStore()
        ctrl = PersistentVolumeController(store)
        from kubernetes_tpu.api.resources import value as qty
        for name, size in [("pv-big", "100Gi"), ("pv-small", "10Gi")]:
            store.create("persistentvolumes", api.PersistentVolume(
                metadata=api.ObjectMeta(name=name),
                spec=api.PersistentVolumeSpec(
                    capacity={"storage": qty(size)})))
        pvc = api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name="claim"),
            spec=api.PersistentVolumeClaimSpec(
                requests={"storage": qty("5Gi")}))
        store.create("persistentvolumeclaims", pvc)
        ctrl.sync_all()
        pvc = store.get("persistentvolumeclaims", "default", "claim")
        assert pvc.spec.volume_name == "pv-small"


class TestManagerCascade:
    def test_deployment_to_endpoints_cascade(self):
        store = ObjectStore()
        mgr = ControllerManager(store)
        store.create("nodes", mknode("n0"))
        store.create("nodes", mknode("n1"))
        store.create("services", api.Service(
            metadata=api.ObjectMeta(name="svc"),
            spec=api.ServiceSpec(selector={"app": "w"},
                                 ports=[api.ServicePort(port=80)])))
        store.create("deployments", api.Deployment(
            metadata=api.ObjectMeta(name="d1"),
            spec=api.DeploymentSpec(replicas=2, selector=SEL, template=TMPL)))
        mgr.sync_all()
        for i, p in enumerate(store.list("pods")):
            if p.status.phase != "Running":
                store.bind(p, f"n{i}")  # simulate the scheduler
                mark_running(store, store.get("pods", p.metadata.namespace,
                                              p.metadata.name))
        mgr.sync_all()
        assert len(store.list("pods")) == 2
        ep = store.get("endpoints", "default", "svc")
        assert ep is not None and len(ep.subsets[0].addresses) == 2
        # deleting the deployment cascades: RS gone -> pods collected
        store.delete("deployments", "default", "d1")
        mgr.sync_all(rounds=4)
        assert store.list("replicasets") == []
        assert store.list("pods") == []


class TestJobActiveDeadline:
    def test_job_fails_past_deadline(self):
        from kubernetes_tpu.controllers.job import JobController

        store = ObjectStore()
        now = [0.0]
        ctrl = JobController(store, clock=lambda: now[0])
        store.create("jobs", api.Job(
            metadata=api.ObjectMeta(name="slow"),
            spec=api.JobSpec(parallelism=2, completions=4,
                             active_deadline_seconds=60, template=TMPL)))
        ctrl.sync_all()
        assert len(store.list("pods")) == 2
        job = store.get("jobs", "default", "slow")
        assert job.status.start_time == 0.0
        now[0] = 61.0
        # production re-wakes via queue.add_after(real clock); the fake
        # clock test enqueues the wake itself
        ctrl.enqueue(job)
        ctrl.sync_all()
        job = store.get("jobs", "default", "slow")
        assert ("Failed", "True:DeadlineExceeded") in job.status.conditions
        assert job.status.active == 0 and store.list("pods") == []
        # terminal: nothing recreated after
        ctrl.sync_all()
        assert store.list("pods") == []


class TestDaemonSetRollingUpdate:
    def _world(self, strategy="RollingUpdate", max_unavailable=1):
        import copy

        from kubernetes_tpu.controllers.daemonset import DaemonSetController

        store = ObjectStore()
        for n in ("n1", "n2", "n3"):
            store.create("nodes", mknode(n))
        ds = api.DaemonSet(
            metadata=api.ObjectMeta(name="agent"),
            spec=api.DaemonSetSpec(
                selector=SEL, template=copy.deepcopy(TMPL),
                update_strategy=api.DaemonSetUpdateStrategy(
                    type=strategy, max_unavailable=max_unavailable)))
        store.create("daemonsets", ds)
        ctrl = DaemonSetController(store)
        ctrl.sync_all()
        for p in store.list("pods"):
            mark_running(store, p)
        ctrl.sync_all()
        return store, ctrl

    def _retag(self, store, image):
        ds = store.get("daemonsets", "default", "agent")
        ds.spec.template.spec.containers[0].image = image
        store.update("daemonsets", ds)

    def test_rolling_update_respects_max_unavailable(self):
        from kubernetes_tpu.controllers.history import (REV_LABEL,
                                                        revision_data,
                                                        revision_hash)

        store, ctrl = self._world(max_unavailable=1)
        assert len(store.list("pods")) == 3
        self._retag(store, "agent:v2")
        ds = store.get("daemonsets", "default", "agent")
        new_hash = revision_hash(revision_data(ds.spec.template))
        ctrl.sync_all()
        # only ONE ready stale pod was replaced this round
        pods = store.list("pods")
        stale = [p for p in pods
                 if (p.metadata.labels or {}).get(REV_LABEL) != new_hash]
        assert len(stale) == 2, [p.metadata.name for p in pods]
        # as replacements go Ready, the rollout advances to completion
        for _ in range(4):
            for p in store.list("pods"):
                mark_running(store, p)
            ctrl._all_dirty()
            ctrl.sync_all()
        pods = store.list("pods")
        assert len(pods) == 3
        assert all((p.metadata.labels or {}).get(REV_LABEL) == new_hash
                   for p in pods)
        ds = store.get("daemonsets", "default", "agent")
        assert ds.status.updated_number_scheduled == 3

    def test_on_delete_waits_for_manual_deletion(self):
        from kubernetes_tpu.controllers.history import (REV_LABEL,
                                                        revision_data,
                                                        revision_hash)

        store, ctrl = self._world(strategy="OnDelete")
        self._retag(store, "agent:v2")
        ctrl.sync_all()
        ds = store.get("daemonsets", "default", "agent")
        new_hash = revision_hash(revision_data(ds.spec.template))
        stale = [p for p in store.list("pods")
                 if (p.metadata.labels or {}).get(REV_LABEL) != new_hash]
        assert len(stale) == 3  # nothing auto-replaced
        store.delete("pods", "default", stale[0].metadata.name)
        ctrl.sync_all()
        pods = store.list("pods")
        assert len(pods) == 3
        fresh = [p for p in pods
                 if (p.metadata.labels or {}).get(REV_LABEL) == new_hash]
        assert len(fresh) == 1  # only the manually-deleted slot


class TestStatefulSetClaims:
    def test_volume_claim_templates_minted_and_retained(self):
        from kubernetes_tpu.controllers.statefulset import (
            StatefulSetController)

        store = ObjectStore()
        ss = api.StatefulSet(
            metadata=api.ObjectMeta(name="db"),
            spec=api.StatefulSetSpec(
                replicas=2, selector=SEL, template=TMPL,
                pod_management_policy="Parallel",
                volume_claim_templates=[api.PersistentVolumeClaim(
                    metadata=api.ObjectMeta(name="data"),
                    spec=api.PersistentVolumeClaimSpec(
                        requests={"storage": 1 << 30}))]))
        store.create("statefulsets", ss)
        ctrl = StatefulSetController(store)
        ctrl.sync_all()
        pods = sorted(store.list("pods"), key=lambda p: p.metadata.name)
        assert [p.metadata.name for p in pods] == ["db-0", "db-1"]
        for i, p in enumerate(pods):
            assert p.spec.volumes[-1].pvc_name == f"data-db-{i}"
        claims = {c.metadata.name
                  for c in store.list("persistentvolumeclaims")}
        assert claims == {"data-db-0", "data-db-1"}
        # scale down: pod goes, claim STAYS
        ss = store.get("statefulsets", "default", "db")
        ss.spec.replicas = 1
        store.update("statefulsets", ss)
        ctrl.sync_all()
        assert len(store.list("pods")) == 1
        assert {c.metadata.name
                for c in store.list("persistentvolumeclaims")} == claims
        # scale back up: db-1 reattaches to the SAME claim
        ss = store.get("statefulsets", "default", "db")
        ss.spec.replicas = 2
        store.update("statefulsets", ss)
        ctrl.sync_all()
        p1 = store.get("pods", "default", "db-1")
        assert p1.spec.volumes[-1].pvc_name == "data-db-1"
