"""Multi-version API serving + conversion (api/conversion.py).

Reference behavior being reproduced: the same stored object is readable
at every served version, with field-level conversion through the hub
(apimachinery pkg/conversion/converter.go:40; pkg/apis/apps/v1beta1/,
pkg/apis/autoscaling/v1/conversion.go), and CRDs can serve multiple
versions of one schema (apiextensions spec.versions, 1.11)."""

import json

from kubernetes_tpu.api import conversion, scheme
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.rest import APIStatusError, RESTClient
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.server import APIServer


def mkdeploy(name="d"):
    return api.Deployment(
        metadata=api.ObjectMeta(name=name),
        spec=api.DeploymentSpec(
            replicas=2,
            template=api.PodTemplateSpec(
                metadata=api.ObjectMeta(labels={"app": name}),
                spec=api.PodSpec(containers=[api.Container(name="c")]))))


class TestWireConversion:
    def test_served_versions(self):
        assert sorted(scheme.served_versions("Deployment")) == \
            ["apps/v1", "apps/v1beta1", "apps/v1beta2",
             "extensions/v1beta1"]
        assert scheme.serves("HorizontalPodAutoscaler", "autoscaling/v2beta1")
        assert not scheme.serves("Pod", "apps/v1")

    def test_deployment_v1beta1_round_trip(self):
        d = mkdeploy()
        d.metadata.annotations[conversion.ROLLBACK_ANNOTATION] = "3"
        wire = scheme.encode_object(d, version="apps/v1beta1")
        assert wire["apiVersion"] == "apps/v1beta1"
        assert wire["spec"]["rollbackTo"] == {"revision": 3}
        # and back: rollbackTo returns to the annotation, selector
        # defaults from template labels (v1beta1 defaulting)
        wire["spec"].pop("selector", None)
        back = scheme.decode_request("Deployment", wire)
        assert back.metadata.annotations[conversion.ROLLBACK_ANNOTATION] == "3"
        assert back.spec.selector.match_labels == {"app": "d"}

    def test_hpa_v2beta1_metrics_mapping(self):
        hpa = api.HorizontalPodAutoscaler(
            metadata=api.ObjectMeta(name="h"),
            spec=api.HorizontalPodAutoscalerSpec(
                target_cpu_utilization_percentage=70))
        wire = scheme.encode_object(hpa, version="autoscaling/v2beta1")
        assert wire["spec"]["metrics"] == [{
            "type": "Resource",
            "resource": {"name": "cpu", "targetAverageUtilization": 70}}]
        assert "targetCPUUtilizationPercentage" not in wire["spec"]
        back = scheme.decode_request("HorizontalPodAutoscaler", wire)
        assert back.spec.target_cpu_utilization_percentage == 70

    def test_rollback_cleared_by_v1beta1_client(self):
        """A v1beta1 client removing spec.rollbackTo must actually clear
        it — the annotation is popped on the way out so it cannot
        resurrect the field on the next round trip."""
        d = mkdeploy()
        d.metadata.annotations[conversion.ROLLBACK_ANNOTATION] = "5"
        wire = scheme.encode_object(d, version="apps/v1beta1")
        assert wire["spec"]["rollbackTo"] == {"revision": 5}
        assert conversion.ROLLBACK_ANNOTATION not in \
            wire["metadata"].get("annotations", {})
        wire["spec"].pop("rollbackTo")
        back = scheme.decode_request("Deployment", wire)
        assert conversion.ROLLBACK_ANNOTATION not in back.metadata.annotations

    def test_hpa_non_cpu_metrics_preserved(self):
        """Metrics the v1 hub can't express survive round trips through
        the alpha annotation (pkg/apis/autoscaling/v1/conversion.go:37),
        and no fabricated cpu metric appears on the way back out."""
        wire = {
            "kind": "HorizontalPodAutoscaler",
            "apiVersion": "autoscaling/v2beta1",
            "metadata": {"name": "h"},
            "spec": {"maxReplicas": 4, "metrics": [
                {"type": "Resource",
                 "resource": {"name": "memory",
                              "targetAverageUtilization": 60}}]}}
        hub = conversion.to_hub("HorizontalPodAutoscaler", wire,
                                "autoscaling/v2beta1", "autoscaling/v1")
        assert conversion.METRICS_ANNOTATION in hub["metadata"]["annotations"]
        assert "targetCpuUtilizationPercentage" not in hub["spec"]
        back = conversion.from_hub("HorizontalPodAutoscaler", hub,
                                   "autoscaling/v2beta1", "autoscaling/v1")
        mem = [m for m in back["spec"]["metrics"]
               if m["resource"]["name"] == "memory"]
        assert mem and mem[0]["resource"]["targetAverageUtilization"] == 60
        assert conversion.METRICS_ANNOTATION not in \
            back["metadata"]["annotations"]

    def test_hpa_status_current_metrics(self):
        hpa = api.HorizontalPodAutoscaler(
            metadata=api.ObjectMeta(name="h"),
            status=api.HorizontalPodAutoscalerStatus(
                current_cpu_utilization_percentage=42))
        wire = scheme.encode_object(hpa, version="autoscaling/v2beta1")
        assert wire["status"]["currentMetrics"][0]["resource"][
            "currentAverageUtilization"] == 42
        back = scheme.decode_request("HorizontalPodAutoscaler", wire)
        assert back.status.current_cpu_utilization_percentage == 42

    def test_tag_only_version(self):
        cj = api.CronJob(metadata=api.ObjectMeta(name="c"))
        wire = scheme.encode_object(cj, version="batch/v2alpha1")
        assert wire["apiVersion"] == "batch/v2alpha1"
        assert scheme.decode_request(
            "CronJob", wire).metadata.name == "c"


class TestServedThroughAPIServer:
    def setup_method(self):
        self.store = ObjectStore()
        self.srv = APIServer(self.store).start()
        self.client = RESTClient(self.srv.url)

    def teardown_method(self):
        self.srv.stop()

    def _get(self, path):
        body, _ = self.client.request_bytes("GET", path)
        return json.loads(body)

    def test_create_old_version_read_both(self):
        """A client posts apps/v1beta1 (no selector, rollbackTo set);
        another reads apps/v1 and sees the converted hub object."""
        body = {
            "kind": "Deployment",
            "metadata": {"name": "web"},
            "spec": {"replicas": 2,
                     "rollbackTo": {"revision": 5},
                     "template": {"metadata": {"labels": {"app": "web"}},
                                  "spec": {"containers": [{"name": "c"}]}}}}
        resp, _ = self.client.request_bytes(
            "POST", "/apis/apps/v1beta1/namespaces/default/deployments",
            body=body)
        created = json.loads(resp)
        assert created["apiVersion"] == "apps/v1beta1"
        assert created["spec"]["rollbackTo"] == {"revision": 5}

        at_v1 = self._get("/apis/apps/v1/namespaces/default/deployments/web")
        assert at_v1["apiVersion"] == "apps/v1"
        assert "rollbackTo" not in at_v1["spec"]
        assert at_v1["metadata"]["annotations"][
            conversion.ROLLBACK_ANNOTATION] == "5"
        assert at_v1["spec"]["selector"]["matchLabels"] == {"app": "web"}

        back = self._get(
            "/apis/apps/v1beta1/namespaces/default/deployments/web")
        assert back["apiVersion"] == "apps/v1beta1"
        assert back["spec"]["rollbackTo"] == {"revision": 5}

    def test_stored_hub_object_served_converted(self):
        """An object stored at the hub version is served converted at the
        old version — the API-evolution contract."""
        self.store.create("horizontalpodautoscalers", api.HorizontalPodAutoscaler(
            metadata=api.ObjectMeta(name="h"),
            spec=api.HorizontalPodAutoscalerSpec(
                target_cpu_utilization_percentage=55)))
        old = self._get("/apis/autoscaling/v2beta1/namespaces/default/"
                        "horizontalpodautoscalers/h")
        assert old["spec"]["metrics"][0]["resource"][
            "targetAverageUtilization"] == 55
        lst = self._get(
            "/apis/autoscaling/v2beta1/namespaces/default/"
            "horizontalpodautoscalers")
        assert lst["apiVersion"] == "autoscaling/v2beta1"
        assert lst["items"][0]["spec"]["metrics"]

    def test_unserved_version_404(self):
        try:
            self._get("/apis/apps/v9/namespaces/default/deployments")
            raise AssertionError("expected 404")
        except APIStatusError as e:
            assert e.code == 404

    def test_discovery_lists_both_versions(self):
        v1 = self._get("/apis/apps/v1")
        v1b1 = self._get("/apis/apps/v1beta1")
        names = {r["name"] for r in v1b1["resources"]}
        assert "deployments" in names
        assert {r["name"] for r in v1["resources"]} >= names
        groups = self._get("/apis")["groups"]
        assert "autoscaling" in groups

    def test_crd_multi_version(self):
        crd = api.CustomResourceDefinition(
            metadata=api.ObjectMeta(name="widgets.example.io", namespace=""),
            spec=api.CustomResourceDefinitionSpec(
                group="example.io", version="v1",
                versions=["v1", "v1alpha1"],
                names=api.CustomResourceNames(kind="Widget",
                                              plural="widgets")))
        self.client.create("customresourcedefinitions", crd)
        resp, _ = self.client.request_bytes(
            "POST", "/apis/example.io/v1alpha1/namespaces/default/widgets",
            body={"kind": "Widget", "metadata": {"name": "w1"},
                  "spec": {"size": 3}})
        created = json.loads(resp)
        assert created["apiVersion"] == "example.io/v1alpha1"
        stored = self._get(
            "/apis/example.io/v1/namespaces/default/widgets/w1")
        assert stored["apiVersion"] == "example.io/v1"
        assert stored["spec"]["size"] == 3
        old = self._get(
            "/apis/example.io/v1alpha1/namespaces/default/widgets/w1")
        assert old["apiVersion"] == "example.io/v1alpha1"
        # cleanup: unregister the dynamic kind for other tests
        self.client.delete("customresourcedefinitions", "",
                           "widgets.example.io")


class TestLegacyWorkloadGroupVersions:
    """The 1.11 reference serves workloads at apps/v1beta2 and
    extensions/v1beta1 simultaneously (pkg/master/master.go InstallAPIs,
    pkg/apis/extensions) — round-trip + serving checks for the added
    group-versions."""

    def _server(self):
        from kubernetes_tpu.runtime.store import ObjectStore
        from kubernetes_tpu.server import APIServer

        return APIServer(ObjectStore()).start()

    def test_extensions_deployment_round_trip(self):
        srv = self._server()
        try:
            from kubernetes_tpu.client.rest import RESTClient

            c = RESTClient(srv.url)
            # create at extensions/v1beta1 with NO selector: legacy
            # defaulting fills it from template labels
            doc = {"apiVersion": "extensions/v1beta1", "kind": "Deployment",
                   "metadata": {"name": "web", "namespace": "default"},
                   "spec": {"replicas": 2, "template": {"metadata": {
                       "labels": {"app": "web"}}}}}
            c.request("POST",
                      "/apis/extensions/v1beta1/namespaces/default"
                      "/deployments", body=doc)
            # hub read sees the defaulted selector
            hub = c.request("GET", "/apis/apps/v1/namespaces/default"
                                   "/deployments/web")
            assert hub["spec"]["selector"]["matchLabels"] == {"app": "web"}
            # extensions read keeps the extensions tag
            ext = c.request("GET",
                            "/apis/extensions/v1beta1/namespaces/default"
                            "/deployments/web")
            assert ext["apiVersion"] == "extensions/v1beta1"
        finally:
            srv.stop()

    def test_v1beta2_replicaset_and_daemonset_served(self):
        srv = self._server()
        try:
            from kubernetes_tpu.client.rest import RESTClient

            c = RESTClient(srv.url)
            for gv in ("apps/v1beta2", "extensions/v1beta1"):
                doc = c.request("GET", f"/apis/{gv}")
                names = {r["name"] for r in doc["resources"]}
                assert {"deployments", "replicasets",
                        "daemonsets"} <= names, (gv, names)
            rs = {"apiVersion": "apps/v1beta2", "kind": "ReplicaSet",
                  "metadata": {"name": "rs1", "namespace": "default"},
                  "spec": {"replicas": 1,
                           "selector": {"matchLabels": {"a": "b"}},
                           "template": {"metadata": {
                               "labels": {"a": "b"}}}}}
            created = c.request(
                "POST",
                "/apis/apps/v1beta2/namespaces/default/replicasets",
                body=rs)
            assert created["apiVersion"] == "apps/v1beta2"
        finally:
            srv.stop()

    def test_statefulset_v1beta1_selector_defaulting(self):
        from kubernetes_tpu.api import conversion

        doc = {"apiVersion": "apps/v1beta1", "kind": "StatefulSet",
               "metadata": {"name": "db"},
               "spec": {"template": {"metadata": {
                   "labels": {"db": "x"}}}}}
        hub = conversion.to_hub("StatefulSet", doc, "apps/v1beta1",
                                "apps/v1")
        assert hub["spec"]["selector"]["matchLabels"] == {"db": "x"}

    def test_explicit_empty_selector_not_defaulted(self):
        # nil-ONLY defaulting (SetDefaults_ReplicaSet): an explicit {}
        # selector is a valid match-everything selector in the legacy
        # versions and must NOT be overwritten by template labels
        from kubernetes_tpu.api import conversion

        doc = {"apiVersion": "extensions/v1beta1", "kind": "ReplicaSet",
               "metadata": {"name": "all"},
               "spec": {"selector": {},
                        "template": {"metadata": {
                            "labels": {"app": "web"}}}}}
        hub = conversion.to_hub("ReplicaSet", doc, "extensions/v1beta1",
                                "apps/v1")
        assert hub["spec"]["selector"] == {}
