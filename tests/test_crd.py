"""CRD-lite: dynamic resource registration.

Reference: staging/src/k8s.io/apiextensions-apiserver — creating a
CustomResourceDefinition makes the apiserver serve the named kind;
kubectl discovers CRDs; controllers reconcile custom resources.
"""

import time

import pytest

from kubernetes_tpu.api import scheme
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.rest import APIStatusError, RESTClient
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.server.admission import AdmissionChain
from kubernetes_tpu.server.apiserver import APIServer


def widget_crd():
    return api.CustomResourceDefinition(
        metadata=api.ObjectMeta(name="widgets.example.com"),
        spec=api.CustomResourceDefinitionSpec(
            group="example.com", version="v1",
            names=api.CustomResourceNames(kind="Widget", plural="widgets",
                                          singular="widget")))


def widget(name, replicas=1):
    return api.CustomObject(
        kind="Widget", api_version="example.com/v1",
        metadata=api.ObjectMeta(name=name),
        spec={"replicas": replicas, "color": "blue"})


@pytest.fixture()
def clean_scheme():
    yield
    scheme.unregister("Widget")


@pytest.fixture()
def server(clean_scheme):
    srv = APIServer(ObjectStore(), admission=AdmissionChain()).start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(server):
    return RESTClient(server.url)


class TestDynamicRegistration:
    def test_crd_roundtrip_over_http(self, server, client):
        # before registration the custom path does not exist
        with pytest.raises(APIStatusError) as ei:
            client.list("widgets")
        assert ei.value.code == 404
        client.create("customresourcedefinitions", widget_crd())
        # CRUD on the custom kind
        client.create("widgets", widget("w1", replicas=3))
        got = client.get("widgets", "default", "w1")
        assert got.kind == "Widget"
        assert got.spec["replicas"] == 3 and got.spec["color"] == "blue"
        got.spec["replicas"] = 5
        client.update("widgets", got)
        items, _ = client.list("widgets")
        assert len(items) == 1 and items[0].spec["replicas"] == 5
        client.delete("widgets", "default", "w1")
        items, _ = client.list("widgets")
        assert items == []

    def test_crd_delete_unserves_the_kind(self, server, client):
        client.create("customresourcedefinitions", widget_crd())
        client.create("widgets", widget("w1"))
        client.delete("customresourcedefinitions", None,
                      "widgets.example.com")
        with pytest.raises(APIStatusError) as ei:
            client.list("widgets")
        assert ei.value.code == 404

    def test_crd_cannot_hijack_builtin_kind(self, server, client):
        """A CRD naming itself 'Pod'/'pods' must be rejected — otherwise
        it would overwrite the built-in registration and, on deletion,
        unregister pods server-wide."""
        bad = api.CustomResourceDefinition(
            metadata=api.ObjectMeta(name="pods.example.com"),
            spec=api.CustomResourceDefinitionSpec(
                group="example.com", version="v1",
                names=api.CustomResourceNames(kind="Pod", plural="pods")))
        with pytest.raises(APIStatusError) as ei:
            client.create("customresourcedefinitions", bad)
        assert ei.value.code == 409
        # built-in still served
        items, _ = client.list("pods")
        assert items == []

    def test_rejected_rename_keeps_old_kind_served(self, server, client):
        """A rename that fails validation (e.g. to a built-in name) must
        leave the original registration fully intact."""
        client.create("customresourcedefinitions", widget_crd())
        client.create("widgets", widget("w1"))
        crd = client.get("customresourcedefinitions", None,
                         "widgets.example.com")
        crd.spec.names = api.CustomResourceNames(kind="Pod", plural="pods")
        with pytest.raises(APIStatusError) as ei:
            client.update("customresourcedefinitions", crd)
        assert ei.value.code == 409
        items, _ = client.list("widgets")  # still served
        assert len(items) == 1

    def test_plural_rename_drops_stale_route(self, server, client):
        """Renaming only the plural must retire the old URL — a stale
        _BY_PLURAL entry would 500 after the CRD is later deleted."""
        client.create("customresourcedefinitions", widget_crd())
        crd = client.get("customresourcedefinitions", None,
                         "widgets.example.com")
        crd.spec.names = api.CustomResourceNames(
            kind="Widget", plural="doodads", singular="doodad")
        client.update("customresourcedefinitions", crd)
        with pytest.raises(APIStatusError) as ei:
            client.list("widgets")
        assert ei.value.code == 404
        items, _ = client.list("doodads")
        assert isinstance(items, list)
        client.delete("customresourcedefinitions", None,
                      "widgets.example.com")
        with pytest.raises(APIStatusError) as ei:
            client.list("widgets")  # must 404, not 500
        assert ei.value.code == 404

    def test_crd_rename_drops_old_registration(self, server, client):
        client.create("customresourcedefinitions", widget_crd())
        client.create("widgets", widget("w1"))
        crd = client.get("customresourcedefinitions", None,
                         "widgets.example.com")
        crd.spec.names = api.CustomResourceNames(
            kind="Gadget", plural="gadgets", singular="gadget")
        client.update("customresourcedefinitions", crd)
        try:
            with pytest.raises(APIStatusError) as ei:
                client.list("widgets")
            assert ei.value.code == 404
            items, _ = client.list("gadgets")
            assert isinstance(items, list)
        finally:
            scheme.unregister("Gadget")

    def test_preexisting_crds_registered_at_startup(self, clean_scheme):
        """Durable-store restart: CRDs already in the store serve
        immediately (the informer's initial list registers them)."""
        store = ObjectStore()
        store.create("customresourcedefinitions", widget_crd())
        scheme.unregister("Widget")  # simulate a fresh process
        srv = APIServer(store, admission=AdmissionChain()).start()
        try:
            client = RESTClient(srv.url)
            client.create("widgets", widget("w1"))
            assert client.get("widgets", "default", "w1") is not None
        finally:
            srv.stop()


class TestKubectlCRD:
    def test_kubectl_apply_and_get_custom_resource(self, server, client,
                                                   tmp_path):
        import io

        from kubernetes_tpu.cli import kubectl

        manifest = tmp_path / "widget.yaml"
        manifest.write_text("""\
kind: CustomResourceDefinition
apiVersion: apiextensions.k8s.io/v1beta1
metadata:
  name: widgets.example.com
spec:
  group: example.com
  version: v1
  names:
    kind: Widget
    plural: widgets
    singular: widget
---
kind: Widget
apiVersion: example.com/v1
metadata:
  name: from-yaml
spec:
  replicas: 2
""")
        out = io.StringIO()
        rc = kubectl.main(["--server", server.url, "apply", "-f",
                           str(manifest)], out=out)
        assert rc == 0, out.getvalue()
        assert "widgets/from-yaml created" in out.getvalue()
        out = io.StringIO()
        rc = kubectl.main(["--server", server.url, "get", "widgets"],
                          out=out)
        assert rc == 0
        assert "from-yaml" in out.getvalue()


class WidgetController(Controller):
    """Proof that the controller machinery runs unchanged against a
    custom resource: reconciles Widget.spec.replicas into pods (the
    operator pattern the reference enables via CRDs + client-go)."""

    name = "widget"

    def __init__(self, store):
        super().__init__(store)
        self.informer("widgets")
        self.informer("pods", enqueue_fn=self._pod_owner)

    def _pod_owner(self, pod, new=None):
        pod = new if new is not None else pod
        for ref in pod.metadata.owner_references:
            if ref.kind == "Widget":
                self.enqueue(f"{pod.namespace}/{ref.name}")

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        w = self.store.get("widgets", ns, name)
        if w is None:
            return
        want = int(w.spec.get("replicas", 1))
        owned = [p for p in self.store.list("pods", ns)
                 if any(r.kind == "Widget" and r.name == name
                        for r in p.metadata.owner_references)]
        for i in range(len(owned), want):
            self.store.create("pods", api.Pod(
                metadata=api.ObjectMeta(
                    name=f"{name}-{i}", namespace=ns,
                    owner_references=[api.OwnerReference(
                        kind="Widget", name=name, uid=w.metadata.uid,
                        controller=True)]),
                spec=api.PodSpec(containers=[api.Container()])))
        for p in owned[want:]:
            self.store.delete("pods", ns, p.metadata.name)
        w.status["readyReplicas"] = min(want, len(owned))
        self.store.update("widgets", w)


class TestCustomResourceController:
    def test_widget_controller_reconciles(self, clean_scheme):
        store = ObjectStore()
        scheme.register_dynamic(widget_crd())
        ctrl = WidgetController(store)
        store.create("widgets", widget("w1", replicas=3))
        ctrl.sync_all()
        assert len(store.list("pods")) == 3
        w = store.get("widgets", "default", "w1")
        w.spec["replicas"] = 1
        store.update("widgets", w)
        ctrl.sync_all()
        assert len(store.list("pods")) == 1


def schema_crd():
    """Widget CRD with an openAPIV3Schema + status/scale subresources."""
    return api.CustomResourceDefinition(
        metadata=api.ObjectMeta(name="widgets.example.com"),
        spec=api.CustomResourceDefinitionSpec(
            group="example.com", version="v1",
            names=api.CustomResourceNames(kind="Widget", plural="widgets",
                                          singular="widget"),
            validation=api.CustomResourceValidation(
                open_api_v3_schema={
                    "type": "object",
                    "properties": {
                        "spec": {
                            "type": "object",
                            "required": ["replicas"],
                            "properties": {
                                "replicas": {"type": "integer",
                                             "minimum": 0,
                                             "maximum": 100},
                                "color": {"type": "string",
                                          "enum": ["blue", "red"]},
                                "host": {"type": "string",
                                         "pattern": "^[a-z0-9.-]+$"},
                            },
                        },
                    },
                }),
            subresources=api.CustomResourceSubresources(
                status=True,
                scale=api.CustomResourceSubresourceScale(
                    spec_replicas_path=".spec.replicas",
                    status_replicas_path=".status.readyReplicas"))))


class TestCRDValidation:
    def test_schema_enforced_on_create_and_update(self, server, client):
        client.create("customresourcedefinitions", schema_crd())
        # missing required spec.replicas
        bad = api.CustomObject(kind="Widget", api_version="example.com/v1",
                               metadata=api.ObjectMeta(name="w"),
                               spec={"color": "blue"})
        with pytest.raises(APIStatusError) as ei:
            client.create("widgets", bad)
        assert ei.value.code == 422 and "spec.replicas" in ei.value.message
        # wrong enum member + out-of-range + bad pattern, all reported
        bad2 = api.CustomObject(kind="Widget", api_version="example.com/v1",
                                metadata=api.ObjectMeta(name="w"),
                                spec={"replicas": 500, "color": "green",
                                      "host": "NOT VALID"})
        with pytest.raises(APIStatusError) as ei:
            client.create("widgets", bad2)
        msg = ei.value.message
        assert "must be <= 100" in msg and "must be one of" in msg \
            and "pattern" in msg
        # valid object passes
        client.create("widgets", widget("w", replicas=3))
        got = client.get("widgets", "default", "w")
        got.spec["replicas"] = -1
        with pytest.raises(APIStatusError) as ei:
            client.update("widgets", got)
        assert ei.value.code == 422

    def test_type_errors(self, server, client):
        client.create("customresourcedefinitions", schema_crd())
        bad = api.CustomObject(kind="Widget", api_version="example.com/v1",
                               metadata=api.ObjectMeta(name="w"),
                               spec={"replicas": "three"})
        with pytest.raises(APIStatusError) as ei:
            client.create("widgets", bad)
        assert "must be of type integer" in ei.value.message


class TestCRDSubresources:
    def test_status_isolation(self, server, client):
        client.create("customresourcedefinitions", schema_crd())
        w = widget("w", replicas=3)
        w.status = {"readyReplicas": 99}  # client status dropped at create
        client.create("widgets", w)
        got = client.get("widgets", "default", "w")
        assert got.status == {}
        # status write never touches spec
        got.status = {"readyReplicas": 2}
        got.spec["replicas"] = 50  # smuggled spec change
        client.update_status("widgets", got)
        got = client.get("widgets", "default", "w")
        assert got.status == {"readyReplicas": 2}
        assert got.spec["replicas"] == 3
        # spec write never touches status
        got.spec["replicas"] = 5
        got.status = {}  # smuggled status wipe
        client.update("widgets", got)
        got = client.get("widgets", "default", "w")
        assert got.spec["replicas"] == 5
        assert got.status == {"readyReplicas": 2}

    def test_status_404_without_optin(self, server, client):
        client.create("customresourcedefinitions", widget_crd())
        client.create("widgets", widget("w"))
        got = client.get("widgets", "default", "w")
        got.status = {"readyReplicas": 1}
        with pytest.raises(APIStatusError) as ei:
            client.update_status("widgets", got)
        assert ei.value.code == 404

    def test_scale_subresource(self, server, client):
        client.create("customresourcedefinitions", schema_crd())
        client.create("widgets", widget("w", replicas=3))
        got = client.get("widgets", "default", "w")
        got.status = {"readyReplicas": 2}
        client.update_status("widgets", got)
        sc = client.get_scale("widgets", "default", "w")
        assert sc["kind"] == "Scale"
        assert sc["spec"]["replicas"] == 3
        assert sc["status"]["replicas"] == 2
        client.update_scale("widgets", "default", "w", 7)
        assert client.get("widgets", "default", "w").spec["replicas"] == 7

    def test_scale_404_without_optin(self, server, client):
        client.create("customresourcedefinitions", widget_crd())
        client.create("widgets", widget("w"))
        with pytest.raises(APIStatusError) as ei:
            client.get_scale("widgets", "default", "w")
        assert ei.value.code == 404


class TestScaleRespectsRules:
    def test_scale_cannot_bypass_schema(self, server, client):
        client.create("customresourcedefinitions", schema_crd())
        client.create("widgets", widget("w", replicas=3))
        # schema caps replicas at 100: the scale path must honor it
        with pytest.raises(APIStatusError) as ei:
            client.update_scale("widgets", "default", "w", 500)
        assert ei.value.code == 422
        # rejected write left the store untouched
        assert client.get("widgets", "default", "w").spec["replicas"] == 3

    def test_rejected_scale_leaves_store_untouched(self, clean_scheme):
        from kubernetes_tpu.api.labels import LabelSelector
        from kubernetes_tpu.server.admission import (AdmissionChain,
                                                     AdmissionError,
                                                     AdmissionPlugin)

        class DenyScale(AdmissionPlugin):
            name = "DenyScale"

            def admit(self, op, kind, obj, old, user, store):
                if op == "update" and kind == "deployments":
                    raise AdmissionError("no scaling today")

        store = ObjectStore()
        srv = APIServer(store,
                        admission=AdmissionChain([DenyScale()])).start()
        try:
            client = RESTClient(srv.url)
            dep = api.Deployment(
                metadata=api.ObjectMeta(name="web"),
                spec=api.DeploymentSpec(
                    replicas=3,
                    selector=LabelSelector(match_labels={"app": "web"}),
                    template=api.PodTemplateSpec(
                        metadata=api.ObjectMeta(labels={"app": "web"}),
                        spec=api.PodSpec(containers=[api.Container()]))))
            store.create("deployments", dep)
            with pytest.raises(APIStatusError) as ei:
                client.update_scale("deployments", "default", "web", 99)
            assert ei.value.code == 403
            assert store.get("deployments", "default",
                             "web").spec.replicas == 3
        finally:
            srv.stop()

    def test_status_subresource_validated(self, server, client):
        crd = schema_crd()
        crd.spec.validation.open_api_v3_schema["properties"]["status"] = {
            "type": "object",
            "properties": {"readyReplicas": {"type": "integer"}}}
        client.create("customresourcedefinitions", crd)
        client.create("widgets", widget("w", replicas=1))
        got = client.get("widgets", "default", "w")
        got.status = {"readyReplicas": "lots"}
        with pytest.raises(APIStatusError) as ei:
            client.update_status("widgets", got)
        assert ei.value.code == 422


class TestCreateStatusDrop:
    def test_discarded_status_cannot_fail_create(self, server, client):
        crd = schema_crd()
        crd.spec.validation.open_api_v3_schema["properties"]["status"] = {
            "type": "object",
            "properties": {"readyReplicas": {"type": "integer"}}}
        client.create("customresourcedefinitions", crd)
        w = widget("w", replicas=1)
        # ill-typed status (e.g. replayed from another cluster's get):
        # the status subresource drops it at create, so it must not 422
        w.status = {"readyReplicas": "lots"}
        client.create("widgets", w)
        assert client.get("widgets", "default", "w").status == {}

    def test_bad_schema_pattern_rejected_at_crd_create(self, server,
                                                       client):
        crd = widget_crd()
        crd.spec.validation = api.CustomResourceValidation(
            open_api_v3_schema={
                "type": "object",
                "properties": {"spec": {
                    "type": "object",
                    "properties": {"color": {"type": "string",
                                             "pattern": "["}}}}})
        # the schema author gets the 422, at registration time —
        # resource authors are never collateral damage
        with pytest.raises(APIStatusError) as ei:
            client.create("customresourcedefinitions", crd)
        assert ei.value.code == 422
        assert "invalid regular expression" in ei.value.message
        # a schema that bypassed create-time checks (direct store
        # write) still degrades to a field 422 on writes, never a 500
        from kubernetes_tpu.api.crdschema import validate_schema
        errs = validate_schema({"spec": {"color": "x"}},
                               crd.spec.validation.open_api_v3_schema)
        assert any("not a valid regular expression" in m
                   for _p, m in errs)

    def test_bad_scale_paths_rejected_at_crd_create(self, server, client):
        """Scale subresource paths outside .spec/.status would make
        every /scale write a silent no-op (dotted_set grafts into a dead
        branch); the CRD author gets a 422 at registration instead."""
        crd = widget_crd()
        crd.spec.subresources = api.CustomResourceSubresources(
            scale=api.CustomResourceSubresourceScale(
                spec_replicas_path=".data.replicas",
                status_replicas_path=".status.readyReplicas"))
        with pytest.raises(APIStatusError) as ei:
            client.create("customresourcedefinitions", crd)
        assert ei.value.code == 422
        assert "specReplicasPath" in ei.value.message
        crd2 = widget_crd()
        crd2.spec.subresources = api.CustomResourceSubresources(
            scale=api.CustomResourceSubresourceScale(
                spec_replicas_path=".spec.replicas",
                status_replicas_path="replicas"))
        with pytest.raises(APIStatusError) as ei:
            client.create("customresourcedefinitions", crd2)
        assert ei.value.code == 422
        assert "statusReplicasPath" in ei.value.message
        # an UPDATE must not smuggle the broken path in either
        good = widget_crd()
        good.spec.subresources = api.CustomResourceSubresources(
            scale=api.CustomResourceSubresourceScale(
                spec_replicas_path=".spec.replicas",
                status_replicas_path=".status.readyReplicas"))
        client.create("customresourcedefinitions", good)
        stored = client.get("customresourcedefinitions", "",
                            "widgets.example.com")
        stored.spec.subresources.scale.spec_replicas_path = ".meta.n"
        with pytest.raises(APIStatusError) as ei:
            client.update("customresourcedefinitions", stored)
        assert ei.value.code == 422
