"""CRD-lite: dynamic resource registration.

Reference: staging/src/k8s.io/apiextensions-apiserver — creating a
CustomResourceDefinition makes the apiserver serve the named kind;
kubectl discovers CRDs; controllers reconcile custom resources.
"""

import time

import pytest

from kubernetes_tpu.api import scheme
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.rest import APIStatusError, RESTClient
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.server.admission import AdmissionChain
from kubernetes_tpu.server.apiserver import APIServer


def widget_crd():
    return api.CustomResourceDefinition(
        metadata=api.ObjectMeta(name="widgets.example.com"),
        spec=api.CustomResourceDefinitionSpec(
            group="example.com", version="v1",
            names=api.CustomResourceNames(kind="Widget", plural="widgets",
                                          singular="widget")))


def widget(name, replicas=1):
    return api.CustomObject(
        kind="Widget", api_version="example.com/v1",
        metadata=api.ObjectMeta(name=name),
        spec={"replicas": replicas, "color": "blue"})


@pytest.fixture()
def clean_scheme():
    yield
    scheme.unregister("Widget")


@pytest.fixture()
def server(clean_scheme):
    srv = APIServer(ObjectStore(), admission=AdmissionChain()).start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(server):
    return RESTClient(server.url)


class TestDynamicRegistration:
    def test_crd_roundtrip_over_http(self, server, client):
        # before registration the custom path does not exist
        with pytest.raises(APIStatusError) as ei:
            client.list("widgets")
        assert ei.value.code == 404
        client.create("customresourcedefinitions", widget_crd())
        # CRUD on the custom kind
        client.create("widgets", widget("w1", replicas=3))
        got = client.get("widgets", "default", "w1")
        assert got.kind == "Widget"
        assert got.spec["replicas"] == 3 and got.spec["color"] == "blue"
        got.spec["replicas"] = 5
        client.update("widgets", got)
        items, _ = client.list("widgets")
        assert len(items) == 1 and items[0].spec["replicas"] == 5
        client.delete("widgets", "default", "w1")
        items, _ = client.list("widgets")
        assert items == []

    def test_crd_delete_unserves_the_kind(self, server, client):
        client.create("customresourcedefinitions", widget_crd())
        client.create("widgets", widget("w1"))
        client.delete("customresourcedefinitions", None,
                      "widgets.example.com")
        with pytest.raises(APIStatusError) as ei:
            client.list("widgets")
        assert ei.value.code == 404

    def test_crd_cannot_hijack_builtin_kind(self, server, client):
        """A CRD naming itself 'Pod'/'pods' must be rejected — otherwise
        it would overwrite the built-in registration and, on deletion,
        unregister pods server-wide."""
        bad = api.CustomResourceDefinition(
            metadata=api.ObjectMeta(name="pods.example.com"),
            spec=api.CustomResourceDefinitionSpec(
                group="example.com", version="v1",
                names=api.CustomResourceNames(kind="Pod", plural="pods")))
        with pytest.raises(APIStatusError) as ei:
            client.create("customresourcedefinitions", bad)
        assert ei.value.code == 409
        # built-in still served
        items, _ = client.list("pods")
        assert items == []

    def test_rejected_rename_keeps_old_kind_served(self, server, client):
        """A rename that fails validation (e.g. to a built-in name) must
        leave the original registration fully intact."""
        client.create("customresourcedefinitions", widget_crd())
        client.create("widgets", widget("w1"))
        crd = client.get("customresourcedefinitions", None,
                         "widgets.example.com")
        crd.spec.names = api.CustomResourceNames(kind="Pod", plural="pods")
        with pytest.raises(APIStatusError) as ei:
            client.update("customresourcedefinitions", crd)
        assert ei.value.code == 409
        items, _ = client.list("widgets")  # still served
        assert len(items) == 1

    def test_plural_rename_drops_stale_route(self, server, client):
        """Renaming only the plural must retire the old URL — a stale
        _BY_PLURAL entry would 500 after the CRD is later deleted."""
        client.create("customresourcedefinitions", widget_crd())
        crd = client.get("customresourcedefinitions", None,
                         "widgets.example.com")
        crd.spec.names = api.CustomResourceNames(
            kind="Widget", plural="doodads", singular="doodad")
        client.update("customresourcedefinitions", crd)
        with pytest.raises(APIStatusError) as ei:
            client.list("widgets")
        assert ei.value.code == 404
        items, _ = client.list("doodads")
        assert isinstance(items, list)
        client.delete("customresourcedefinitions", None,
                      "widgets.example.com")
        with pytest.raises(APIStatusError) as ei:
            client.list("widgets")  # must 404, not 500
        assert ei.value.code == 404

    def test_crd_rename_drops_old_registration(self, server, client):
        client.create("customresourcedefinitions", widget_crd())
        client.create("widgets", widget("w1"))
        crd = client.get("customresourcedefinitions", None,
                         "widgets.example.com")
        crd.spec.names = api.CustomResourceNames(
            kind="Gadget", plural="gadgets", singular="gadget")
        client.update("customresourcedefinitions", crd)
        try:
            with pytest.raises(APIStatusError) as ei:
                client.list("widgets")
            assert ei.value.code == 404
            items, _ = client.list("gadgets")
            assert isinstance(items, list)
        finally:
            scheme.unregister("Gadget")

    def test_preexisting_crds_registered_at_startup(self, clean_scheme):
        """Durable-store restart: CRDs already in the store serve
        immediately (the informer's initial list registers them)."""
        store = ObjectStore()
        store.create("customresourcedefinitions", widget_crd())
        scheme.unregister("Widget")  # simulate a fresh process
        srv = APIServer(store, admission=AdmissionChain()).start()
        try:
            client = RESTClient(srv.url)
            client.create("widgets", widget("w1"))
            assert client.get("widgets", "default", "w1") is not None
        finally:
            srv.stop()


class TestKubectlCRD:
    def test_kubectl_apply_and_get_custom_resource(self, server, client,
                                                   tmp_path):
        import io

        from kubernetes_tpu.cli import kubectl

        manifest = tmp_path / "widget.yaml"
        manifest.write_text("""\
kind: CustomResourceDefinition
apiVersion: apiextensions.k8s.io/v1beta1
metadata:
  name: widgets.example.com
spec:
  group: example.com
  version: v1
  names:
    kind: Widget
    plural: widgets
    singular: widget
---
kind: Widget
apiVersion: example.com/v1
metadata:
  name: from-yaml
spec:
  replicas: 2
""")
        out = io.StringIO()
        rc = kubectl.main(["--server", server.url, "apply", "-f",
                           str(manifest)], out=out)
        assert rc == 0, out.getvalue()
        assert "widgets/from-yaml created" in out.getvalue()
        out = io.StringIO()
        rc = kubectl.main(["--server", server.url, "get", "widgets"],
                          out=out)
        assert rc == 0
        assert "from-yaml" in out.getvalue()


class WidgetController(Controller):
    """Proof that the controller machinery runs unchanged against a
    custom resource: reconciles Widget.spec.replicas into pods (the
    operator pattern the reference enables via CRDs + client-go)."""

    name = "widget"

    def __init__(self, store):
        super().__init__(store)
        self.informer("widgets")
        self.informer("pods", enqueue_fn=self._pod_owner)

    def _pod_owner(self, pod, new=None):
        pod = new if new is not None else pod
        for ref in pod.metadata.owner_references:
            if ref.kind == "Widget":
                self.enqueue(f"{pod.namespace}/{ref.name}")

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        w = self.store.get("widgets", ns, name)
        if w is None:
            return
        want = int(w.spec.get("replicas", 1))
        owned = [p for p in self.store.list("pods", ns)
                 if any(r.kind == "Widget" and r.name == name
                        for r in p.metadata.owner_references)]
        for i in range(len(owned), want):
            self.store.create("pods", api.Pod(
                metadata=api.ObjectMeta(
                    name=f"{name}-{i}", namespace=ns,
                    owner_references=[api.OwnerReference(
                        kind="Widget", name=name, uid=w.metadata.uid,
                        controller=True)]),
                spec=api.PodSpec(containers=[api.Container()])))
        for p in owned[want:]:
            self.store.delete("pods", ns, p.metadata.name)
        w.status["readyReplicas"] = min(want, len(owned))
        self.store.update("widgets", w)


class TestCustomResourceController:
    def test_widget_controller_reconciles(self, clean_scheme):
        store = ObjectStore()
        scheme.register_dynamic(widget_crd())
        ctrl = WidgetController(store)
        store.create("widgets", widget("w1", replicas=3))
        ctrl.sync_all()
        assert len(store.list("pods")) == 3
        w = store.get("widgets", "default", "w1")
        w.spec["replicas"] = 1
        store.update("widgets", w)
        ctrl.sync_all()
        assert len(store.list("pods")) == 1
