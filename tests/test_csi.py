"""CSI: the out-of-process volume driver seam, end to end.

Reference: pkg/volume/csi/csi_plugin.go:45 (the in-tree shim),
external-provisioner/external-attacher sidecars. Round-4 verdict item
5's 'done' bar: a pod using a CSI-provisioned volume schedules,
attaches, mounts (table-level), tears down — with every step crossing
the wire protocol to the driver, which here is either an in-process
HTTP server (unit flow) or a genuinely separate OS process
(test_out_of_process_driver)."""

import subprocess
import sys
import time

from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers.attachdetach import AttachDetachController
from kubernetes_tpu.controllers.volumebinding import \
    PersistentVolumeController
from kubernetes_tpu.kubelet import Kubelet
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.volume import csi

from helpers import make_node


def _claimed_pod(name, pvc):
    return api.Pod(
        metadata=api.ObjectMeta(name=name),
        spec=api.PodSpec(
            containers=[api.Container(resources=api.ResourceRequirements(
                requests=api.resource_list(cpu="100m", memory="64Mi")))],
            volumes=[api.Volume(name="data", pvc_name=pvc)]))


def _annotated_pvc(name, driver, storage="1Gi"):
    return api.PersistentVolumeClaim(
        metadata=api.ObjectMeta(
            name=name,
            annotations={csi.PROVISIONER_ANNOTATION: driver}),
        spec=api.PersistentVolumeClaimSpec(
            requests=api.resource_list(storage=storage)))


class TestCSILifecycle:
    def setup_method(self):
        self.store = ObjectStore()
        self.driver = csi.MockCSIDriver()
        self.server = csi.CSIDriverServer(self.driver).start()
        csi.register_driver(self.store, self.driver.name, self.server.url)
        self.store.create("nodes", make_node("n1", cpu="4"))
        self.prov = csi.CSIProvisioner(self.store, self.driver.name)
        self.pvctrl = PersistentVolumeController(self.store)
        self.adctrl = AttachDetachController(self.store)

    def teardown_method(self):
        self.server.stop()

    def _settle(self, rounds=3):
        for _ in range(rounds):
            self.prov.sync()
            self.pvctrl.sync_all()
            self.adctrl.sync_all()

    def test_provision_schedule_attach_mount_teardown(self):
        # 1. dynamic provisioning: annotated claim -> CreateVolume -> PV
        self.store.create("persistentvolumeclaims",
                          _annotated_pvc("data-claim", self.driver.name))
        self._settle()
        pvc = self.store.get("persistentvolumeclaims", "default",
                             "data-claim")
        assert pvc.spec.volume_name, "claim never bound to provisioned PV"
        pv = self.store.get("persistentvolumes", "", pvc.spec.volume_name)
        assert pv.spec.source_kind == "CSI"
        assert pv.spec.source_id in self.driver.volumes  # driver made it

        # 2. the pod schedules (bound claim passes CheckVolumeBinding)
        sched = Scheduler(self.store)
        self.store.create("pods", _claimed_pod("app", "data-claim"))
        assert sched.schedule_pending() == 1
        pod = self.store.get("pods", "default", "app")
        assert pod.spec.node_name == "n1"

        # 3. attach: the controller calls ControllerPublishVolume BEFORE
        # recording the attachment
        self._settle()
        assert self.driver.published[pv.spec.source_id] == "n1"
        node = self.store.get("nodes", "default", "n1")
        assert pv.metadata.name in node.status.volumes_attached

        # 4. mount: the kubelet volume manager gates on the attachment,
        # then NodePublishVolume materializes the mount
        kl = Kubelet(self.store, "n1")
        kl.sync_once()
        assert self.store.get("pods", "default", "app").status.phase == \
            "Running"
        m = kl.volume_manager.mount.get(pod.metadata.uid, "data")
        assert m is not None and m.kind == "kubernetes.io/csi"
        assert m.payload["csi/device"] == f"/dev/csi/{pv.spec.source_id}"
        assert (pv.spec.source_id,
                f"{pod.metadata.uid}/data") in self.driver.node_published

        # 5. teardown: pod deleted -> NodeUnpublish (kubelet) ->
        # ControllerUnpublish (controller) -> claim deleted ->
        # DeleteVolume (provisioner reclaim)
        self.store.delete("pods", "default", "app")
        kl.sync_once()
        kl.volume_manager.reconcile(node)
        assert kl.volume_manager.mount.get(pod.metadata.uid, "data") is None
        assert not self.driver.node_published
        self._settle()
        assert pv.spec.source_id not in self.driver.published
        node = self.store.get("nodes", "default", "n1")
        assert pv.metadata.name not in node.status.volumes_attached
        self.store.delete("persistentvolumeclaims", "default", "data-claim")
        self._settle()
        assert pv.spec.source_id not in self.driver.volumes
        assert self.store.get("persistentvolumes", "",
                              pv.metadata.name) is None
        sched.close()

    def test_multi_attach_guard_spans_the_driver(self):
        """The driver itself also refuses double-publish — the control
        plane's RWO guard and the driver's are independent defenses."""
        self.store.create("persistentvolumeclaims",
                          _annotated_pvc("c2", self.driver.name))
        self._settle()
        pvc = self.store.get("persistentvolumeclaims", "default", "c2")
        pv = self.store.get("persistentvolumes", "", pvc.spec.volume_name)
        att = csi.CSIPlugin(self.store).new_attacher()
        from kubernetes_tpu.volume.plugin import Spec

        att.attach(Spec(pv=pv), "n1")
        try:
            att.attach(Spec(pv=pv), "n2")
            raise AssertionError("double publish was accepted")
        except csi.CSIError:
            pass

    def test_unregistered_driver_blocks_attach_not_control_plane(self):
        """A PV naming an unregistered driver: the controller keeps the
        volume unattached (and retries) without recording a lie in
        node.status."""
        self.store.create("persistentvolumes", api.PersistentVolume(
            metadata=api.ObjectMeta(name="ghost-pv", namespace=""),
            spec=api.PersistentVolumeSpec(
                source_kind="CSI", source_id="vol-x",
                csi_driver="ghost.csi.example",
                capacity=api.resource_list(storage="1Gi"))))
        self.store.create("persistentvolumeclaims",
                          api.PersistentVolumeClaim(
                              metadata=api.ObjectMeta(name="ghost-claim"),
                              spec=api.PersistentVolumeClaimSpec(
                                  requests=api.resource_list(storage="1Gi"))))
        self._settle()
        self.store.create("pods", _claimed_pod("ghost-pod", "ghost-claim"))
        sched = Scheduler(self.store)
        sched.schedule_pending()
        self._settle()
        node = self.store.get("nodes", "default", "n1")
        assert "ghost-pv" not in node.status.volumes_attached
        sched.close()


class TestOutOfProcessDriver:
    def test_subprocess_driver_serves_the_full_flow(self):
        """The driver runs as a REAL separate OS process
        (python -m kubernetes_tpu.volume.csi) — nothing shared but the
        wire protocol."""
        proc = subprocess.Popen(
            [sys.executable, "-m", "kubernetes_tpu.volume.csi"],
            stdout=subprocess.PIPE, text=True, cwd="/root/repo")
        try:
            url = proc.stdout.readline().strip()
            assert url.startswith("http://"), url
            store = ObjectStore()
            csi.register_driver(store, "mock.csi.k8s.io", url)
            client = csi._client_for(store, "mock.csi.k8s.io")
            ident = client.call("GET", "/identity")
            assert ident["name"] == "mock.csi.k8s.io"
            store.create("nodes", make_node("n1", cpu="2"))
            store.create("persistentvolumeclaims",
                         _annotated_pvc("sub-claim", "mock.csi.k8s.io"))
            prov = csi.CSIProvisioner(store, "mock.csi.k8s.io")
            pvctrl = PersistentVolumeController(store)
            adctrl = AttachDetachController(store)
            for _ in range(3):
                prov.sync()
                pvctrl.sync_all()
                adctrl.sync_all()
            pvc = store.get("persistentvolumeclaims", "default",
                            "sub-claim")
            assert pvc.spec.volume_name
            store.create("pods", _claimed_pod("sub-app", "sub-claim"))
            sched = Scheduler(store)
            assert sched.schedule_pending() == 1
            for _ in range(3):
                adctrl.sync_all()
            kl = Kubelet(store, "n1")
            kl.sync_once()
            pod = store.get("pods", "default", "sub-app")
            assert pod.status.phase == "Running"
            m = kl.volume_manager.mount.get(pod.metadata.uid, "data")
            assert m is not None and m.payload["csi/driver"] == \
                "mock.csi.k8s.io"
            sched.close()
        finally:
            proc.terminate()
            proc.wait(timeout=10)


class TestCSIFailureModes:
    def setup_method(self):
        self.store = ObjectStore()
        self.driver = csi.MockCSIDriver()
        self.server = csi.CSIDriverServer(self.driver).start()
        csi.register_driver(self.store, self.driver.name, self.server.url)
        self.store.create("nodes", make_node("n1", cpu="4"))

    def teardown_method(self):
        self.server.stop()

    def test_provisioner_double_sync_does_not_reclaim_unbound_pv(self):
        """Provision, then sync AGAIN before the binder runs: the PV
        (and its backing volume) must survive — reclaiming a
        pending-bind PV would flip-flop provision/destroy."""
        self.store.create("persistentvolumeclaims",
                          _annotated_pvc("slow-claim", self.driver.name))
        prov = csi.CSIProvisioner(self.store, self.driver.name)
        prov.sync()
        pvc = self.store.get("persistentvolumeclaims", "default",
                             "slow-claim")
        pv_name = f"pvc-{pvc.metadata.uid}"
        assert self.store.get("persistentvolumes", "", pv_name) is not None
        prov.sync()  # binder has NOT run: volume_name still empty
        prov.sync()
        pv = self.store.get("persistentvolumes", "", pv_name)
        assert pv is not None, "pending-bind PV was reclaimed"
        assert pv.spec.source_id in self.driver.volumes

    def test_driver_outage_does_not_wedge_kubelet(self):
        """NodePublish failing (driver down) keeps the pod gated and the
        sync loop alive; the mount lands once the driver returns."""
        self.store.create("persistentvolumeclaims",
                          _annotated_pvc("c3", self.driver.name))
        prov = csi.CSIProvisioner(self.store, self.driver.name)
        pvctrl = PersistentVolumeController(self.store)
        adctrl = AttachDetachController(self.store)
        for _ in range(2):
            prov.sync()
            pvctrl.sync_all()
        self.store.create("pods", _claimed_pod("app3", "c3"))
        sched = Scheduler(self.store)
        assert sched.schedule_pending() == 1
        adctrl.sync_all()
        # driver dies BEFORE the kubelet mounts
        self.server.stop()
        kl = Kubelet(self.store, "n1")
        kl.sync_once()  # must not raise; pod stays gated
        pod = self.store.get("pods", "default", "app3")
        assert pod.status.phase != "Running"
        # driver returns at the SAME registered endpoint
        self.server = csi.CSIDriverServer(self.driver,
                                          port=self.server.port).start()
        kl.sync_once()
        kl.sync_once()
        assert self.store.get("pods", "default",
                              "app3").status.phase == "Running"
        sched.close()
