"""Delta (per-row) snapshot uploads: randomized bind/evict/heartbeat
churn must leave the delta-updated device mirror bit-for-bit identical
to a from-scratch upload of the same snapshot (the scrubber's
golden-row trick applied to the transport layer: the host arrays ARE
the truth, the device cache must always equal them), including the
grow/realloc path that invalidates every dirty range — and the whole
point, a >=10x cut in steady-state upload bytes per round on a
trickle-style workload, measured via snapshot_upload_bytes_total.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.state.snapshot import Snapshot

from helpers import make_node, make_pod
from test_parity import build, random_world

GROUPS = ("res", "topo", "pods", "terms")


def _device_groups(snap, mesh=None):
    """Upload (delta or full, whatever the dirt dictates) and fetch the
    cached device groups back as host arrays."""
    snap.to_device(mesh=mesh)
    return {g: [np.asarray(a) for a in snap._device_cache[g]]
            for g in GROUPS}


def _assert_matches_fresh(snap, mesh=None):
    """The golden comparison: the delta-maintained device cache vs a
    from-scratch to_device() of the SAME snapshot (cache cleared ->
    whole-group re-upload of the live host arrays)."""
    got = _device_groups(snap, mesh=mesh)
    snap._device_cache.clear()
    want = _device_groups(snap, mesh=mesh)
    for g in GROUPS:
        assert len(got[g]) == len(want[g])
        for i, (a, b) in enumerate(zip(got[g], want[g])):
            np.testing.assert_array_equal(
                a, b, err_msg=f"group {g} array {i} diverged after delta "
                              f"upload")


def _churn(rng, cache, snap, nodes, n_ops=40):
    """One randomized churn burst: binds (new pods, some with
    anti-affinity terms so the term table churns too), evictions, and
    node heartbeats (topology refreshes)."""
    from kubernetes_tpu.api import labels as lbl

    bound = [uid for uid in snap.pod_slot]
    seq = rng.randrange(10**6)
    for _ in range(n_ops):
        op = rng.random()
        if op < 0.5:  # bind
            seq += 1
            node = rng.choice(nodes).metadata.name
            aff = None
            labels = {"app": rng.choice(["web", "db"])}
            if rng.random() < 0.3:
                labels["anti"] = f"g{rng.randrange(3)}"
                aff = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
                    required=[api.PodAffinityTerm(
                        label_selector=lbl.LabelSelector(
                            match_labels={"anti": labels["anti"]}),
                        topology_key="kubernetes.io/hostname")]))
            p = make_pod(f"churn-{seq}", cpu="100m", memory="64Mi",
                         labels=labels, node_name=node, affinity=aff)
            cache.add_pod(p)
            snap.refresh_node_resources(cache.node_infos[node])
            snap.add_pod(p)
            bound.append(p.uid)
        elif op < 0.8 and bound:  # evict
            uid = bound.pop(rng.randrange(len(bound)))
            slot = snap.pod_slot.get(uid)
            if slot is None:
                continue
            node_idx = int(snap.ep_node[slot])
            snap.remove_pod_by_uid(uid)
            name = snap.node_names[node_idx]
            ni = cache.node_infos.get(name)
            if ni is not None:
                ni.pods = [q for q in ni.pods if q.uid != uid]
                snap.refresh_node_resources(ni)
        else:  # heartbeat / node refresh
            node = rng.choice(nodes)
            snap.set_node(cache.node_infos[node.metadata.name])


@pytest.mark.parametrize("seed", range(4))
def test_randomized_churn_bitwise_parity(seed):
    rng = random.Random(seed)
    nodes, existing, _ = random_world(rng, n_nodes=20, n_existing=24)
    cache, snap = build(nodes, existing)
    snap.to_device()  # warm full upload
    for _ in range(5):
        _churn(rng, cache, snap, nodes)
        _assert_matches_fresh(snap)


def test_delta_path_actually_engages():
    """The parity test is vacuous if every round takes the full-upload
    fallback: a small churn against a warm cache must move FEWER bytes
    than the resident footprint, and must not mark any group bytes as
    re-uploaded wholesale."""
    rng = random.Random(7)
    nodes, existing, _ = random_world(rng, n_nodes=24, n_existing=30)
    cache, snap = build(nodes, existing)
    snap.to_device()
    full = sum(snap._group_bytes.values())
    # one bind: touches one res row + one pods row
    node = nodes[0].metadata.name
    p = make_pod("delta-probe", cpu="100m", node_name=node)
    cache.add_pod(p)
    snap.refresh_node_resources(cache.node_infos[node])
    snap.add_pod(p)
    before = snap.upload_bytes_total
    snap.to_device()
    moved = snap.upload_bytes_total - before
    assert 0 < moved < full // 4, (moved, full)
    _assert_matches_fresh(snap)


@pytest.mark.parametrize("grow_dim", ["node", "label"])
def test_grow_realloc_invalidates_dirty_ranges(grow_dim):
    """Growth reallocates the host arrays: every pending dirty row range
    refers to the OLD shapes and must be discarded for a whole-group
    upload — a stale range applied to reallocated arrays would silently
    corrupt rows."""
    rng = random.Random(11)
    nodes, existing, _ = random_world(rng, n_nodes=12, n_existing=16)
    cache, snap = build(nodes, existing)
    snap.to_device()
    # dirty some rows, then grow BEFORE uploading them
    _churn(rng, cache, snap, nodes, n_ops=10)
    pre = {g: set(s) for g, s in snap._dirty_rows.items()}
    assert any(pre.values())
    if grow_dim == "node":
        extra = [make_node(f"grown-{i}", cpu="8",
                           labels={"kubernetes.io/hostname": f"grown-{i}"})
                 for i in range(snap.caps.N - len(snap.node_names) + 1)]
    else:
        extra = [make_node("fat-label", cpu="8",
                           labels={f"grow-key-{i}": "v"
                                   for i in range(snap.caps.K + 1)})]
    for n in extra:
        cache.add_node(n)
        snap.set_node(cache.node_infos[n.name])
    assert snap.dirty_topology  # growth forces whole-group flags
    # every PRE-grow dirty row was discarded at realloc (only the
    # growth-triggering nodes' own fresh rows may be marked now)
    for g in GROUPS:
        assert not (snap._dirty_rows[g] & pre[g]), (g, snap._dirty_rows[g])
    _assert_matches_fresh(snap)


def test_churn_parity_under_mesh():
    """Delta scatters against a node-sharded device cache (GSPMD
    partitions the row scatter) stay bit-for-bit with the from-scratch
    sharded upload."""
    from kubernetes_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    rng = random.Random(3)
    nodes, existing, _ = random_world(rng, n_nodes=20, n_existing=24)
    cache, snap = build(nodes, existing)
    snap.to_device(mesh=mesh)
    for _ in range(3):
        _churn(rng, cache, snap, nodes)
        _assert_matches_fresh(snap, mesh=mesh)


def test_mode_switch_invalidates_cache():
    """to_device(mesh=...) after to_device() (and back) must re-commit
    the groups, not serve arrays with the wrong sharding."""
    from kubernetes_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    rng = random.Random(5)
    nodes, existing, _ = random_world(rng, n_nodes=16, n_existing=8)
    cache, snap = build(nodes, existing)
    nt_single, _, _ = snap.to_device()
    nt_mesh, _, _ = snap.to_device(mesh=mesh)
    assert len(nt_mesh.valid.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(nt_mesh.valid),
                                  np.asarray(nt_single.valid))
    nt_back, _, _ = snap.to_device()
    assert len(nt_back.valid.sharding.device_set) == 1


def test_reform_invalidates_delta_tracking():
    """Mesh reform (parallel/mesh.py reform_mesh) regression: a NEW mesh
    object must drop the whole device cache — pending dirty rows were
    tracked against the OLD sharding and applying them as a delta
    scatter against re-committed arrays would be wrong. The reformed
    upload is FULL (bytes == resident footprint), delta tracking resets,
    and the re-committed groups match a from-scratch sharded upload
    bit-for-bit; subsequent churn deltas engage again."""
    from kubernetes_tpu.parallel.mesh import make_mesh, reform_mesh

    mesh = make_mesh(8)
    rng = random.Random(17)
    nodes, existing, _ = random_world(rng, n_nodes=20, n_existing=24)
    cache, snap = build(nodes, existing)
    snap.to_device(mesh=mesh)
    # dirty some rows against the 8-way sharding, then reform to 4
    _churn(rng, cache, snap, nodes, n_ops=12)
    assert any(snap._dirty_rows.values())
    small = reform_mesh(list(mesh.devices.flat),
                        exclude={str(mesh.devices.flat[3])})
    assert small.devices.size == 4
    before = snap.upload_bytes_total
    nt, _, _ = snap.to_device(mesh=small)
    # full re-upload to the new sharding, delta tracking reset
    assert snap.upload_bytes_total - before >= sum(
        snap._group_bytes.values())
    assert not any(snap._dirty_rows.values())
    assert len(nt.valid.sharding.device_set) == 4
    _assert_matches_fresh(snap, mesh=small)
    # churn against the reformed mesh: deltas engage and stay bitwise
    _churn(rng, cache, snap, nodes, n_ops=12)
    _assert_matches_fresh(snap, mesh=small)
    # healing back upward re-commits again, same contract
    _churn(rng, cache, snap, nodes, n_ops=6)
    _assert_matches_fresh(snap, mesh=mesh)


def test_trickle_upload_bytes_cut_10x():
    """The acceptance gate: steady-state upload bytes per trickle round
    are >=10x below the whole-mirror re-upload the pre-delta scheduler
    paid, measured via the scheduler's snapshot_upload_bytes_total."""
    from kubernetes_tpu.ops.encoding import Caps
    from kubernetes_tpu.runtime.store import ObjectStore
    from kubernetes_tpu.sched.scheduler import Scheduler
    from kubernetes_tpu.state.vocab import bucket_size

    store = ObjectStore()
    caps = Caps(M=bucket_size(1024), P=32, LV=bucket_size(256 + 256, 64))
    sched = Scheduler(store, wave_size=32, caps=caps)
    for i in range(256):
        store.create("nodes", make_node(
            f"node-{i}", cpu="16", memory="32Gi",
            labels={api.LABEL_ZONE: f"zone-{i % 3}",
                    "kubernetes.io/hostname": f"node-{i}"}))
    # fill pass: places one wave, warms the device cache
    for i in range(32):
        store.create("pods", make_pod(f"fill-{i}", cpu="100m",
                                      memory="128Mi", owner_uid="rc-fill"))
    assert sched.schedule_pending() == 32
    full = sum(sched.snapshot._group_bytes.values())
    assert full > 0
    # steady state: 16-pod chunks, each drained before the next lands
    per_round = []
    for r in range(6):
        for i in range(16):
            store.create("pods", make_pod(f"t{r}-{i}", cpu="100m",
                                          memory="128Mi",
                                          owner_uid="rc-trickle"))
        before = sched.metrics.snapshot_upload_bytes.value
        assert sched.schedule_pending() == 16
        per_round.append(sched.metrics.snapshot_upload_bytes.value - before)
    # skip the first steady round (residual dirt from the fill pass)
    steady = per_round[1:]
    assert all(b > 0 for b in steady), steady  # rounds DID upload deltas
    worst = max(steady)
    assert worst * 10 <= full, (per_round, full)
    sched.close()
