"""Equivalence cache tests (core/equivalence_cache.go semantics): class
derivation from owner refs, hit/miss accounting through the scheduler's
host-plugin path, and event-driven invalidation."""

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched.equivalence import (EquivalenceCache,
                                              equivalence_class)
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.utils.feature_gates import FeatureGates


def owned_pod(name, rs_name="rs1", uid="u1", volume=None):
    vols = [volume] if volume else []
    return api.Pod(
        metadata=api.ObjectMeta(
            name=name, labels={"app": "w"},
            owner_references=[api.OwnerReference(
                kind="ReplicaSet", name=rs_name, uid=uid, controller=True)]),
        spec=api.PodSpec(volumes=vols, containers=[api.Container(
            resources=api.ResourceRequirements(
                requests=api.resource_list(cpu="100m", memory="64Mi")))]))


def mknode(name):
    return api.Node(
        metadata=api.ObjectMeta(name=name, labels={api.LABEL_HOSTNAME: name}),
        status=api.NodeStatus(
            allocatable=api.resource_list(cpu="8", memory="16Gi", pods=110),
            conditions=[api.NodeCondition(api.NODE_READY, api.COND_TRUE)]))


class TestClass:
    def test_same_controller_same_class(self):
        a = equivalence_class(owned_pod("a"))
        b = equivalence_class(owned_pod("b"))
        assert a == b and a is not None

    def test_different_controller_differs(self):
        a = equivalence_class(owned_pod("a", rs_name="rs1"))
        b = equivalence_class(owned_pod("b", rs_name="rs2", uid="u2"))
        assert a != b

    def test_no_controller_no_class(self):
        p = api.Pod(metadata=api.ObjectMeta(name="solo"))
        assert equivalence_class(p) is None

    def test_differing_spec_splits_class(self):
        """Same controller ref but different scheduling-relevant spec
        (e.g. volumes) must NOT share cached predicate results — the
        reference's equivalencePod hashes the spec fields, not just the
        owner (round-1 advisor finding)."""
        plain = owned_pod("a")
        with_vol = owned_pod("b", volume=api.Volume(
            name="d", source_kind="GCEPersistentDisk", source_id="disk-1"))
        assert equivalence_class(plain) != equivalence_class(with_vol)
        # differing host ports split too (PodFitsHostPorts is cached)
        ported = owned_pod("c")
        ported.spec.containers[0].ports = [
            api.ContainerPort(container_port=80, host_port=80)]
        assert equivalence_class(plain) != equivalence_class(ported)
        # labels split (CheckServiceAffinity reads them)
        relabeled = owned_pod("d")
        relabeled.metadata.labels = {"app": "other"}
        assert equivalence_class(plain) != equivalence_class(relabeled)


class TestCacheMechanics:
    def test_lookup_update_invalidate(self):
        ec = EquivalenceCache()
        ec.update(1, "n1", "NoDiskConflict", True, ())
        assert ec.lookup(1, "n1", "NoDiskConflict") == (True, ())
        assert ec.hits == 1
        assert ec.lookup(2, "n1", "NoDiskConflict") is None
        ec.on_node_event("n1")
        assert ec.lookup(1, "n1", "NoDiskConflict") is None

    def test_targeted_invalidation(self):
        ec = EquivalenceCache()
        ec.update(1, "n1", "NoDiskConflict", True, ())
        ec.update(1, "n1", "NoVolumeZoneConflict", True, ())
        ec.update(1, "n2", "NoDiskConflict", False, ("x",))
        ec.on_assigned_pod_event("n1")  # pod-derived preds on n1 only
        assert ec.lookup(1, "n1", "NoDiskConflict") is None
        assert ec.lookup(1, "n1", "NoVolumeZoneConflict") == (True, ())
        assert ec.lookup(1, "n2", "NoDiskConflict") == (False, ("x",))
        ec.on_volume_event()  # volume-derived everywhere
        assert ec.lookup(1, "n1", "NoVolumeZoneConflict") is None


class TestSchedulerIntegration:
    def make(self):
        store = ObjectStore()
        for i in range(4):
            store.create("nodes", mknode(f"n{i}"))
        features = FeatureGates({"EnableEquivalenceClassCache": True})
        return store, Scheduler(store, wave_size=16, features=features)

    def test_siblings_hit_cache(self):
        store, sched = self.make()
        vol = api.Volume(name="d", source_kind="GCEPersistentDisk",
                         source_id="pd-1")
        # NoDiskConflict is `relevant` only for pods with special volumes,
        # so give every sibling the (read-only-ish) volume marker
        for i in range(6):
            store.create("pods", owned_pod(f"p{i}", volume=vol))
        placed = 0
        for _ in range(10):
            placed += sched.run_once()
            if placed >= 6:
                break
        sched.wait_for_binds()
        assert placed >= 1  # disk conflicts limit placement to one node...
        assert sched.ecache.hits > 0
        assert sched.ecache.misses > 0

    def test_gate_off_no_cache(self):
        store = ObjectStore()
        sched = Scheduler(store, wave_size=4)
        assert sched.ecache is None

    def test_node_event_invalidates(self):
        store, sched = self.make()
        sched.ecache.update(1, "n1", "NoDiskConflict", True, ())
        node = store.get("nodes", "default", "n1")
        store.update("nodes", node)  # node event -> invalidate n1
        assert sched.ecache.lookup(1, "n1", "NoDiskConflict") is None
