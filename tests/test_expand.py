"""Volume expansion: PVC resize admission + expand controller +
node-side filesystem-resize completion.

Reference test model: pkg/controller/volume/expand tests +
plugin/pkg/admission/storage/persistentvolume/resize/admission_test.go.
"""

from kubernetes_tpu.api import resources as res
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers.expand import (FS_RESIZE_PENDING,
                                               ExpandController)
from kubernetes_tpu.kubelet.kubelet import Kubelet
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.server.admission import (AdmissionChain,
                                             AdmissionError)


def world(expandable=True):
    store = ObjectStore()
    store.create("storageclasses", api.StorageClass(
        metadata=api.ObjectMeta(name="fast", namespace=""),
        provisioner="kubernetes.io/fake",
        allow_volume_expansion=expandable))
    store.create("persistentvolumes", api.PersistentVolume(
        metadata=api.ObjectMeta(name="pv1", namespace=""),
        spec=api.PersistentVolumeSpec(
            capacity={res.STORAGE: 10 << 30})))
    store.create("persistentvolumeclaims", api.PersistentVolumeClaim(
        metadata=api.ObjectMeta(name="data"),
        spec=api.PersistentVolumeClaimSpec(
            storage_class_name="fast", volume_name="pv1",
            requests={res.STORAGE: 10 << 30})))
    return store, ExpandController(store)


class TestResizeAdmission:
    def _admit(self, store, new, old):
        AdmissionChain.default().admit(
            "update", "persistentvolumeclaims", new, old, None, store)

    def test_shrink_always_rejected(self):
        store, _ = world()
        old = store.get("persistentvolumeclaims", "default", "data")
        import copy
        new = copy.deepcopy(old)
        new.spec.requests[res.STORAGE] = 5 << 30
        try:
            self._admit(store, new, old)
            assert False, "shrink admitted"
        except AdmissionError as e:
            assert "shrunk" in str(e)

    def test_grow_requires_expandable_class(self):
        store, _ = world(expandable=False)
        old = store.get("persistentvolumeclaims", "default", "data")
        import copy
        new = copy.deepcopy(old)
        new.spec.requests[res.STORAGE] = 20 << 30
        try:
            self._admit(store, new, old)
            assert False, "grow admitted without allowVolumeExpansion"
        except AdmissionError as e:
            assert "allowVolumeExpansion" in str(e)
        # with expansion allowed, the same grow passes
        store2, _ = world(expandable=True)
        old2 = store2.get("persistentvolumeclaims", "default", "data")
        new2 = copy.deepcopy(old2)
        new2.spec.requests[res.STORAGE] = 20 << 30
        self._admit(store2, new2, old2)


class TestExpandController:
    def test_offline_expand_completes_immediately(self):
        store, ctrl = world()
        ctrl.sync_all()  # records granted capacity
        pvc = store.get("persistentvolumeclaims", "default", "data")
        assert pvc.status.capacity[res.STORAGE] == 10 << 30
        pvc.spec.requests[res.STORAGE] = 20 << 30
        store.update("persistentvolumeclaims", pvc)
        ctrl.sync_all()
        pv = store.get("persistentvolumes", "", "pv1")
        assert pv.spec.capacity[res.STORAGE] == 20 << 30
        pvc = store.get("persistentvolumeclaims", "default", "data")
        assert pvc.status.capacity[res.STORAGE] == 20 << 30
        assert pvc.status.conditions == []

    def test_online_expand_waits_for_kubelet(self):
        store, ctrl = world()
        ctrl.sync_all()
        kl = Kubelet(store, "n1", heartbeat_period=0.0)
        pod = api.Pod(
            metadata=api.ObjectMeta(name="db", uid="u-db"),
            spec=api.PodSpec(node_name="n1", containers=[
                api.Container(name="c")],
                volumes=[api.Volume(name="data", pvc_name="data")]))
        store.create("pods", pod)
        kl.sync_once(1.0)
        pvc = store.get("persistentvolumeclaims", "default", "data")
        pvc.spec.requests[res.STORAGE] = 20 << 30
        store.update("persistentvolumeclaims", pvc)
        ctrl.sync_all()
        pvc = store.get("persistentvolumeclaims", "default", "data")
        # controller half done: PV grown, fs resize owed to the node
        assert store.get("persistentvolumes", "", "pv1") \
            .spec.capacity[res.STORAGE] == 20 << 30
        assert any(c[0] == FS_RESIZE_PENDING
                   for c in pvc.status.conditions)
        assert pvc.status.capacity[res.STORAGE] == 10 << 30
        # the claim's kubelet finishes the resize in housekeeping
        kl.sync_once(2.0)
        pvc = store.get("persistentvolumeclaims", "default", "data")
        assert pvc.status.capacity[res.STORAGE] == 20 << 30
        assert pvc.status.conditions == []

    def test_replace_wiped_status_does_not_fake_completion(self):
        """A full PUT (kubectl replace) arrives with empty status; the
        controller must re-baseline from the PV's real capacity and run
        the expansion, not stamp the grown request as already granted."""
        store, ctrl = world()
        ctrl.sync_all()
        pvc = store.get("persistentvolumeclaims", "default", "data")
        # simulate replace: grown spec + wiped status in one write
        pvc.spec.requests[res.STORAGE] = 20 << 30
        pvc.status = api.PersistentVolumeClaimStatus()
        store.update("persistentvolumeclaims", pvc)
        ctrl.sync_all()
        pv = store.get("persistentvolumes", "", "pv1")
        assert pv.spec.capacity[res.STORAGE] == 20 << 30  # really grown
        pvc = store.get("persistentvolumeclaims", "default", "data")
        assert pvc.status.capacity[res.STORAGE] == 20 << 30

    def test_status_wipe_mid_online_expand_waits_for_node(self):
        """Status wiped AFTER the PV was already grown for an online
        expand: the controller must re-mark FileSystemResizePending —
        not fake completion — and the kubelet confirms."""
        store, ctrl = world()
        ctrl.sync_all()
        kl = Kubelet(store, "n1", heartbeat_period=0.0)
        store.create("pods", api.Pod(
            metadata=api.ObjectMeta(name="db", uid="u-db"),
            spec=api.PodSpec(node_name="n1",
                             containers=[api.Container(name="c")],
                             volumes=[api.Volume(name="data",
                                                 pvc_name="data")])))
        kl.sync_once(1.0)
        pvc = store.get("persistentvolumeclaims", "default", "data")
        pvc.spec.requests[res.STORAGE] = 20 << 30
        store.update("persistentvolumeclaims", pvc)
        ctrl.sync_all()  # PV grown, FS pending set
        # replace wipes status mid-flight
        pvc = store.get("persistentvolumeclaims", "default", "data")
        pvc.status = api.PersistentVolumeClaimStatus()
        store.update("persistentvolumeclaims", pvc)
        ctrl.sync_all()
        pvc = store.get("persistentvolumeclaims", "default", "data")
        assert any(c[0] == FS_RESIZE_PENDING
                   for c in pvc.status.conditions)
        assert pvc.status.capacity.get(res.STORAGE) is None
        kl.sync_once(2.0)  # the node confirms
        pvc = store.get("persistentvolumeclaims", "default", "data")
        assert pvc.status.capacity[res.STORAGE] == 20 << 30
        assert pvc.status.conditions == []


class TestSystemPriorityClasses:
    def test_bootstrap_and_resolution(self):
        from kubernetes_tpu.client.rest import RESTClient
        from kubernetes_tpu.server import APIServer
        from kubernetes_tpu.server.admission import AdmissionChain

        store = ObjectStore()
        store.create("namespaces", api.Namespace(
            metadata=api.ObjectMeta(name="default"),
            status=api.NamespaceStatus(phase="Active")))
        store.create("serviceaccounts", api.ServiceAccount(
            metadata=api.ObjectMeta(name="default")))
        srv = APIServer(store, admission=AdmissionChain.default()).start()
        try:
            client = RESTClient(srv.url)
            pcs = {p.metadata.name: p.value
                   for p, in zip(store.list("priorityclasses"))}
            assert pcs["system-node-critical"] == 2_000_001_000
            assert pcs["system-cluster-critical"] == 2_000_000_000
            # a pod naming the class gets the resolved priority — which
            # makes it critical for kubelet preemption
            client.create("pods", api.Pod(
                metadata=api.ObjectMeta(name="cp"),
                spec=api.PodSpec(
                    priority_class_name="system-node-critical",
                    containers=[api.Container(name="c")])))
            got = store.get("pods", "default", "cp")
            assert got.spec.priority == 2_000_001_000
            kl = Kubelet(store, "n1", heartbeat_period=0.0)
            assert kl._is_critical(got)
        finally:
            srv.stop()
