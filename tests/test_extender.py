"""HTTP extender tests — in-process webhook server, mirroring the
reference's test/integration/scheduler/extender_test.go setup (a local
httptest server implementing Filter/Prioritize/Bind)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.plugins.registry import default_profile
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched.extender import HTTPExtender
from kubernetes_tpu.sched.scheduler import Scheduler

from helpers import make_node, make_pod


class _ExtenderHandler(BaseHTTPRequestHandler):
    # class-level knobs set by the fixture
    ban_nodes = set()
    prefer_node = None
    bound = []

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        args = json.loads(self.rfile.read(n).decode())
        verb = self.path.rsplit("/", 1)[-1]
        if verb == "filter":
            names = [x for x in args["nodenames"] if x not in self.ban_nodes]
            out = {"nodenames": names,
                   "failedNodes": {x: "extender said no"
                                   for x in args["nodenames"] if x in self.ban_nodes}}
        elif verb == "prioritize":
            out = [{"host": x, "score": (10 if x == self.prefer_node else 0)}
                   for x in args["nodenames"]]
        elif verb == "bind":
            type(self).bound.append((args["podName"], args["node"]))
            out = {}
        else:
            out = {"error": f"unknown verb {verb}"}
        body = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


@pytest.fixture
def extender_server():
    server = HTTPServer(("127.0.0.1", 0), _ExtenderHandler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    _ExtenderHandler.ban_nodes = set()
    _ExtenderHandler.prefer_node = None
    _ExtenderHandler.bound = []
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


def _sched_with_extender(url, **ext_kw):
    store = ObjectStore()
    prof = default_profile(store)
    prof.extenders = [HTTPExtender(url, **ext_kw)]
    return store, Scheduler(store, profile=prof, wave_size=8)


def test_extender_filter_bans_nodes(extender_server):
    _ExtenderHandler.ban_nodes = {"n1", "n2"}
    store, sched = _sched_with_extender(extender_server, filter_verb="filter")
    for i in range(1, 4):
        store.create("nodes", make_node(f"n{i}"))
    store.create("pods", make_pod("p1", cpu="100m"))
    assert sched.schedule_pending() == 1
    assert store.get("pods", "default", "p1").spec.node_name == "n3"


def test_extender_prioritize_steers(extender_server):
    _ExtenderHandler.prefer_node = "n2"
    store, sched = _sched_with_extender(
        extender_server, prioritize_verb="prioritize", weight=100)
    for i in range(1, 4):
        store.create("nodes", make_node(f"n{i}"))
    store.create("pods", make_pod("p1", cpu="100m"))
    assert sched.schedule_pending() == 1
    assert store.get("pods", "default", "p1").spec.node_name == "n2"


def test_extender_bind_delegates(extender_server):
    store, sched = _sched_with_extender(extender_server, bind_verb="bind")
    store.create("nodes", make_node("n1"))
    store.create("pods", make_pod("p1", cpu="100m"))
    assert sched.schedule_pending() == 1
    assert _ExtenderHandler.bound == [("p1", "n1")]
    # the store still reflects the binding (extender bind is the authority,
    # the in-process store mirrors it for informers)
    assert store.get("pods", "default", "p1").spec.node_name == "n1"


def test_extender_filter_all_banned_unschedulable(extender_server):
    _ExtenderHandler.ban_nodes = {"n1"}
    store, sched = _sched_with_extender(extender_server, filter_verb="filter")
    store.create("nodes", make_node("n1"))
    store.create("pods", make_pod("p1", cpu="100m"))
    assert sched.schedule_pending(max_waves=2) == 0
    assert store.get("pods", "default", "p1").spec.node_name == ""


def test_ignorable_extender_down_does_not_block():
    store = ObjectStore()
    prof = default_profile(store)
    prof.extenders = [HTTPExtender("http://127.0.0.1:1", filter_verb="filter",
                                   prioritize_verb="prioritize",
                                   http_timeout=0.2, ignorable=True)]
    sched = Scheduler(store, profile=prof, wave_size=8)
    store.create("nodes", make_node("n1"))
    store.create("pods", make_pod("p1", cpu="100m"))
    assert sched.schedule_pending() == 1


def test_policy_config_builds_extender():
    from kubernetes_tpu.plugins.registry import Registry

    prof = Registry().profile_from_policy(json.dumps({
        "extenders": [{"urlPrefix": "http://example.invalid/sched",
                       "filterVerb": "filter", "weight": 3}]}))
    assert len(prof.extenders) == 1
    assert prof.extenders[0].weight == 3
    assert prof.extenders[0].filter_verb == "filter"
