"""Fault-injection tier (`make chaos`, marker `faults`): named fault
points drive the robustness layer deterministically — the device-path
circuit breaker keeps placements flowing through the exact host path
under persistent kernel failures, and the snapshot scrubber catches the
silent row corruption a faulting device path can leave behind.

Acceptance bar: with fault points injecting persistent device-kernel
failures, `schedule_pending` still places all feasible pods (via host
path) and resumes the device path after faults clear.
"""

import time

import pytest

from kubernetes_tpu.runtime.informer import SharedInformer
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched.breaker import (CLOSED, HALF_OPEN, OPEN,
                                          DevicePathBreaker)
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.utils import faultpoints
from kubernetes_tpu.utils.faultpoints import FaultInjected

from helpers import make_node, make_pod

pytestmark = pytest.mark.faults


class TestFaultPoints:
    def test_inactive_is_noop(self):
        assert not faultpoints.active()
        assert faultpoints.fire("anything") is False
        assert faultpoints.hits("anything") == 0

    def test_raise_mode_and_times(self):
        faultpoints.activate("pt", "raise", times=2)
        with pytest.raises(FaultInjected):
            faultpoints.fire("pt")
        with pytest.raises(FaultInjected):
            faultpoints.fire("pt")
        assert faultpoints.fire("pt") is False  # exhausted
        assert faultpoints.hits("pt") == 2

    def test_custom_exception_factory(self):
        faultpoints.activate("pt", "raise", exc=lambda: ConnectionError("x"))
        with pytest.raises(ConnectionError):
            faultpoints.fire("pt")

    def test_latency_mode(self):
        faultpoints.activate("pt", "latency", arg=0.02)
        t0 = time.monotonic()
        assert faultpoints.fire("pt") is False
        assert time.monotonic() - t0 >= 0.015

    def test_drop_mode_returns_true(self):
        faultpoints.activate("pt", "drop", times=1)
        assert faultpoints.fire("pt") is True
        assert faultpoints.fire("pt") is False

    def test_context_manager_disarms(self):
        with faultpoints.injected("pt", "drop"):
            assert faultpoints.fire("pt") is True
        assert faultpoints.fire("pt") is False
        assert faultpoints.hits("pt") == 1  # hit history survives

    def test_env_spec_parsing(self):
        faultpoints.activate_spec(
            "kernel.wave=raise, bind.post=latency:0.5, queue.shed=drop::3,")
        try:
            assert faultpoints._active["kernel.wave"].mode == "raise"
            assert faultpoints._active["bind.post"].mode == "latency"
            assert faultpoints._active["bind.post"].arg == 0.5
            assert faultpoints._active["queue.shed"].times == 3
        finally:
            faultpoints.reset()
        # malformed tokens fail loudly instead of silently arming nothing
        with pytest.raises(ValueError):
            faultpoints.activate_spec("=bad")

    def test_watch_delivery_drop_loses_event_until_relist(self):
        """The lost-watch-event scenario: a dropped delivery leaves
        every mirror stale; a relisting informer converges."""
        store = ObjectStore()
        inf = SharedInformer(store, "pods")
        with faultpoints.injected("watch.deliver", "drop", times=1):
            store.create("pods", make_pod("px"))
        assert inf.get("default", "px") is None  # mirror missed it
        assert store.get("pods", "default", "px") is not None
        inf2 = SharedInformer(store, "pods")  # list+watch relist
        assert inf2.get("default", "px") is not None


class TestBreakerStateMachine:
    def test_trip_cooldown_probe_recover(self):
        now = [0.0]
        recovered = []
        b = DevicePathBreaker(threshold=2, cooldown=10.0,
                              clock=lambda: now[0],
                              on_recover=lambda: recovered.append(1))
        assert b.allow() and b.state == CLOSED
        b.record_failure()
        assert b.state == CLOSED  # below threshold
        b.record_failure()
        assert b.state == OPEN and b.trips == 1
        assert not b.allow()
        now[0] += 9.9
        assert not b.allow()  # cooldown not elapsed
        now[0] += 0.2
        assert b.allow() and b.state == HALF_OPEN  # the probe
        b.record_success()
        assert b.state == CLOSED and recovered == [1]

    def test_half_open_failure_reopens(self):
        now = [0.0]
        b = DevicePathBreaker(threshold=1, cooldown=5.0,
                              clock=lambda: now[0])
        b.record_failure()
        assert b.state == OPEN
        now[0] += 6.0
        assert b.allow() and b.state == HALF_OPEN
        b.record_failure()
        assert b.state == OPEN and b.trips == 2
        assert not b.allow()  # fresh cooldown

    def test_success_resets_consecutive_count(self):
        b = DevicePathBreaker(threshold=2, clock=lambda: 0.0)
        b.record_failure()
        b.record_success()
        b.record_failure()
        assert b.state == CLOSED  # never two CONSECUTIVE failures


def _faulted_cluster(n_nodes=3, breaker_threshold=2):
    now = [1000.0]
    store = ObjectStore()
    sched = Scheduler(store, clock=lambda: now[0],
                      breaker_threshold=breaker_threshold,
                      breaker_cooldown=30.0)
    for i in range(n_nodes):
        store.create("nodes", make_node(f"n{i}", cpu="4"))
    return store, sched, now


class TestDevicePathBreakerEndToEnd:
    def test_persistent_kernel_faults_never_stop_placement(self):
        store, sched, now = _faulted_cluster()
        faultpoints.activate("kernel.round", "raise")
        faultpoints.activate("kernel.wave", "raise")
        for i in range(6):
            store.create("pods", make_pod(f"p{i}", cpu="1"))
        placed = sched.schedule_pending()
        assert placed == 6  # every feasible pod landed via host path
        assert sched.breaker.state == OPEN
        assert sched.breaker.trips == 1
        assert sched.metrics.device_path_trips.value == 1
        assert sched.metrics.scheduling_errors.value(stage="wave") >= 2
        bound = [p for p in store.list("pods") if p.spec.node_name]
        assert len(bound) == 6
        per_node = {}
        for p in bound:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(v <= 4 for v in per_node.values()), per_node

        # while open: no device attempt is even made, host path carries
        hits0 = faultpoints.hits("kernel.round") + faultpoints.hits("kernel.wave")
        for i in range(3):
            store.create("pods", make_pod(f"q{i}", cpu="1"))
        assert sched.schedule_pending() == 3
        assert sched.breaker.state == OPEN
        assert faultpoints.hits("kernel.round") \
            + faultpoints.hits("kernel.wave") == hits0

    def test_half_open_probe_recovers_device_path(self):
        store, sched, now = _faulted_cluster()
        faultpoints.activate("kernel.round", "raise")
        faultpoints.activate("kernel.wave", "raise")
        for i in range(4):
            store.create("pods", make_pod(f"p{i}", cpu="1"))
        assert sched.schedule_pending() == 4
        assert sched.breaker.state == OPEN

        # faults clear; cooldown elapses; the probe wave re-admits the
        # device path and recovery forces a full snapshot rebuild
        faultpoints.reset()
        now[0] += 31.0
        for i in range(4):
            store.create("pods", make_pod(f"q{i}", cpu="1"))
        assert sched.schedule_pending() == 4
        assert sched.breaker.state == CLOSED
        assert sched.wave_path() in ("pallas", "xla")  # device executed
        # the rebuilt snapshot is exactly host truth
        assert sched.scrubber.scrub().clean

    def test_probe_failure_reopens_with_fresh_cooldown(self):
        store, sched, now = _faulted_cluster(breaker_threshold=1)
        faultpoints.activate("kernel.round", "raise")
        faultpoints.activate("kernel.wave", "raise")
        store.create("pods", make_pod("p0", cpu="1"))
        assert sched.schedule_pending() == 1
        assert sched.breaker.state == OPEN
        now[0] += 31.0  # cooldown over, but the fault persists
        store.create("pods", make_pod("p1", cpu="1"))
        assert sched.schedule_pending() == 1  # probe fails, host path lands it
        assert sched.breaker.state == OPEN
        assert sched.breaker.trips == 2


class TestFaultDrivenScrub:
    def test_corrupt_row_fault_caught_by_scrub(self):
        """The full loop: a corrupt-mode fault silently inflates a node's
        allocatable after a bind's snapshot refresh; the scrub detects
        exactly that row, repairs it, and scheduling proceeds correctly."""
        store, sched, _ = _faulted_cluster(n_nodes=3)
        faultpoints.activate("snapshot.write", "corrupt", times=1)
        store.create("pods", make_pod("p0", cpu="1"))
        assert sched.schedule_pending() == 1
        assert faultpoints.hits("snapshot.write") == 1
        rep = sched.scrubber.scrub()
        assert len(rep.divergences) == 1, rep.summary()
        assert rep.divergences[0].fields == ["alloc"]
        assert rep.divergences[0].repaired
        assert sched.scrubber.scrub().clean
        # post-repair waves place within REAL capacity
        for i in range(11):
            store.create("pods", make_pod(f"q{i}", cpu="1"))
        assert sched.schedule_pending() == 11  # 3x4cpu, 12x1cpu total
        per_node = {}
        for p in store.list("pods"):
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(v <= 4 for v in per_node.values()), per_node

    def test_bind_post_fault_rolls_back_and_retries(self):
        store, sched, _ = _faulted_cluster(n_nodes=2)
        faultpoints.activate("bind.post", "raise", times=2,
                             exc=lambda: ConnectionError("bind lost"))
        for i in range(4):
            store.create("pods", make_pod(f"p{i}", cpu="1"))
        placed = sched.schedule_pending()
        assert faultpoints.hits("bind.post") == 2
        bound = [p for p in store.list("pods") if p.spec.node_name]
        assert len(bound) == 4, (placed, len(bound))
        assert len({p.uid for p in bound}) == 4  # exactly once each
        # the failed binds rolled their assumes back: capacity honest
        rep = sched.scrubber.scrub()
        assert rep.clean, rep.summary()
