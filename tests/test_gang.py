"""Gang scheduling (PodGroup coscheduling): all-or-nothing placement.

Forward-port (no 1.11 reference equivalent): pods annotated with
pod-group.scheduling.k8s.io/name park in the queue's gang waiting area
until minMember members exist, then place atomically through the
joint-assignment kernel (ops/gang.py) — a gang either fully holds
capacity or holds none, and a failed gang backs off as a unit.
"""

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.validation import validate
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched.scheduler import Scheduler

from helpers import make_node, make_pod
from test_scheduler_e2e import FakeClock


def gang_pod(name, gang, min_avail=None, **kw):
    p = make_pod(name, **kw)
    p.metadata.annotations[api.POD_GROUP_NAME_ANNOTATION] = gang
    if min_avail is not None:
        p.metadata.annotations[api.POD_GROUP_MIN_AVAILABLE_ANNOTATION] = \
            str(min_avail)
    return p


def make_world(n_nodes=4, clock=None, wave=16, **node_kw):
    store = ObjectStore()
    kw = dict(clock=clock) if clock is not None else {}
    sched = Scheduler(store, wave_size=wave, **kw)
    for i in range(n_nodes):
        store.create("nodes", make_node(f"n{i}", **node_kw))
    return store, sched


def bound_count(store, gang):
    return sum(1 for p in store.list("pods")
               if api.pod_group_name(p) == gang and p.spec.node_name)


class TestGangAdmission:
    def test_incomplete_gang_waits_then_releases(self):
        """Members below minMember never reach the active queue; the
        arrival of the minMember-th pod releases the whole gang, which
        then places atomically — the smoke test for the fast tier."""
        store, sched = make_world(4)
        store.create("pods", gang_pod("a0", "ga", 3, cpu="1"))
        store.create("pods", gang_pod("a1", "ga", 3, cpu="1"))
        assert sched.schedule_pending() == 0
        assert sched.queue.active_count() == 0
        assert sched.queue.gang_waiting_count() == 2
        assert sched.queue.pending_count() == 2
        store.create("pods", gang_pod("a2", "ga", 3, cpu="1"))
        assert sched.schedule_pending() == 3
        for n in ("a0", "a1", "a2"):
            assert store.get("pods", "default", n).spec.node_name, n
        assert sched.metrics.gang_schedule_attempts.value >= 1
        assert sched.metrics.gang_wait_seconds.total == 1

    def test_min_member_from_podgroup_object(self):
        """A PodGroup API object is the authoritative minMember source;
        members need only the name annotation."""
        store, sched = make_world(4)
        store.create("podgroups", api.PodGroup(
            metadata=api.ObjectMeta(name="gb"),
            spec=api.PodGroupSpec(min_member=2)))
        store.create("pods", gang_pod("b0", "gb", cpu="1"))
        assert sched.schedule_pending() == 0
        assert sched.queue.gang_waiting_count() == 1
        store.create("pods", gang_pod("b1", "gb", cpu="1"))
        assert sched.schedule_pending() == 2

    def test_podgroup_created_after_pods_releases_gang(self):
        """A PodGroup arriving late (lowering the annotation-derived
        minMember) re-evaluates parked gangs."""
        store, sched = make_world(4)
        store.create("pods", gang_pod("c0", "gc", 5, cpu="1"))
        store.create("pods", gang_pod("c1", "gc", 5, cpu="1"))
        assert sched.schedule_pending() == 0
        assert sched.queue.gang_waiting_count() == 2
        store.create("podgroups", api.PodGroup(
            metadata=api.ObjectMeta(name="gc"),
            spec=api.PodGroupSpec(min_member=2)))
        assert sched.schedule_pending() == 2

    def test_member_deleted_while_waiting(self):
        """Deleting a parked member shrinks the gang's member count —
        the gate must NOT open on stale uids (which would place a
        sub-minMember gang); a replacement member then releases the
        survivors."""
        store, sched = make_world(4)
        store.create("pods", gang_pod("d0", "gd", 3, cpu="1"))
        store.create("pods", gang_pod("d1", "gd", 3, cpu="1"))
        assert sched.schedule_pending() == 0
        store.delete("pods", "default", "d1")
        # two live members would be needed again: d2 alone must not open
        # the gate (d1's uid is gone from the member set)
        store.create("pods", gang_pod("d2", "gd", 3, cpu="1"))
        assert sched.schedule_pending() == 0
        assert bound_count(store, "gd") == 0
        store.create("pods", gang_pod("d3", "gd", 3, cpu="1"))
        assert sched.schedule_pending() == 3
        assert bound_count(store, "gd") == 3

    def test_non_gang_pods_unaffected(self):
        """Ordinary pods bypass every gang gate."""
        store, sched = make_world(2)
        store.create("pods", make_pod("plain", cpu="1"))
        assert sched.schedule_pending() == 1
        assert sched.queue.gang_waiting_count() == 0
        assert sched.metrics.gang_schedule_attempts.value == 0


class TestGangAtomicity:
    def test_gang_larger_than_cluster_fails_with_zero_commits(self):
        """The whole gang is infeasible: NOTHING binds, every member is
        parked with a Gang fit error and one shared backoff deadline."""
        clock = FakeClock()
        store, sched = make_world(2, clock=clock, cpu="2")
        for i in range(4):
            store.create("pods", gang_pod(f"e{i}", "ge", 4, cpu="2"))
        assert sched.schedule_pending() == 0
        assert bound_count(store, "ge") == 0
        assert sched.cache.pod_count() == 0  # zero assumes leaked
        for i in range(4):
            pod = store.get("pods", "default", f"e{i}")
            assert pod.spec.node_name == ""
            assert any("pod group could not be placed in full" in c[1]
                       for c in pod.status.conditions), pod.status.conditions
        # unit backoff: all four parked, none active until the window ends
        assert sched.queue.active_count() == 0
        store.create("nodes", make_node("late", cpu="2"))
        assert sched.queue.active_count() == 0  # still inside the window
        clock.advance(1.1)
        # capacity is still short (3 nodes < 4 pods): fails atomically again
        assert sched.schedule_pending() == 0
        assert bound_count(store, "ge") == 0
        store.create("nodes", make_node("late2", cpu="2"))
        clock.advance(2.2)  # second failure doubled the gang's window
        assert sched.schedule_pending() == 4
        assert bound_count(store, "ge") == 4

    def test_two_gangs_contending_never_interleave(self):
        """Node-contention stress (the acceptance invariant): two gangs
        that cannot both fit fight over the same nodes across many
        rounds — after EVERY round, each gang's bound count is 0 or >=
        minMember, never in between."""
        clock = FakeClock()
        store, sched = make_world(4, clock=clock, cpu="2")
        # each gang needs 3 of the 4 single-slot nodes: only one can win
        for i in range(3):
            store.create("pods", gang_pod(f"ga{i}", "g-left", 3, cpu="2"))
            store.create("pods", gang_pod(f"gb{i}", "g-right", 3, cpu="2"))

        def check_invariant():
            for gang in ("g-left", "g-right"):
                n = bound_count(store, gang)
                assert n == 0 or n >= 3, \
                    f"gang {gang} partially bound: {n}/3"

        for round_i in range(8):
            sched.schedule_pending()
            check_invariant()
            clock.advance(2.0 ** min(round_i, 6) + 0.1)
        winners = sorted(bound_count(store, g)
                         for g in ("g-left", "g-right"))
        assert winners == [0, 3]  # exactly one gang holds capacity

    def test_loser_gang_places_after_capacity_frees(self):
        """The losing gang stays whole and places as soon as the winner
        leaves — no deadlock from half-held capacity."""
        clock = FakeClock()
        store, sched = make_world(3, clock=clock, cpu="2")
        for i in range(3):
            store.create("pods", gang_pod(f"wa{i}", "g-win", 3, cpu="2"))
        assert sched.schedule_pending() == 3
        for i in range(3):
            store.create("pods", gang_pod(f"wb{i}", "g-lose", 3, cpu="2"))
        assert sched.schedule_pending() == 0
        assert bound_count(store, "g-lose") == 0
        for i in range(3):
            store.delete("pods", "default", f"wa{i}")
        clock.advance(1.1)
        assert sched.schedule_pending() == 3
        assert bound_count(store, "g-lose") == 3

    def test_partial_gang_beyond_min_member_parks_surplus(self):
        """minMember < gang size: the gang admits once minMember place;
        surplus members that did not fit park individually."""
        store, sched = make_world(2, cpu="2")
        for i in range(3):
            store.create("pods", gang_pod(f"s{i}", "gs", 2, cpu="2"))
        assert sched.schedule_pending() == 2
        assert bound_count(store, "gs") == 2

    def test_wave_boundary_never_splits_a_gang(self):
        """pop_wave either takes a gang whole or defers it whole; a gang
        wider than the wave still travels as one batch."""
        store, sched = make_world(8, wave=4, cpu="4")
        for i in range(6):
            store.create("pods", gang_pod(f"w{i}", "gw", 6, cpu="1"))
        # one extra plain pod shares the backlog
        store.create("pods", make_pod("filler", cpu="1"))
        assert sched.schedule_pending() == 7
        assert bound_count(store, "gw") == 6


class TestGangPreemption:
    def test_high_priority_gang_evicts_whole_victim_gang(self):
        """A higher-priority gang preempts; victim-gang members are
        never left below minMember — the survivors are evicted with the
        direct victims (whole-gang eviction)."""
        clock = FakeClock()
        store, sched = make_world(3, clock=clock, cpu="2")
        for i in range(3):
            p = gang_pod(f"low{i}", "g-low", 3, cpu="2")
            p.spec.priority = 1
            store.create("pods", p)
        assert sched.schedule_pending() == 3
        for i in range(3):
            p = gang_pod(f"high{i}", "g-high", 3, cpu="2")
            p.spec.priority = 100
            store.create("pods", p)
        sched.schedule_pending()
        # victims evicted — and NEVER a sub-minMember remnant left behind
        n_low = bound_count(store, "g-low")
        assert n_low == 0, f"victim gang left at {n_low}/3"
        for _ in range(4):
            clock.advance(2.0)
            sched.schedule_pending()
            if bound_count(store, "g-high") == 3:
                break
        assert bound_count(store, "g-high") == 3

    def test_single_pod_preemptor_spares_gang_with_slack(self):
        """PDB-style gang guard: when one node hosts a no-slack gang
        member and another hosts a plain pod, the preemptor picks the
        plain victim (gang disruption ranks as a violation)."""
        clock = FakeClock()
        store, sched = make_world(2, clock=clock, cpu="2")
        gm = gang_pod("gm0", "g-guard", 1, cpu="2")
        gm.spec.priority = 1
        store.create("pods", gm)
        # the gang annotation resolves min via PodGroup: min=1 means NO
        # slack (evicting its only member breaks it)
        store.create("podgroups", api.PodGroup(
            metadata=api.ObjectMeta(name="g-guard"),
            spec=api.PodGroupSpec(min_member=1)))
        plain = make_pod("plain", cpu="2", priority=1)
        store.create("pods", plain)
        assert sched.schedule_pending() == 2
        vip = make_pod("vip", cpu="2", priority=100)
        store.create("pods", vip)
        sched.schedule_pending()
        clock.advance(2.0)
        sched.schedule_pending()
        assert store.get("pods", "default", "vip").spec.node_name
        # the guard steered the eviction to the plain pod
        assert store.get("pods", "default", "plain") is None
        assert store.get("pods", "default", "gm0") is not None


class TestPodGroupAPI:
    def test_validation(self):
        good = api.PodGroup(metadata=api.ObjectMeta(name="pg"),
                            spec=api.PodGroupSpec(min_member=2))
        assert not validate("podgroups", good)
        bad = api.PodGroup(metadata=api.ObjectMeta(name="pg"),
                           spec=api.PodGroupSpec(min_member=0))
        errs = validate("podgroups", bad)
        assert errs and "minMember" in errs[0].field

    def test_scheme_roundtrip(self):
        from kubernetes_tpu.api import scheme

        pg = api.PodGroup(metadata=api.ObjectMeta(name="pg"),
                          spec=api.PodGroupSpec(min_member=4))
        wire = scheme.encode_object(pg)
        assert wire["kind"] == "PodGroup"
        assert wire["apiVersion"] == "scheduling.sigs.k8s.io/v1alpha1"
        back = scheme.decode_object(wire)
        assert back.spec.min_member == 4

    def test_annotation_helpers(self):
        p = gang_pod("x", "gx", 7)
        assert api.pod_group_name(p) == "gx"
        assert api.pod_group_min_available(p) == 7
        assert api.pod_group_name(make_pod("y")) is None
        p2 = gang_pod("z", "gz")
        p2.metadata.annotations[
            api.POD_GROUP_MIN_AVAILABLE_ANNOTATION] = "junk"
        assert api.pod_group_min_available(p2) is None


class TestGangKernel:
    def test_all_or_nothing_on_device(self):
        """The kernel itself discards placements when need is unmet —
        the host never sees a partial assignment."""
        import jax.numpy as jnp

        from kubernetes_tpu.ops.gang import schedule_gang

        store, sched = make_world(2, cpu="2")
        pods = [gang_pod(f"k{i}", "gk", 4, cpu="2") for i in range(4)]
        pb = sched.featurizer.featurize(pods)
        nt, pm, tt = sched.snapshot.to_device()
        P, N = pb.req.shape[0], nt.valid.shape[0]
        ones = np.ones((P, N), bool)
        kw = dict(weights=sched.profile.weights(),
                  num_zones=sched.snapshot.caps.Z,
                  num_label_values=sched.snapshot.num_label_values)
        res = schedule_gang(nt, pm, tt, pb, ones,
                            jnp.asarray(0, jnp.int32), None,
                            jnp.asarray(4, jnp.int32), **kw)
        assert not bool(np.asarray(res.ok))
        assert int(np.asarray(res.placed)) == 2  # the scan COULD place 2
        assert (np.asarray(res.chosen) == -1).all()  # ...but discarded all
        assert int(np.asarray(res.rr_end)) == 0  # rr rewound
        # with need lowered to what fits, the same batch admits
        res2 = schedule_gang(nt, pm, tt, pb, ones,
                             jnp.asarray(0, jnp.int32), None,
                             jnp.asarray(2, jnp.int32), **kw)
        assert bool(np.asarray(res2.ok))
        chosen = np.asarray(res2.chosen)
        assert (chosen >= 0).sum() == 2
