"""Graph-based garbage collector (controllers/garbagecollector.py).

Verdict criteria: a recreated same-name owner must NOT readopt old
dependents (uid-keyed graph, garbagecollector.go:404 solid/dangling
classification), and a Deployment delete must cascade RS -> pods through
the graph (background cascading deletion)."""

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.labels import LabelSelector
from kubernetes_tpu.controllers.garbagecollector import (ORPHAN_ANNOTATION,
                                                         GarbageCollector)
from kubernetes_tpu.runtime.store import ObjectStore

SEL = LabelSelector(match_labels={"app": "w"})


def owned_pod(name, kind, owner):
    return api.Pod(metadata=api.ObjectMeta(
        name=name, labels={"app": "w"},
        owner_references=[api.OwnerReference(
            kind=kind, name=owner.metadata.name, uid=owner.metadata.uid,
            controller=True)]))


def mkrs(name="rs1", owner=None):
    refs = []
    if owner is not None:
        refs = [api.OwnerReference(kind="Deployment",
                                   name=owner.metadata.name,
                                   uid=owner.metadata.uid, controller=True)]
    return api.ReplicaSet(
        metadata=api.ObjectMeta(name=name, labels={"app": "w"},
                                owner_references=refs),
        spec=api.ReplicaSetSpec(selector=SEL))


class TestGraphGC:
    def test_recreated_owner_does_not_readopt(self):
        """Same name, different uid: the old dependents belong to the
        DEAD incarnation and must be collected."""
        store = ObjectStore()
        gc = GarbageCollector(store)
        rs = mkrs()
        store.create("replicasets", rs)
        store.create("pods", owned_pod("p-old", "ReplicaSet", rs))
        assert gc.sweep() == 0
        store.delete("replicasets", "default", "rs1")
        # recreate the owner under the same name BEFORE the sweep runs
        rs2 = mkrs()
        assert rs2.metadata.uid != rs.metadata.uid
        store.create("replicasets", rs2)
        store.create("pods", owned_pod("p-new", "ReplicaSet", rs2))
        assert gc.sweep() == 1
        names = {p.metadata.name for p in store.list("pods")}
        assert names == {"p-new"}

    def test_deployment_cascade_through_graph(self):
        """Deleting the Deployment cascades RS -> pods: each delete event
        enqueues the next tier of dependents."""
        store = ObjectStore()
        gc = GarbageCollector(store)
        d = api.Deployment(metadata=api.ObjectMeta(name="web"),
                           spec=api.DeploymentSpec(selector=SEL))
        store.create("deployments", d)
        rs = mkrs("web-1", owner=d)
        store.create("replicasets", rs)
        for i in range(3):
            store.create("pods", owned_pod(f"web-1-{i}", "ReplicaSet", rs))
        assert gc.sweep() == 0
        store.delete("deployments", "default", "web")
        assert gc.sweep() == 4  # 1 RS + 3 pods
        assert store.list("replicasets") == []
        assert store.list("pods") == []

    def test_virtual_owner_never_existed(self):
        """A dependent created with a reference to an owner that never
        existed: the virtual node fails verification and the dependent
        is collected (graph_builder attemptToDelete of virtual nodes)."""
        store = ObjectStore()
        gc = GarbageCollector(store)
        ghost = api.ReplicaSet(metadata=api.ObjectMeta(name="ghost"),
                               spec=api.ReplicaSetSpec(selector=SEL))
        store.create("pods", owned_pod("p", "ReplicaSet", ghost))
        assert gc.sweep() == 1
        assert store.list("pods") == []

    def test_mixed_refs_strip_dangling_only(self):
        """Solid + dangling owners: the object survives, the dangling
        reference is patched away (attemptToDeleteItem patch branch)."""
        store = ObjectStore()
        gc = GarbageCollector(store)
        rs = mkrs()
        store.create("replicasets", rs)
        dead = mkrs("dead")
        pod = api.Pod(metadata=api.ObjectMeta(
            name="p", labels={"app": "w"},
            owner_references=[
                api.OwnerReference(kind="ReplicaSet", name="rs1",
                                   uid=rs.metadata.uid, controller=True),
                api.OwnerReference(kind="ReplicaSet", name="dead",
                                   uid=dead.metadata.uid)]))
        store.create("pods", pod)
        assert gc.sweep() == 0
        got = store.get("pods", "default", "p")
        assert len(got.metadata.owner_references) == 1
        assert got.metadata.owner_references[0].name == "rs1"

    def test_orphan_annotation_strips_refs(self):
        """Owner annotated for orphaning: dependents lose the reference
        instead of being collected (propagationPolicy=Orphan analog)."""
        store = ObjectStore()
        gc = GarbageCollector(store)
        rs = mkrs()
        rs.metadata.annotations[ORPHAN_ANNOTATION] = "true"
        store.create("replicasets", rs)
        store.create("pods", owned_pod("p", "ReplicaSet", rs))
        gc.sweep()
        store.delete("replicasets", "default", "rs1")
        assert gc.sweep() == 0
        got = store.get("pods", "default", "p")
        assert got is not None
        assert got.metadata.owner_references == []

    def test_uidless_owner_reference_collected(self):
        """An ownerReference without a uid links by identity; deleting
        the owner still collects the dependent (the reference's server
        always stamps uids, this model tolerates their absence)."""
        store = ObjectStore()
        gc = GarbageCollector(store)
        rs = mkrs()
        store.create("replicasets", rs)
        store.create("pods", api.Pod(metadata=api.ObjectMeta(
            name="p", labels={"app": "w"},
            owner_references=[api.OwnerReference(
                kind="ReplicaSet", name="rs1", controller=True)])))
        assert gc.sweep() == 0
        store.delete("replicasets", "default", "rs1")
        assert gc.sweep() == 1
        assert store.list("pods") == []

    def test_cluster_scoped_owner(self):
        """Owner lookup crosses the namespace boundary for cluster-scoped
        kinds (the dependent's namespace is not the owner's)."""
        store = ObjectStore()
        gc = GarbageCollector(store)
        node = api.Node(metadata=api.ObjectMeta(name="n1", namespace=""))
        store.create("nodes", node)
        store.create("pods", owned_pod("mirror", "Node", node))
        assert gc.sweep() == 0
        store.delete("nodes", "", "n1")
        assert gc.sweep() == 1

    def test_uidless_ref_to_cluster_scoped_owner_nondefault_ns(self):
        """A uid-less reference from a pod in a non-default namespace to
        a cluster-scoped owner still collects when the owner dies."""
        store = ObjectStore()
        gc = GarbageCollector(store)
        node = api.Node(metadata=api.ObjectMeta(name="n1", namespace=""))
        store.create("nodes", node)
        store.create("pods", api.Pod(metadata=api.ObjectMeta(
            name="mirror", namespace="prod",
            owner_references=[api.OwnerReference(kind="Node", name="n1",
                                                 controller=True)])))
        assert gc.sweep() == 0
        store.delete("nodes", "", "n1")
        assert gc.sweep() == 1
        assert store.get("pods", "prod", "mirror") is None
