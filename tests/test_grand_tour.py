"""The user journey, end to end: one cluster, every layer.

A deployment goes in through kubectl, the scheduler places pods onto
kubelet-served nodes, a rolling update rides ControllerRevision-backed
machinery, scale goes through /scale, a TPU workload flows device
plugin -> scheduler -> pinned env, a drain evicts with PDB respect, and
a graceful delete terminates through the kubelet. The reference's e2e
suite checks this composition (test/e2e/apps + scheduling); here it is
one deterministic in-process pump."""

import io
import time

from kubernetes_tpu.api import types as api
from kubernetes_tpu.cli.kubectl import main
from kubernetes_tpu.controllers.deployment import DeploymentController
from kubernetes_tpu.controllers.replicaset import ReplicaSetController
from kubernetes_tpu.kubelet.devicemanager import DevicePlugin
from kubernetes_tpu.kubelet.kubelet import Kubelet
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.server import APIServer, AdmissionChain


def kubectl(srv, *argv):
    out = io.StringIO()
    rc = main(["--server", srv.url, *argv], out=out)
    return rc, out.getvalue()


class World:
    def __init__(self):
        self.store = ObjectStore()
        self.store.create("namespaces", api.Namespace(
            metadata=api.ObjectMeta(name="default"),
            status=api.NamespaceStatus(phase="Active")))
        self.srv = APIServer(self.store,
                             admission=AdmissionChain()).start()
        self.kubelets = []
        for i in range(2):
            kl = Kubelet(self.store, f"n{i}", heartbeat_period=0.0)
            self.kubelets.append(kl)
        # n0 carries the TPUs
        self.kubelets[0].device_manager.register(
            DevicePlugin("google.com/tpu", ["tpu0", "tpu1"]))
        self.sched = Scheduler(self.store, wave_size=16)
        self.dep_ctrl = DeploymentController(self.store)
        self.rs_ctrl = ReplicaSetController(self.store)
        self.t = [0.0]

    def pump(self, rounds=10):
        """One deterministic control-plane heartbeat: controllers,
        scheduler, kubelets — repeated until the world settles."""
        for _ in range(rounds):
            self.t[0] += 1.0
            self.dep_ctrl.sync_all()
            self.rs_ctrl.sync_all()
            self.sched.schedule_pending()
            time.sleep(0.05)  # async binds land
            for kl in self.kubelets:
                kl.sync_once(self.t[0])

    def stop(self):
        self.srv.stop()


def test_grand_tour():
    w = World()
    try:
        # --- deploy through kubectl ---------------------------------
        rc, out = kubectl(w.srv, "create", "deployment", "web",
                          "--image", "web:v1", "--replicas", "3")
        assert rc == 0, out
        w.pump()
        running = [p for p in w.store.list("pods")
                   if p.status.phase == "Running"
                   and "web" in p.metadata.name]
        assert len(running) == 3
        assert all(p.status.pod_ip for p in running)  # networked
        rc, out = kubectl(w.srv, "rollout", "status", "deployment", "web")
        assert "successfully rolled out" in out, out

        # --- rolling update + history + undo ------------------------
        rc, out = kubectl(w.srv, "set", "image", "deployment/web",
                          "web=web:v2")
        assert rc == 0, out
        w.pump(16)
        rc, out = kubectl(w.srv, "rollout", "history", "deployment",
                          "web")
        assert "1" in out and "2" in out
        images = {w.store.get("pods", "default", p.metadata.name)
                  .spec.containers[0].image
                  for p in w.store.list("pods")
                  if "web" in p.metadata.name
                  and p.status.phase == "Running"}
        assert images == {"web:v2"}, images
        rc, out = kubectl(w.srv, "rollout", "undo", "deployment", "web")
        assert "rolled back" in out
        w.pump(16)

        # --- scale through the polymorphic subresource --------------
        rc, out = kubectl(w.srv, "scale", "deployment", "web",
                          "--replicas", "5")
        assert rc == 0
        w.pump(12)
        assert w.store.get("deployments", "default",
                           "web").status.ready_replicas == 5

        # --- a TPU workload flows to the TPU node -------------------
        w.store.create("pods", api.Pod(
            metadata=api.ObjectMeta(name="train", uid="u-train"),
            spec=api.PodSpec(containers=[api.Container(
                name="c", image="trainer:v1",
                resources=api.ResourceRequirements(
                    requests={"cpu": 100, "google.com/tpu": 2},
                    limits={"google.com/tpu": 2}))])))
        w.pump(6)
        train = w.store.get("pods", "default", "train")
        assert train.spec.node_name == "n0"
        st = w.kubelets[0].runtime.get("u-train", "c")
        assert st.env["TPU_VISIBLE_DEVICES"] == "tpu0,tpu1"

        # --- PDB-respecting drain -----------------------------------
        w.store.create("poddisruptionbudgets", api.PodDisruptionBudget(
            metadata=api.ObjectMeta(name="keep-web"),
            spec=api.PodDisruptionBudgetSpec(
                selector=api.LabelSelector(match_labels={"app": "web"}),
                min_available=5)))
        from kubernetes_tpu.controllers.disruption import \
            DisruptionController
        DisruptionController(w.store).sync_all()
        n1_web = [p for p in w.store.list("pods")
                  if p.spec.node_name == "n1" and "web" in p.metadata.name
                  and p.status.phase == "Running"]
        assert n1_web  # spreading put some replicas on n1
        rc, out = kubectl(w.srv, "drain", "n1")
        assert "eviction blocked" in out  # PDB holds at minAvailable=5
        assert w.store.get("nodes", "default",
                           "n1").spec.unschedulable
        rc, out = kubectl(w.srv, "uncordon", "n1")
        assert rc == 0

        # --- graceful delete through the kubelet --------------------
        rc, out = kubectl(w.srv, "delete", "pods", "train",
                          "--grace-period", "30")
        assert rc == 0
        assert w.store.get("pods", "default", "train") is not None
        w.pump(3)
        assert w.store.get("pods", "default", "train") is None
        assert not w.kubelets[0].device_manager.pod_devices("u-train")
    finally:
        w.stop()
