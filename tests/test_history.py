"""ControllerRevision history: DaemonSet/StatefulSet rollout tracking.

Reference test model: pkg/controller/history/controller_history_test.go
(create/find/trim), pkg/controller/statefulset/stateful_set_control_test.go
(RollingUpdate partition + monotonic ordinal order),
pkg/kubectl/history.go viewers via the CLI surface.
"""

import io

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.labels import LabelSelector
from kubernetes_tpu.cli.kubectl import main
from kubernetes_tpu.controllers import history
from kubernetes_tpu.controllers.daemonset import DaemonSetController
from kubernetes_tpu.controllers.statefulset import StatefulSetController
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.server import APIServer, AdmissionChain

SEL = LabelSelector(match_labels={"app": "w"})


def tmpl(image="app:v1"):
    return api.PodTemplateSpec(
        metadata=api.ObjectMeta(labels={"app": "w"}),
        spec=api.PodSpec(containers=[api.Container(name="c", image=image)]))


def mknode(name):
    return api.Node(
        metadata=api.ObjectMeta(name=name),
        status=api.NodeStatus(
            allocatable=api.resource_list(cpu="8", memory="16Gi", pods=110),
            conditions=[api.NodeCondition(api.NODE_READY, api.COND_TRUE)]))


def mark_ready(store, pod):
    pod.status.phase = "Running"
    pod.status.conditions = [c for c in pod.status.conditions
                             if c[0] != "Ready"] + [("Ready", "True")]
    store.update("pods", pod)


def settle(store, ctrl, rounds=10):
    import time
    for _ in range(rounds):
        ctrl.sync_all()
        for p in store.list("pods"):
            if p.status.phase != "Running":
                mark_ready(store, p)
        time.sleep(0.02)


class TestHistoryManager:
    def test_sync_creates_numbered_revisions(self):
        store = ObjectStore()
        ds = api.DaemonSet(metadata=api.ObjectMeta(name="d", uid="u1"),
                           spec=api.DaemonSetSpec(selector=SEL,
                                                  template=tmpl("v1")))
        store.create("daemonsets", ds)
        r1 = history.sync_revision(store, ds, "DaemonSet", ds.spec.template)
        assert r1.revision == 1
        # same template: no new revision
        again = history.sync_revision(store, ds, "DaemonSet", ds.spec.template)
        assert again.metadata.name == r1.metadata.name
        assert len(store.list("controllerrevisions")) == 1
        ds.spec.template = tmpl("v2")
        r2 = history.sync_revision(store, ds, "DaemonSet", ds.spec.template)
        assert r2.revision == 2 and r2.metadata.name != r1.metadata.name

    def test_rollback_reuses_snapshot_at_head(self):
        store = ObjectStore()
        ds = api.DaemonSet(metadata=api.ObjectMeta(name="d", uid="u1"),
                           spec=api.DaemonSetSpec(selector=SEL,
                                                  template=tmpl("v1")))
        store.create("daemonsets", ds)
        r1 = history.sync_revision(store, ds, "DaemonSet", tmpl("v1"))
        history.sync_revision(store, ds, "DaemonSet", tmpl("v2"))
        # roll back to v1: the v1 revision advances to revision 3
        r1b = history.sync_revision(store, ds, "DaemonSet", tmpl("v1"))
        assert r1b.metadata.name == r1.metadata.name
        assert r1b.revision == 3
        assert len(store.list("controllerrevisions")) == 2

    def test_truncate_respects_limit_and_live(self):
        store = ObjectStore()
        ds = api.DaemonSet(
            metadata=api.ObjectMeta(name="d", uid="u1"),
            spec=api.DaemonSetSpec(selector=SEL, template=tmpl("v1"),
                                   revision_history_limit=2))
        store.create("daemonsets", ds)
        hashes = []
        for i in range(5):
            r = history.sync_revision(store, ds, "DaemonSet",
                                      tmpl(f"v{i}"))
            hashes.append(r.metadata.labels["controller-revision-hash"])
        history.truncate_history(store, ds, "DaemonSet",
                                 live_hashes={hashes[0]})
        kept = {(r.metadata.labels or {}).get("controller-revision-hash")
                for r in store.list("controllerrevisions")}
        # live hash survives regardless of age; newest survives; total
        # non-live trimmed to the limit
        assert hashes[0] in kept and hashes[4] in kept
        assert len(kept) == 3  # live + limit(2) newest non-live

    def test_foreign_owner_uid_not_adopted(self):
        store = ObjectStore()
        ds = api.DaemonSet(metadata=api.ObjectMeta(name="d", uid="u1"),
                           spec=api.DaemonSetSpec(selector=SEL,
                                                  template=tmpl("v1")))
        store.create("daemonsets", ds)
        history.sync_revision(store, ds, "DaemonSet", tmpl("v1"))
        # recreated same-name owner with a new uid sees no history
        ds2 = api.DaemonSet(metadata=api.ObjectMeta(name="d", uid="u2"),
                            spec=api.DaemonSetSpec(selector=SEL,
                                                   template=tmpl("v1")))
        assert history.list_revisions(store, ds2, "DaemonSet") == []


class TestDaemonSetHistory:
    def test_sync_snapshots_and_stamps_pods(self):
        store = ObjectStore()
        for i in range(2):
            store.create("nodes", mknode(f"n{i}"))
        ctrl = DaemonSetController(store)
        ds = api.DaemonSet(metadata=api.ObjectMeta(name="d"),
                           spec=api.DaemonSetSpec(selector=SEL,
                                                  template=tmpl("v1")))
        store.create("daemonsets", ds)
        settle(store, ctrl)
        revs = store.list("controllerrevisions")
        assert len(revs) == 1 and revs[0].revision == 1
        h = revs[0].metadata.labels["controller-revision-hash"]
        pods = [p for p in store.list("pods")]
        assert len(pods) == 2
        assert all(p.metadata.labels.get("controller-revision-hash") == h
                   for p in pods)
        # template change: second revision appears, pods roll to it
        ds = store.get("daemonsets", "default", "d")
        ds.spec.template = tmpl("v2")
        store.update("daemonsets", ds)
        settle(store, ctrl)
        revs = sorted(store.list("controllerrevisions"),
                      key=lambda r: r.revision)
        assert [r.revision for r in revs] == [1, 2]
        h2 = revs[1].metadata.labels["controller-revision-hash"]
        assert all(p.metadata.labels.get("controller-revision-hash") == h2
                   for p in store.list("pods"))


class TestStatefulSetRollingUpdate:
    def mksts(self, replicas=3, partition=0, image="app:v1"):
        return api.StatefulSet(
            metadata=api.ObjectMeta(name="db"),
            spec=api.StatefulSetSpec(
                replicas=replicas, selector=SEL, template=tmpl(image),
                update_strategy=api.StatefulSetUpdateStrategy(
                    partition=partition)))

    def test_revision_status_and_rolling_update(self):
        store = ObjectStore()
        ctrl = StatefulSetController(store)
        store.create("statefulsets", self.mksts())
        settle(store, ctrl)
        ss = store.get("statefulsets", "default", "db")
        assert ss.status.current_revision == ss.status.update_revision != ""
        assert ss.status.updated_replicas == 3
        first_rev = ss.status.update_revision
        ss.spec.template = tmpl("v2")
        store.update("statefulsets", ss)
        settle(store, ctrl, rounds=14)
        ss = store.get("statefulsets", "default", "db")
        assert ss.status.update_revision != first_rev
        assert ss.status.current_revision == ss.status.update_revision
        assert ss.status.updated_replicas == 3
        h2 = None
        for r in store.list("controllerrevisions"):
            if r.metadata.name == ss.status.update_revision:
                h2 = r.metadata.labels["controller-revision-hash"]
        pods = store.list("pods")
        assert len(pods) == 3
        assert all(p.metadata.labels["controller-revision-hash"] == h2
                   for p in pods)

    def test_partition_pins_low_ordinals(self):
        store = ObjectStore()
        ctrl = StatefulSetController(store)
        store.create("statefulsets", self.mksts(partition=2))
        settle(store, ctrl)
        old_hash = store.get("pods", "default", "db-0") \
            .metadata.labels["controller-revision-hash"]
        ss = store.get("statefulsets", "default", "db")
        ss.spec.template = tmpl("v2")
        store.update("statefulsets", ss)
        settle(store, ctrl, rounds=14)
        labels = {i: store.get("pods", "default", f"db-{i}")
                  .metadata.labels["controller-revision-hash"]
                  for i in range(3)}
        # ordinals below the partition stay at the old revision
        assert labels[0] == labels[1] == old_hash
        assert labels[2] != old_hash
        ss = store.get("statefulsets", "default", "db")
        assert ss.status.updated_replicas == 1
        # rollout is NOT complete: currentRevision must trail
        assert ss.status.current_revision != ss.status.update_revision

    def test_pinned_ordinal_restarts_at_current_revision(self):
        store = ObjectStore()
        ctrl = StatefulSetController(store)
        store.create("statefulsets", self.mksts(partition=2))
        settle(store, ctrl)
        old_hash = store.get("pods", "default", "db-0") \
            .metadata.labels["controller-revision-hash"]
        ss = store.get("statefulsets", "default", "db")
        ss.spec.template = tmpl("v2")
        store.update("statefulsets", ss)
        settle(store, ctrl, rounds=14)
        # db-0 is pinned below the partition; kill it — the controller
        # must rebuild it from the CURRENT revision's snapshot, not v2
        store.delete("pods", "default", "db-0")
        settle(store, ctrl, rounds=14)
        p0 = store.get("pods", "default", "db-0")
        assert p0.metadata.labels["controller-revision-hash"] == old_hash
        assert p0.spec.containers[0].image == "app:v1"
        ss = store.get("statefulsets", "default", "db")
        assert ss.status.updated_replicas == 1
        assert ss.status.current_revision != ss.status.update_revision

    def test_rollout_not_complete_until_ready(self):
        store = ObjectStore()
        ctrl = StatefulSetController(store)
        store.create("statefulsets", self.mksts(replicas=2))
        settle(store, ctrl)
        ss = store.get("statefulsets", "default", "db")
        ss.spec.template = tmpl("v2")
        store.update("statefulsets", ss)
        # roll, but db-1 never becomes Ready (crash-looping image):
        # currentRevision must NOT catch up to updateRevision
        import time
        for _ in range(14):
            ctrl.sync_all()
            for p in store.list("pods"):
                if p.status.phase != "Running":
                    p.status.phase = "Running"
                    ready = "False" if p.metadata.name == "db-1" else "True"
                    p.status.conditions = [("Ready", ready)]
                    store.update("pods", p)
            time.sleep(0.02)
        ss = store.get("statefulsets", "default", "db")
        assert ss.status.current_revision != ss.status.update_revision

    def test_ondelete_waits_for_manual_delete(self):
        store = ObjectStore()
        ctrl = StatefulSetController(store)
        sts = self.mksts()
        sts.spec.update_strategy = api.StatefulSetUpdateStrategy(
            type="OnDelete")
        store.create("statefulsets", sts)
        settle(store, ctrl)
        old_hash = store.get("pods", "default", "db-0") \
            .metadata.labels["controller-revision-hash"]
        ss = store.get("statefulsets", "default", "db")
        ss.spec.template = tmpl("v2")
        store.update("statefulsets", ss)
        settle(store, ctrl)
        # no automatic roll
        assert store.get("pods", "default", "db-2") \
            .metadata.labels["controller-revision-hash"] == old_hash
        # manual delete: recreated at the update revision
        store.delete("pods", "default", "db-2")
        settle(store, ctrl)
        assert store.get("pods", "default", "db-2") \
            .metadata.labels["controller-revision-hash"] != old_hash


class TestGeneration:
    def test_spec_change_bumps_generation_status_write_does_not(self):
        store = ObjectStore()
        ds = api.DaemonSet(metadata=api.ObjectMeta(name="d"),
                           spec=api.DaemonSetSpec(selector=SEL,
                                                  template=tmpl("v1")))
        store.create("daemonsets", ds)
        assert ds.metadata.generation == 1
        # status-only write: generation holds
        ds.status.number_ready = 1
        store.update("daemonsets", ds)
        assert ds.metadata.generation == 1
        # spec change (in-place mutation of the stored object): bump
        ds.spec.template = tmpl("v2")
        store.update("daemonsets", ds)
        assert ds.metadata.generation == 2

    def test_controller_reports_observed_generation(self):
        store = ObjectStore()
        store.create("nodes", mknode("n0"))
        ctrl = DaemonSetController(store)
        ds = api.DaemonSet(metadata=api.ObjectMeta(name="d"),
                           spec=api.DaemonSetSpec(selector=SEL,
                                                  template=tmpl("v1")))
        store.create("daemonsets", ds)
        settle(store, ctrl)
        ds = store.get("daemonsets", "default", "d")
        assert ds.status.observed_generation == ds.metadata.generation == 1
        ds.spec.template = tmpl("v2")
        store.update("daemonsets", ds)
        assert ds.metadata.generation == 2
        settle(store, ctrl)
        assert store.get("daemonsets", "default", "d") \
            .status.observed_generation == 2


class TestRolloutCLIRevisioned:
    def run(self, server, *argv):
        out = io.StringIO()
        rc = main(["--server", server.url, *argv], out=out)
        return rc, out.getvalue()

    def test_daemonset_history_and_undo(self):
        store = ObjectStore()
        srv = APIServer(store, admission=AdmissionChain()).start()
        try:
            store.create("nodes", mknode("n0"))
            ctrl = DaemonSetController(store)
            ds = api.DaemonSet(metadata=api.ObjectMeta(name="d"),
                               spec=api.DaemonSetSpec(selector=SEL,
                                                      template=tmpl("v1")))
            store.create("daemonsets", ds)
            settle(store, ctrl)
            ds = store.get("daemonsets", "default", "d")
            ds.spec.template = tmpl("v2")
            store.update("daemonsets", ds)
            settle(store, ctrl)
            rc, txt = self.run(srv, "rollout", "history", "daemonset", "d")
            assert rc == 0 and "1" in txt and "2" in txt
            rc, txt = self.run(srv, "rollout", "undo", "daemonset", "d")
            assert rc == 0 and "rolled back to revision 1" in txt
            ds = store.get("daemonsets", "default", "d")
            assert ds.spec.template.spec.containers[0].image == "v1"
            settle(store, ctrl)
            # rollback reuses the old snapshot at a new head revision
            revs = sorted(r.revision
                          for r in store.list("controllerrevisions"))
            assert revs == [2, 3]
            rc, txt = self.run(srv, "rollout", "status", "daemonset", "d")
            assert "successfully rolled out" in txt
        finally:
            srv.stop()

    def test_statefulset_undo_to_revision(self):
        store = ObjectStore()
        srv = APIServer(store, admission=AdmissionChain()).start()
        try:
            ctrl = StatefulSetController(store)
            sts = api.StatefulSet(
                metadata=api.ObjectMeta(name="db"),
                spec=api.StatefulSetSpec(replicas=2, selector=SEL,
                                         template=tmpl("v1")))
            store.create("statefulsets", sts)
            settle(store, ctrl)
            ss = store.get("statefulsets", "default", "db")
            ss.spec.template = tmpl("v2")
            store.update("statefulsets", ss)
            settle(store, ctrl, rounds=14)
            rc, txt = self.run(srv, "rollout", "undo", "statefulset", "db",
                               "--to-revision", "1")
            assert rc == 0 and "rolled back to revision 1" in txt
            ss = store.get("statefulsets", "default", "db")
            assert ss.spec.template.spec.containers[0].image == "v1"
            settle(store, ctrl, rounds=14)
            rc, txt = self.run(srv, "rollout", "status", "statefulset", "db")
            assert "rolling update complete" in txt
        finally:
            srv.stop()


class TestHistoryDetailAndDescribe:
    def run(self, server, *argv):
        out = io.StringIO()
        rc = main(["--server", server.url, *argv], out=out)
        return rc, out.getvalue()

    def test_history_revision_detail_and_describe(self):
        store = ObjectStore()
        srv = APIServer(store, admission=AdmissionChain()).start()
        try:
            store.create("nodes", mknode("n0"))
            ctrl = DaemonSetController(store)
            ds = api.DaemonSet(metadata=api.ObjectMeta(name="d"),
                               spec=api.DaemonSetSpec(selector=SEL,
                                                      template=tmpl("v1")))
            store.create("daemonsets", ds)
            settle(store, ctrl)
            ds = store.get("daemonsets", "default", "d")
            ds.spec.template = tmpl("v2")
            store.update("daemonsets", ds)
            settle(store, ctrl)
            rc, txt = self.run(srv, "rollout", "history", "daemonset", "d",
                               "--revision", "1")
            assert rc == 0 and "revision #1" in txt and "v1" in txt
            rc, txt = self.run(srv, "rollout", "history", "daemonset", "d",
                               "--revision", "2")
            assert "v2" in txt
            rc, txt = self.run(srv, "describe", "daemonset", "d")
            assert rc == 0
            assert "Desired Number of Nodes Scheduled: 1" in txt
            assert "Revisions:" in txt
        finally:
            srv.stop()

    def test_describe_statefulset_shows_revisions(self):
        store = ObjectStore()
        srv = APIServer(store, admission=AdmissionChain()).start()
        try:
            ctrl = StatefulSetController(store)
            store.create("statefulsets", api.StatefulSet(
                metadata=api.ObjectMeta(name="db"),
                spec=api.StatefulSetSpec(replicas=2, selector=SEL,
                                         template=tmpl("v1"))))
            settle(store, ctrl)
            rc, txt = self.run(srv, "describe", "statefulset", "db")
            assert rc == 0
            assert "Replicas:        2 current / 2 desired" in txt
            assert "Current Revision: db-" in txt
        finally:
            srv.stop()
