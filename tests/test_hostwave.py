"""Vectorized numpy host twin (ops/hostwave.py) — ISSUE 7.

Two properties under test:

  1. PARITY — over randomized snapshots, the twin's feasibility masks,
     scores, placements, and preemption stat planes are bit-for-bit
     identical to the jit kernels', and its combined feasibility agrees
     with the golden oracle (plugins/golden.py) per (pod, node). The
     golden comparison runs over a shared-vocab scratch Snapshot (the
     scrubber's trick, via ops/simulate.shadow_snapshot) so interned ids
     line up without touching the live mirror.
  2. DEGRADED MODE — with every device kernel entry faulted
     (breaker-open), the scheduler drains whole backlogs through the
     twin: placements match an identical un-faulted device scheduler,
     preemption stays batched, gang atomicity holds, and inter-pod
     affinity pods ride the twin's batched affinity plane
     (incoming_statics_host) instead of draining through the per-pod
     golden path; only multi-topology-key pods still route golden,
     exactly like the device path.
"""

import numpy as np
import pytest

import kubernetes_tpu.api.types as api
from kubernetes_tpu.ops import hostwave
from kubernetes_tpu.ops.encoding import Caps
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched.breaker import OPEN
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.utils import faultpoints

from helpers import make_node, make_pod

pytestmark = pytest.mark.hostpath


def _weights(sched):
    return dict(weights=sched.profile.weights(),
                num_zones=sched.snapshot.caps.Z,
                num_label_values=sched.snapshot.num_label_values)


def random_world(seed, n_nodes=8, n_existing=10, n_pending=12):
    """Randomized cluster + pending batch over the twin-encodable
    feature set (no inter-pod affinity — those pods take the golden
    path on both backends by design)."""
    rng = np.random.RandomState(seed)
    store = ObjectStore()
    sched = Scheduler(store, wave_size=16)
    for i in range(n_nodes):
        labels = {"zone": f"z{rng.randint(3)}",
                  "kubernetes.io/hostname": f"n{i}"}
        if rng.rand() < 0.5:
            labels["disk"] = rng.choice(["ssd", "hdd"])
        if rng.rand() < 0.3:
            labels["gen"] = str(rng.randint(1, 4))
        taints = []
        if rng.rand() < 0.25:
            taints.append(api.Taint(key="dedicated",
                                    value=rng.choice(["a", "b"]),
                                    effect=rng.choice(
                                        ["NoSchedule", "PreferNoSchedule"])))
        conds = [api.NodeCondition(api.NODE_READY,
                                   api.COND_TRUE if rng.rand() < 0.9
                                   else api.COND_FALSE)]
        store.create("nodes", make_node(
            f"n{i}", cpu=str(rng.randint(2, 9)),
            memory=f"{rng.randint(2, 9)}Gi", labels=labels, taints=taints,
            unschedulable=bool(rng.rand() < 0.1), conditions=conds))
    for i in range(n_existing):
        store.create("pods", make_pod(
            f"ex-{i}", cpu=str(rng.randint(1, 3)),
            priority=int(rng.choice([0, 1, 5, 50])),
            labels={"app": rng.choice(["a", "b", "c"])},
            ports=[int(9000 + rng.randint(4))] if rng.rand() < 0.3 else None))
    sched.schedule_pending()
    pending = []
    for i in range(n_pending):
        kw = {}
        if rng.rand() < 0.3:
            kw["node_selector"] = {"disk": rng.choice(["ssd", "hdd", "nvme"])}
        if rng.rand() < 0.3:
            kw["tolerations"] = [api.Toleration(
                key="dedicated", operator="Exists",
                effect=rng.choice(["NoSchedule", ""]))]
        if rng.rand() < 0.3:
            kw["ports"] = [int(9000 + rng.randint(4))]
        pending.append(make_pod(
            f"pend-{i}", cpu=str(rng.randint(1, 4)),
            priority=int(rng.choice([5, 10, 100])),
            labels={"app": rng.choice(["a", "b", "c"])}, **kw))
    return store, sched, pending


class TestWaveParity:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
    def test_device_host_bitwise_parity(self, seed):
        """Every WaveResult plane — masks, chosen, scores, fail counts,
        feasible counts, round-robin — identical between the jit wave
        kernel and the numpy twin on a randomized snapshot."""
        import jax.numpy as jnp

        from kubernetes_tpu.ops.kernel import schedule_wave

        store, sched, pending = random_world(seed)
        pb = sched.featurizer.featurize(pending)
        P = pb.req.shape[0]
        extra = np.ones((P, sched.snapshot.caps.N), bool)
        nt_d, pm_d, tt_d = sched.snapshot.to_device()
        res_d = schedule_wave(nt_d, pm_d, tt_d, pb, extra,
                              jnp.asarray(3, jnp.int32), None,
                              has_ipa=False, **_weights(sched))
        nt, pm, tt = sched.snapshot.host_tensors()
        res_h, _usage = hostwave.schedule_wave_host(
            nt, pm, tt, pb, extra, 3, None, **_weights(sched))
        assert np.array_equal(np.asarray(res_d.masks), res_h.masks)
        assert np.array_equal(np.asarray(res_d.chosen), res_h.chosen)
        assert np.array_equal(np.asarray(res_d.score), res_h.score)
        assert np.array_equal(np.asarray(res_d.fail_counts),
                              res_h.fail_counts)
        assert np.array_equal(np.asarray(res_d.feasible_count),
                              res_h.feasible_count)
        assert int(res_d.rr_end) == int(res_h.rr_end)

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_twin_matches_golden_oracle(self, seed):
        """Per (pod, node) combined feasibility of the twin equals the
        golden predicates, evaluated over a shared-vocab scratch
        Snapshot (the scrubber trick) so the live mirror stays
        untouched."""
        from kubernetes_tpu.ops.simulate import shadow_snapshot
        from kubernetes_tpu.plugins import golden

        store, sched, pending = random_world(seed, n_pending=6)
        shadow, n_real = shadow_snapshot(sched.cache, sched.snapshot)
        feat = sched.shadow_featurizer(shadow)
        for pod in pending:
            pb = feat.featurize([pod])
            nt, pm, tt = shadow.host_tensors()
            extra = np.ones((pb.req.shape[0], shadow.caps.N), bool)
            res, _ = hostwave.schedule_wave_host(
                nt, pm, tt, pb, extra, 0, None,
                weights=sched.profile.weights(), num_zones=shadow.caps.Z,
                num_label_values=shadow.num_label_values)
            combined = res.masks.all(axis=0)[0]  # [N]
            for name, idx in shadow.node_index.items():
                ni = sched.cache.node_infos.get(name)
                if ni is None or ni.node is None:
                    continue
                ok, _reasons = golden.pod_fits_on_node(pod, ni)
                assert bool(combined[idx]) == ok, \
                    f"pod {pod.name} node {name}: twin={bool(combined[idx])} golden={ok}"


class TestPreemptionParity:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_stats_bitwise_parity(self, seed):
        """The packed [5, P, N] what-if stat stack — ok, victim count,
        priority max, bitcast priority sum, bitcast gang weight —
        byte-identical between the device kernel and the twin."""
        import jax.numpy as jnp

        from kubernetes_tpu.ops.preempt import preemption_stats

        store, sched, pending = random_world(seed, n_existing=14)
        vips = [make_pod(f"vip-{i}", cpu="2", priority=100)
                for i in range(4)]
        pb = sched.featurizer.featurize(vips)
        live = sched.snapshot.ep_valid & sched.snapshot.ep_alive
        levels = hostwave.victim_levels(sched.snapshot.ep_prio, live, 8)
        assert levels is not None
        gang_w = np.zeros((sched.snapshot.caps.M,), np.float32)
        gang_w[:3] = 1.0  # arbitrary disruption weights exercise plane 4
        nt_d, pm_d, tt_d = sched.snapshot.to_device()
        pk_d = np.asarray(preemption_stats(
            nt_d, pm_d, pb, jnp.asarray(levels, jnp.int32), num_levels=8,
            gang_w=jnp.asarray(gang_w)))
        nt, pm, tt = sched.snapshot.host_tensors()
        pk_h = hostwave.preemption_stats_host(
            nt, pm, pb, np.asarray(levels, np.int32), num_levels=8,
            gang_w=gang_w)
        assert np.array_equal(pk_d, pk_h)

    def test_prune_preserves_preempt_choice(self):
        """preempt() with the vectorized candidate prune picks the same
        node and victim set as the unpruned validate-everything loop."""
        from kubernetes_tpu.sched.preemption import preempt

        store = ObjectStore()
        sched = Scheduler(store, wave_size=4)
        for i in range(6):
            store.create("nodes", make_node(f"n{i}", cpu="2"))
        for i in range(6):
            store.create("pods", make_pod(f"hog-{i}", cpu="2",
                                          priority=1 if i % 2 else 50))
        assert sched.schedule_pending() == 6
        vip = make_pod("vip", cpu="2", priority=100)
        failed = {f"n{i}": ["PodFitsResources"] for i in range(6)}
        exact = preempt(vip, sched.cache, failed, [])
        pruned = preempt(vip, sched.cache, failed, [],
                         snapshot=sched.snapshot,
                         featurizer=sched.featurizer)
        assert exact is not None and pruned is not None
        # the prune ranks odd-numbered nodes (priority-1 victims) ahead
        # of the priority-50 ones — same lexicographic criteria the
        # exact pick applies after validating everything
        assert {v.uid for v in pruned.victims} == \
            {v.uid for v in exact.victims}
        assert api.pod_priority(pruned.victims[0]) == 1

    def test_prune_drops_hopeless_nodes(self):
        """A node that cannot fit the pod even with EVERY lower-priority
        pod removed is pruned before any clone/reprieve work."""
        from kubernetes_tpu.sched.preemption import vector_candidate_order

        store = ObjectStore()
        sched = Scheduler(store, wave_size=4)
        store.create("nodes", make_node("big", cpu="4"))
        store.create("nodes", make_node("small", cpu="1"))
        store.create("pods", make_pod("hog-big", cpu="4", priority=1))
        store.create("pods", make_pod("hog-small", cpu="1", priority=1))
        assert sched.schedule_pending() == 2
        vip = make_pod("vip", cpu="3", priority=100)
        order = vector_candidate_order(vip, sched.snapshot,
                                       sched.featurizer)
        assert order == ["big"]  # "small" can never host a 3-cpu pod


def _faulted(n_nodes=4, cpu="4", wave=8, threshold=2):
    """Cluster whose device path faults at every kernel entry — after
    `threshold` failures the breaker opens and the twin carries."""
    store = ObjectStore()
    sched = Scheduler(store, wave_size=wave, breaker_threshold=threshold)
    for i in range(n_nodes):
        store.create("nodes", make_node(f"n{i}", cpu=cpu))
    faultpoints.activate("kernel.round", "raise")
    faultpoints.activate("kernel.wave", "raise")
    faultpoints.activate("kernel.gang", "raise")
    return store, sched


class TestDegradedVectorWave:
    def test_breaker_open_placements_match_device_path(self):
        """End-to-end device==host: an identical workload placed by a
        clean device scheduler and by a breaker-open (twin) scheduler
        lands every pod on the same node."""
        def build(faulted):
            store = ObjectStore()
            sched = Scheduler(store, wave_size=8, breaker_threshold=1)
            for i in range(5):
                store.create("nodes", make_node(f"n{i}", cpu="4"))
            if faulted:
                faultpoints.activate("kernel.round", "raise")
                faultpoints.activate("kernel.wave", "raise")
            for i in range(12):
                store.create("pods", make_pod(f"p{i}", cpu="1"))
            assert sched.schedule_pending() == 12
            return store, sched

        store_d, sched_d = build(False)
        want = {p.metadata.name: p.spec.node_name
                for p in store_d.list("pods")}
        faultpoints.reset()
        store_h, sched_h = build(True)
        got = {p.metadata.name: p.spec.node_name
               for p in store_h.list("pods")}
        assert sched_h.breaker.state == OPEN
        assert got == want
        assert sched_h.metrics.waves_total.value(path="host") >= 1
        # degraded waves ran the VECTOR backend, not the golden loop
        assert sched_h.wave_path() == "vector"

    def test_degraded_preemption_is_batched(self):
        """Breaker open + saturated cluster + high-priority backlog:
        evictions happen through the batched twin what-if (pipeline
        accounting), not the per-pod cascade, and the vips land."""
        store, sched = _faulted(n_nodes=4, cpu="2", wave=4)
        for i in range(4):
            store.create("pods", make_pod(f"hog-{i}", cpu="2", priority=1))
        assert sched.schedule_pending() == 4
        for i in range(4):
            store.create("pods", make_pod(f"vip-{i}", cpu="2",
                                          priority=100))
        sched.schedule_pending()
        assert sched.breaker.state == OPEN
        assert sched.pipeline_preemptions == 4
        assert all(store.get("pods", "default", f"hog-{i}") is None
                   for i in range(4))
        import time

        deadline = time.monotonic() + 10.0
        placed = 0
        while placed < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
            placed += sched.schedule_pending()
        vips = [store.get("pods", "default", f"vip-{i}") for i in range(4)]
        assert all(v.spec.node_name for v in vips)

    def test_degraded_gang_atomicity_restored(self):
        """Gangs stay all-or-nothing in degraded mode through the twin's
        count-feasibility plane: a fitting gang fully places, an
        unfittable one places NOTHING (PR 2 suspended this; the twin
        restores it)."""
        store, sched = _faulted(n_nodes=3, cpu="2", wave=8)
        # trip the breaker with plain pods first: a gang arriving while
        # the breaker is CLOSED parks on the device failure (atomicity:
        # nothing placed) rather than degrading mid-attempt
        for i in range(2):
            store.create("pods", make_pod(f"filler-{i}", cpu="100m"))
        assert sched.schedule_pending() == 2
        assert sched.breaker.state == OPEN
        for i in range(2):
            store.delete("pods", "default", f"filler-{i}")

        def gang(name, size, cpu):
            out = []
            for j in range(size):
                p = make_pod(f"{name}-{j}", cpu=cpu)
                p.metadata.annotations = {
                    "pod-group.scheduling.k8s.io/name": name,
                    "pod-group.scheduling.k8s.io/min-available": str(size)}
                out.append(p)
            return out

        for p in gang("fits", 3, "2"):
            store.create("pods", p)
        assert sched.schedule_pending() == 3
        assert sched.breaker.state == OPEN
        for p in gang("toobig", 4, "2"):
            store.create("pods", p)
        assert sched.schedule_pending() == 0
        assert all(not store.get("pods", "default", f"toobig-{j}").spec.node_name
                   for j in range(4))

    def test_degraded_affinity_pods_take_the_twin(self):
        """The inter-pod affinity plane IS twinned: breaker-open
        placement of anti-affine pods stays on the batched numpy twin
        (no per-pod golden routing — reason=affinity stays zero) and
        still honors the constraint exactly."""
        from kubernetes_tpu.api.labels import LabelSelector

        store, sched = _faulted(n_nodes=3, cpu="4", wave=8)
        aff = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
            required=[api.PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"g": "x"}),
                topology_key="kubernetes.io/hostname")]))
        for i in range(3):
            store.create("pods", make_pod(f"anti-{i}", cpu="1",
                                          labels={"g": "x"}, affinity=aff))
        assert sched.schedule_pending() == 3
        assert sched.breaker.state == OPEN
        nodes = {store.get("pods", "default", f"anti-{i}").spec.node_name
                 for i in range(3)}
        assert len(nodes) == 3  # one per host, exactly
        # the affinity coverage gap is CLOSED: no pod went golden for
        # reason=affinity — the twin carried the whole wave batched
        assert sched.metrics.degraded_golden_pods.value(
            reason="affinity") == 0
        assert sched.metrics.degraded_golden_pods.value(
            reason="multi_tk") == 0
        # and the twin actually ran (host waves, not golden pods/s)
        assert sched.metrics.waves_total.value(path="host") >= 1

    def test_degraded_golden_reasons_and_ledger_tag(self):
        """multi-topology-key pods count under reason=multi_tk, and the
        degraded round's ledger entry carries the per-reason tally."""
        from kubernetes_tpu.api.labels import LabelSelector
        from kubernetes_tpu.utils import tracing

        store, sched = _faulted(n_nodes=4, cpu="8", wave=8)
        rec = tracing.enable()
        try:
            # required anti-affinity over TWO topology keys -> the
            # multi-tk encoding limit (needs_host_path), not just the
            # untwinned-affinity plane
            aff = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
                required=[
                    api.PodAffinityTerm(
                        label_selector=LabelSelector(
                            match_labels={"g": "y"}),
                        topology_key="kubernetes.io/hostname"),
                    api.PodAffinityTerm(
                        label_selector=LabelSelector(
                            match_labels={"g": "y"}),
                        topology_key=api.LABEL_ZONE),
                ]))
            # trip the breaker with plain pods FIRST: only pods that
            # arrive while it's open take the DEGRADED golden route
            for i in range(3):
                store.create("pods", make_pod(f"plain-{i}", cpu="1"))
            assert sched.schedule_pending() == 3
            assert sched.breaker.state == OPEN
            store.create("pods", make_pod("multi-tk", cpu="1",
                                          labels={"g": "y"}, affinity=aff))
            assert sched.schedule_pending() == 1
            assert sched.metrics.degraded_golden_pods.value(
                reason="multi_tk") == 1
            ledgers = [r for r in rec.ledger_rows()
                       if r.get("degraded_golden")]
            assert ledgers, "degraded round ledger entry not tagged"
            assert ledgers[-1]["degraded_golden"] == {"multi_tk": 1}
        finally:
            tracing.disable()

    def test_simulate_host_backend_matches_device(self):
        """The autoscaler what-if's host backend returns the same
        verdict planes as the device pass on the same shadow."""
        from kubernetes_tpu.ops import simulate

        store, sched, pending = random_world(7, n_pending=5)
        shadow, n_real = simulate.shadow_snapshot(sched.cache,
                                                  sched.snapshot)
        feat = sched.shadow_featurizer(shadow)
        pb = feat.featurize(pending)
        kw = dict(weights=sched.profile.weights(),
                  num_zones=shadow.caps.Z,
                  num_label_values=shadow.num_label_values)
        v_d = simulate.simulate_placements(shadow, pb, **kw)
        v_h = simulate.simulate_placements(shadow, pb, backend="host", **kw)
        assert np.array_equal(v_d.chosen, v_h.chosen)
        assert np.array_equal(v_d.feasible, v_h.feasible)


class TestInterPodAffinityTwin:
    """Bitwise parity of the twinned inter-pod affinity plane
    (ops/hostwave.py incoming_statics_host + the has_ipa commit loop)
    against the device kernel — the coverage gap the degraded path used
    to pay for with per-pod golden routing."""

    @staticmethod
    def _ipa_world(seed, n_nodes=10, n_existing=8, n_pods=12):
        """Randomized world that is GUARANTEED affinity-rich: required
        (anti)affinity, preferred terms, and existing pods carrying
        required anti terms (the symmetry plane)."""
        import random

        from kubernetes_tpu.api.labels import LabelSelector
        from test_parity import build

        rng = random.Random(seed)
        nodes = [make_node(f"n{i}", cpu="8", memory="16Gi",
                           labels={"kubernetes.io/hostname": f"n{i}",
                                   api.LABEL_ZONE: f"z{i % 3}"})
                 for i in range(n_nodes)]
        existing = []
        for i in range(n_existing):
            aff = None
            if rng.random() < 0.5:
                aff = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
                    required=[api.PodAffinityTerm(
                        label_selector=LabelSelector(
                            match_labels={"grp": f"g{i % 3}"}),
                        topology_key="kubernetes.io/hostname")]))
            existing.append(make_pod(
                f"ex-{i}", cpu="200m", memory="256Mi",
                labels={"grp": f"g{i % 3}", "app": "web"},
                node_name=f"n{i % n_nodes}", affinity=aff))
        pods = []
        for i in range(n_pods):
            r = rng.random()
            aff = None
            if r < 0.3:
                aff = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
                    required=[api.PodAffinityTerm(
                        label_selector=LabelSelector(
                            match_labels={"grp": f"g{i % 3}"}),
                        topology_key="kubernetes.io/hostname")]))
            elif r < 0.5:
                aff = api.Affinity(pod_affinity=api.PodAffinity(
                    required=[api.PodAffinityTerm(
                        label_selector=LabelSelector(
                            match_labels={"grp": f"g{(i + 1) % 3}"}),
                        topology_key=api.LABEL_ZONE)]))
            elif r < 0.7:
                aff = api.Affinity(pod_affinity=api.PodAffinity(
                    preferred=[api.WeightedPodAffinityTerm(
                        weight=rng.randint(1, 100),
                        pod_affinity_term=api.PodAffinityTerm(
                            label_selector=LabelSelector(
                                match_labels={"app": "web"}),
                            topology_key=api.LABEL_ZONE))]))
            pods.append(make_pod(
                f"p{i}", cpu=f"{rng.randint(1, 8) * 100}m", memory="128Mi",
                labels={"grp": f"g{i % 3}", "app": "web"}, affinity=aff))
        cache, snap = build(nodes, existing)
        return rng, snap, pods

    @pytest.mark.parametrize("seed", range(6))
    def test_ipa_wave_bitwise_parity(self, seed):
        """Device kernel == numpy twin on affinity-rich worlds: chosen,
        score, rr, fail counts, the FULL mask stack (incl. the
        MatchInterPodAffinity row), and the score decomposition."""
        import jax.numpy as jnp

        from kubernetes_tpu.ops.kernel import Weights, schedule_wave
        from kubernetes_tpu.state.featurize import PodFeaturizer

        rng, snap, pods = self._ipa_world(seed)
        feat = PodFeaturizer(snap, group_selectors=lambda p: [])
        pb = feat.featurize(pods)
        nt, pm, tt = snap.to_device()
        P = pb.req.shape[0]
        extra = np.ones((P, snap.caps.N), bool)
        kw = dict(weights=Weights(), num_zones=snap.caps.Z,
                  num_label_values=snap.num_label_values, has_ipa=True)
        rr0 = rng.randint(0, 5)
        dev = schedule_wave(nt, pm, tt, pb, extra,
                            jnp.asarray(rr0, jnp.int32),
                            collect_scores=True, **kw)
        nth, pmh, tth = snap.host_tensors()
        host, _u = hostwave.schedule_wave_host(
            nth, pmh, tth, pb, extra, rr0, None, collect_scores=True, **kw)
        # the statics twin actually saw affinity programs
        assert (np.any(pb.ra_has) or np.any(pb.rn_has)
                or np.any(pb.pa_w != 0) or np.any(np.asarray(tth.valid)))
        np.testing.assert_array_equal(np.asarray(dev.chosen), host.chosen)
        np.testing.assert_array_equal(np.asarray(dev.score), host.score)
        np.testing.assert_array_equal(np.asarray(dev.fail_counts),
                                      host.fail_counts)
        np.testing.assert_array_equal(np.asarray(dev.masks), host.masks)
        assert int(np.asarray(dev.rr_end)) == int(host.rr_end)
        for a, b in zip(dev.deco, host.deco):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("seed", range(3))
    def test_ipa_gang_bitwise_parity(self, seed):
        """The all-or-nothing gang wrapper under has_ipa: device ==
        twin on ok / chosen / placed / rr."""
        import jax.numpy as jnp

        from kubernetes_tpu.ops.gang import schedule_gang
        from kubernetes_tpu.ops.kernel import Weights
        from kubernetes_tpu.state.featurize import PodFeaturizer

        rng, snap, pods = self._ipa_world(seed + 100, n_pods=6)
        feat = PodFeaturizer(snap, group_selectors=lambda p: [])
        pb = feat.featurize(pods)
        nt, pm, tt = snap.to_device()
        P = pb.req.shape[0]
        extra = np.ones((P, snap.caps.N), bool)
        kw = dict(weights=Weights(), num_zones=snap.caps.Z,
                  num_label_values=snap.num_label_values, has_ipa=True)
        need = rng.randint(1, len(pods))
        dev = schedule_gang(nt, pm, tt, pb, extra,
                            jnp.asarray(0, jnp.int32), None,
                            jnp.asarray(need, jnp.int32), **kw)
        nth, pmh, tth = snap.host_tensors()
        host = hostwave.schedule_gang_host(
            nth, pmh, tth, pb, extra, 0, None, need, **kw)
        assert bool(np.asarray(dev.ok)) == bool(host.ok)
        np.testing.assert_array_equal(np.asarray(dev.chosen), host.chosen)
        assert int(np.asarray(dev.placed)) == int(host.placed)
        assert int(np.asarray(dev.rr_end)) == int(host.rr_end)

    def test_degraded_affinity_e2e_matches_clean_device_run(self):
        """Breaker-open end-to-end with required (anti)affinity, a
        preferred term, and symmetry from existing pods: the degraded
        scheduler's placements equal a clean device scheduler's exactly
        — and no pod was routed golden for reason=affinity."""
        from kubernetes_tpu.api.labels import LabelSelector

        def world(store):
            for i in range(8):
                store.create("nodes", make_node(
                    f"n{i}", cpu="8", memory="16Gi",
                    labels={"kubernetes.io/hostname": f"n{i}",
                            api.LABEL_ZONE: f"z{i % 2}"}))
            for i in range(12):
                aff = None
                labels = {"app": "w"}
                if i % 4 == 0:
                    labels = {"anti": "a", "app": "w"}
                    aff = api.Affinity(
                        pod_anti_affinity=api.PodAntiAffinity(
                            required=[api.PodAffinityTerm(
                                label_selector=LabelSelector(
                                    match_labels={"anti": "a"}),
                                topology_key="kubernetes.io/hostname")]))
                elif i % 4 == 1:
                    aff = api.Affinity(pod_affinity=api.PodAffinity(
                        preferred=[api.WeightedPodAffinityTerm(
                            weight=10,
                            pod_affinity_term=api.PodAffinityTerm(
                                label_selector=LabelSelector(
                                    match_labels={"app": "w"}),
                                topology_key=api.LABEL_ZONE))]))
                store.create("pods", make_pod(
                    f"p{i}", cpu="500m", memory="128Mi", labels=labels,
                    affinity=aff))

        ref_store = ObjectStore()
        ref = Scheduler(ref_store, wave_size=8)
        world(ref_store)
        assert ref.schedule_pending() == 12

        store, sched = _faulted(n_nodes=0, wave=8)
        world(store)
        assert sched.schedule_pending() == 12
        assert sched.breaker.state == OPEN
        want = sorted((p.metadata.name, p.spec.node_name)
                      for p in ref_store.list("pods"))
        got = sorted((p.metadata.name, p.spec.node_name)
                     for p in store.list("pods"))
        assert got == want
        assert sched.metrics.degraded_golden_pods.value(
            reason="affinity") == 0
