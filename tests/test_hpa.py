"""Horizontal Pod Autoscaler tests.

Reference control law: pkg/controller/podautoscaler/horizontal.go:80 +
replica_calculator.go (ratio = utilization/target, ceil, 0.1 tolerance,
min/max clamp, upscale/downscale forbidden windows).
"""

from kubernetes_tpu.api import labels as lbl
from kubernetes_tpu.api import resources as res
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers import (DeploymentController,
                                        HorizontalPodAutoscalerController,
                                        ReplicaSetController)
from kubernetes_tpu.runtime.store import ObjectStore

from test_controllers import SEL, TMPL


def mkhpa(name="hpa", target="d1", minr=1, maxr=10, cpu=50):
    return api.HorizontalPodAutoscaler(
        metadata=api.ObjectMeta(name=name),
        spec=api.HorizontalPodAutoscalerSpec(
            scale_target_ref=api.CrossVersionObjectReference(
                kind="Deployment", name=target),
            min_replicas=minr, max_replicas=maxr,
            target_cpu_utilization_percentage=cpu))


def set_metrics(store, pod_name, cpu_milli):
    cur = store.get("podmetrics", "default", pod_name)
    if cur is None:
        store.create("podmetrics", api.PodMetrics(
            metadata=api.ObjectMeta(name=pod_name),
            usage={res.CPU: cpu_milli}))
    else:
        cur.usage[res.CPU] = cpu_milli
        store.update("podmetrics", cur)


def world(replicas=2, target_cpu=50):
    store = ObjectStore()
    now = [1000.0]
    dep_ctrl = DeploymentController(store)
    rs_ctrl = ReplicaSetController(store)
    hpa_ctrl = HorizontalPodAutoscalerController(store,
                                                 clock=lambda: now[0])
    store.create("deployments", api.Deployment(
        metadata=api.ObjectMeta(name="d1"),
        spec=api.DeploymentSpec(replicas=replicas, selector=SEL,
                                template=TMPL)))
    store.create("horizontalpodautoscalers", mkhpa(cpu=target_cpu))
    dep_ctrl.sync_all()
    rs_ctrl.sync_all()
    return store, dep_ctrl, rs_ctrl, hpa_ctrl, now


def pods(store):
    return [p for p in store.list("pods") if api.is_pod_active(p)]


def test_scales_up_under_load():
    """Deployment at 2 replicas, each pod at 100m usage vs 100m request
    (100% util) against a 50% target -> ratio 2.0 -> 4 replicas, and the
    deployment controller materializes the new pods."""
    store, dep_ctrl, rs_ctrl, hpa_ctrl, now = world(replicas=2)
    for p in pods(store):
        set_metrics(store, p.metadata.name, 100)
    hpa_ctrl.sync_all()
    dep = store.get("deployments", "default", "d1")
    assert dep.spec.replicas == 4
    hpa = store.get("horizontalpodautoscalers", "default", "hpa")
    assert hpa.status.current_cpu_utilization_percentage == 100
    assert hpa.status.desired_replicas == 4
    dep_ctrl.sync_all()
    rs_ctrl.sync_all()
    assert len(pods(store)) == 4


def test_within_tolerance_does_not_scale():
    store, _, _, hpa_ctrl, now = world(replicas=2, target_cpu=50)
    for p in pods(store):
        set_metrics(store, p.metadata.name, 52)  # 52% vs 50% -> ratio 1.04
    hpa_ctrl.sync_all()
    assert store.get("deployments", "default", "d1").spec.replicas == 2


def test_scale_down_respects_forbidden_window():
    store, dep_ctrl, rs_ctrl, hpa_ctrl, now = world(replicas=2)
    for p in pods(store):
        set_metrics(store, p.metadata.name, 100)
    hpa_ctrl.sync_all()
    assert store.get("deployments", "default", "d1").spec.replicas == 4
    dep_ctrl.sync_all()
    rs_ctrl.sync_all()
    # load drops immediately: downscale forbidden for 5 minutes
    for p in pods(store):
        set_metrics(store, p.metadata.name, 5)
    hpa_ctrl.sync_all()
    assert store.get("deployments", "default", "d1").spec.replicas == 4
    now[0] += 5 * 60 + 1
    hpa_ctrl.resync()
    hpa_ctrl.sync_all()
    dep = store.get("deployments", "default", "d1")
    assert dep.spec.replicas < 4


def test_max_replicas_clamp():
    store, _, _, hpa_ctrl, now = world(replicas=2)
    hpa = store.get("horizontalpodautoscalers", "default", "hpa")
    hpa.spec.max_replicas = 3
    store.update("horizontalpodautoscalers", hpa)
    for p in pods(store):
        set_metrics(store, p.metadata.name, 500)  # ratio 10
    hpa_ctrl.sync_all()
    assert store.get("deployments", "default", "d1").spec.replicas == 3


def test_no_metrics_no_action():
    store, _, _, hpa_ctrl, now = world(replicas=2)
    hpa_ctrl.sync_all()
    assert store.get("deployments", "default", "d1").spec.replicas == 2


def test_e2e_synthetic_load_cycle():
    """Full loop: scale up under load, settle, scale back down after the
    stabilization window — the reference's e2e autoscaling shape
    (test/e2e/autoscaling) in miniature."""
    store, dep_ctrl, rs_ctrl, hpa_ctrl, now = world(replicas=1,
                                                    target_cpu=50)
    settle = lambda: (dep_ctrl.sync_all(), rs_ctrl.sync_all())  # noqa: E731
    settle()
    # load spike: 1 pod at 200% of request
    for p in pods(store):
        set_metrics(store, p.metadata.name, 200)
    hpa_ctrl.sync_all()
    settle()
    n_up = len(pods(store))
    assert n_up == 4  # ceil(200/50 * 1)
    # load spreads out and drops to 10% per pod
    now[0] += 6 * 60
    for p in pods(store):
        set_metrics(store, p.metadata.name, 10)
    hpa_ctrl.resync()
    hpa_ctrl.sync_all()
    settle()
    assert len(pods(store)) == 1  # ceil(0.2 * 4) = 1


def test_min_replicas_enforced_even_on_target():
    """horizontal.go normalizeDesiredReplicas: the [min,max] clamp
    applies even when utilization is within tolerance."""
    store, dep_ctrl, rs_ctrl, hpa_ctrl, now = world(replicas=2)
    for p in pods(store):
        set_metrics(store, p.metadata.name, 50)  # exactly on target
    hpa = store.get("horizontalpodautoscalers", "default", "hpa")
    hpa.spec.min_replicas = 5
    store.update("horizontalpodautoscalers", hpa)
    hpa_ctrl.sync_all()
    assert store.get("deployments", "default", "d1").spec.replicas == 5


def test_partial_samples_do_not_overscale():
    """Missing-metrics pods count as idle for a scale-up decision
    (replica_calculator.go rebalance): 2 measured pods at 100% among 4
    must not extrapolate 100% to the whole fleet."""
    store, dep_ctrl, rs_ctrl, hpa_ctrl, now = world(replicas=4)
    ps = pods(store)
    assert len(ps) == 4
    for p in ps[:2]:
        set_metrics(store, p.metadata.name, 100)  # 2 sampled at 200% of target
    # rebalanced: (100+100)/(4*100) = 50% == target -> direction flips -> hold
    hpa_ctrl.sync_all()
    assert store.get("deployments", "default", "d1").spec.replicas == 4


def test_in_manager_roster():
    from kubernetes_tpu.controllers.manager import DEFAULT_CONTROLLERS

    assert HorizontalPodAutoscalerController in DEFAULT_CONTROLLERS


def test_hpa_scales_custom_resource():
    """An HPA targeting a CRD kind that declares subresources.scale:
    replicas are read/written through the CRD's dotted paths and pods
    are selected via the labelSelectorPath selector string (the
    reference HPA's polymorphic scale-client path)."""
    from kubernetes_tpu.api import scheme

    store = ObjectStore()
    crd = api.CustomResourceDefinition(
        metadata=api.ObjectMeta(name="tpujobs.ml.example.com"),
        spec=api.CustomResourceDefinitionSpec(
            group="ml.example.com", version="v1",
            names=api.CustomResourceNames(kind="TPUJob", plural="tpujobs",
                                          singular="tpujob"),
            subresources=api.CustomResourceSubresources(
                status=True,
                scale=api.CustomResourceSubresourceScale(
                    spec_replicas_path=".spec.replicas",
                    status_replicas_path=".status.readyReplicas",
                    label_selector_path=".spec.selector"))))
    store.create("customresourcedefinitions", crd)
    scheme.register_dynamic(crd)
    try:
        now = [1000.0]
        hpa_ctrl = HorizontalPodAutoscalerController(store,
                                                     clock=lambda: now[0])
        store.create("tpujobs", api.CustomObject(
            kind="TPUJob", api_version="ml.example.com/v1",
            metadata=api.ObjectMeta(name="train"),
            spec={"replicas": 2, "selector": "app=train"}))
        # the "operator" runs 2 worker pods wearing the selector labels
        for i in range(2):
            store.create("pods", api.Pod(
                metadata=api.ObjectMeta(name=f"train-{i}",
                                        labels={"app": "train"}),
                spec=api.PodSpec(containers=[api.Container(
                    resources=api.ResourceRequirements(
                        requests=api.resource_list(cpu="100m")))]),
                status=api.PodStatus(phase="Running",
                                     conditions=[("Ready", "True")])))
            set_metrics(store, f"train-{i}", 100)  # 100% of request
        hpa = mkhpa(target="train", cpu=50)
        hpa.spec.scale_target_ref = api.CrossVersionObjectReference(
            kind="TPUJob", name="train")
        store.create("horizontalpodautoscalers", hpa)
        hpa_ctrl.sync_all()
        job = store.get("tpujobs", "default", "train")
        # 100% util vs 50% target -> double
        assert job.spec["replicas"] == 4
        got = store.get("horizontalpodautoscalers", "default", "hpa")
        assert got.status.desired_replicas == 4
    finally:
        scheme.unregister("TPUJob")
