"""The debugging/interaction plane end to end: real exec against the
fake runtime's container state, attach following live output,
port-forward moving real TCP bytes to a hollow pod's backend, and
kubectl patch/annotate/edit/cp round-trips.

Reference: pkg/kubelet/server/server.go:325 getExec, :640 getAttach,
:751 getPortForward; pkg/kubectl/cmd/{patch,annotate,cp,attach,
portforward}.go, editor/editoptions.go. Round-4 verdict item 6's 'done'
bar: patch round-trips through merge-patch, attach streams follow-on
log output, port-forward proxies a TCP echo to a hollow pod."""

import io
import json
import os
import socket
import socketserver
import threading
import time

from kubernetes_tpu.api import types as api
from kubernetes_tpu.cli import kubectl
from kubernetes_tpu.kubemark.hollow import HollowNode
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.server import APIServer

from helpers import make_pod


class _Fixture:
    def setup_method(self):
        self.store = ObjectStore()
        self.srv = APIServer(self.store).start()
        self.node = HollowNode(self.store, "n1", serve=True)
        self.pod = make_pod("web", cpu="100m", node_name="n1")
        self.pod.spec.containers[0].env = {"APP_MODE": "prod",
                                           "REGION": "us-x1"}
        self.store.create("pods", self.pod)
        self.node.kubelet.sync_once()
        self.cname = self.pod.spec.containers[0].name

    def teardown_method(self):
        self.node.stop()
        self.srv.stop()

    def kubectl(self, *argv):
        out = io.StringIO()
        rc = kubectl.main(["--server", self.srv.url, *argv], out=out)
        return rc, out.getvalue()


class TestRealExec(_Fixture):
    def test_exec_operates_on_container_state(self):
        # env comes from the pod spec, through the kubelet, into the
        # runtime — not a canned reply
        rc, out = self.kubectl("exec", "web", "env")
        assert rc == 0 and "APP_MODE=prod" in out and "REGION=us-x1" in out
        # write a file via sh -c redirection, read it back with cat
        rc, _ = self.kubectl("exec", "web", "--", "sh", "-c",
                             "echo hello-state > /etc/conf")
        assert rc == 0
        rc, out = self.kubectl("exec", "web", "cat", "/etc/conf")
        assert rc == 0 and out.strip() == "hello-state"
        rc, out = self.kubectl("exec", "web", "ls", "/etc")
        assert rc == 0 and "conf" in out
        # failures carry real exit codes
        rc, out = self.kubectl("exec", "web", "cat", "/no/such")
        assert rc == 1 and "No such file" in out
        rc, _ = self.kubectl("exec", "web", "definitely-not-a-command")
        assert rc == 127

    def test_exec_refused_for_non_running(self):
        floating = make_pod("floating", cpu="100m", node_name="n1")
        self.store.create("pods", floating)  # never synced -> no container
        rc, out = self.kubectl("exec", "floating", "echo", "hi")
        assert rc == 126


class TestAttach(_Fixture):
    def test_attach_streams_follow_on_output(self):
        uid = self.pod.metadata.uid

        def writer():
            for i in range(3):
                time.sleep(0.15)
                self.node.runtime.append_log(uid, self.cname,
                                             f"tick-{i}")

        t = threading.Thread(target=writer)
        t.start()
        # the attach long-poll must pick up lines appended AFTER it arms
        rc, out = self.kubectl("attach", "web", "--follow-rounds", "4",
                               "--wait", "1")
        t.join()
        assert rc == 0
        for i in range(3):
            assert f"tick-{i}" in out, out

    def test_logs_follow_rides_the_same_stream(self):
        uid = self.pod.metadata.uid
        self.node.runtime.append_log(uid, self.cname, "before")

        def writer():
            time.sleep(0.15)
            self.node.runtime.append_log(uid, self.cname, "after")

        t = threading.Thread(target=writer)
        t.start()
        rc, out = self.kubectl("logs", "web", "-f",
                               "--follow-rounds", "3", "--wait", "1")
        t.join()
        assert rc == 0
        # history AND the line appended after the follow armed
        assert "before" in out and "after" in out, out


class TestPortForward(_Fixture):
    def test_tcp_echo_through_the_full_chain(self):
        """client socket -> kubectl local listener -> kubelet relay ->
        pod backend (a real echo server): actual bytes, both ways."""

        class Echo(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    data = self.request.recv(4096)
                    if not data:
                        break
                    self.request.sendall(b"echo:" + data)

        backend = socketserver.ThreadingTCPServer(("127.0.0.1", 0), Echo)
        backend.daemon_threads = True
        threading.Thread(target=backend.serve_forever, daemon=True).start()
        try:
            self.node.runtime.register_pod_server(
                self.pod.metadata.uid, 8080, backend.server_address[1])
            out = io.StringIO()
            rc = kubectl.main(["--server", self.srv.url, "port-forward",
                               "web", "8080", "--once"], out=out)
            assert rc == 0
            lport = int(out.getvalue().split("127.0.0.1:")[1].split(" ")[0])
            with socket.create_connection(("127.0.0.1", lport),
                                          timeout=5) as s:
                s.sendall(b"ping")
                got = s.recv(4096)
            assert got == b"echo:ping", got
        finally:
            backend.shutdown()
            backend.server_close()

    def test_unbound_port_is_400(self):
        rc, out = self.kubectl("port-forward", "web", "9999", "--once")
        assert rc == 1


class TestKubectlPatchAnnotateEditCp(_Fixture):
    def test_patch_round_trips_merge_patch(self):
        rc, out = self.kubectl("patch", "pods", "web", "-p",
                               json.dumps({"metadata": {"labels":
                                           {"tier": "gold"}}}))
        assert rc == 0 and "patched" in out
        assert self.store.get("pods", "default", "web") \
                   .metadata.labels["tier"] == "gold"

    def test_annotate_set_and_remove(self):
        rc, _ = self.kubectl("annotate", "pods", "web", "team=infra")
        assert rc == 0
        pod = self.store.get("pods", "default", "web")
        assert pod.metadata.annotations["team"] == "infra"
        rc, _ = self.kubectl("annotate", "pods", "web", "team-")
        assert rc == 0
        pod = self.store.get("pods", "default", "web")
        assert "team" not in (pod.metadata.annotations or {})

    def test_edit_applies_editor_changes(self, tmp_path):
        script = tmp_path / "fake-editor.sh"
        script.write_text("#!/bin/sh\n"
                          "sed -i 's/restartPolicy: Always/"
                          "restartPolicy: Never/' \"$1\"\n")
        script.chmod(0o755)
        old = os.environ.get("KUBE_EDITOR")
        os.environ["KUBE_EDITOR"] = str(script)
        try:
            rc, out = self.kubectl("edit", "pods", "web")
        finally:
            if old is None:
                os.environ.pop("KUBE_EDITOR", None)
            else:
                os.environ["KUBE_EDITOR"] = old
        assert rc == 0 and "edited" in out
        assert self.store.get("pods", "default", "web") \
                   .spec.restart_policy == "Never"

    def test_cp_upload_and_download(self, tmp_path):
        src = tmp_path / "config.ini"
        src.write_text("mode=fast\n")
        rc, _ = self.kubectl("cp", str(src), "web:/app/config.ini")
        assert rc == 0
        # the uploaded file is REAL container state: exec sees it
        rc, out = self.kubectl("exec", "web", "cat", "/app/config.ini")
        assert rc == 0 and out.strip() == "mode=fast"
        dst = tmp_path / "out.ini"
        rc, _ = self.kubectl("cp", "web:/app/config.ini", str(dst))
        assert rc == 0
        assert dst.read_text() == "mode=fast\n"


class TestStaticPodInteraction(_Fixture):
    def test_logs_and_exec_reach_static_pods(self, tmp_path):
        """The mirror pod's runtime uid translation: logs/exec against
        a static pod must hit the containers running under the
        file-derived static uid (pod/mirror_client.go TranslatePodUID)."""
        (tmp_path / "etcd.yaml").write_text("""
apiVersion: v1
kind: Pod
metadata:
  name: etcd
spec:
  containers:
  - name: etcd
    image: etcd:3.2
""")
        self.node.kubelet.manifest_dir = str(tmp_path)
        self.node.kubelet.sync_once()
        uid = list(self.node.kubelet._static_by_uid)[0]
        self.node.runtime.append_log(uid, "etcd", "serving on 2379")
        rc, out = self.kubectl("logs", "etcd-n1")
        assert rc == 0 and "serving on 2379" in out, out
        rc, out = self.kubectl("exec", "etcd-n1", "env")
        assert rc == 0

    def test_logs_tail_with_follow(self):
        uid = self.pod.metadata.uid
        for i in range(10):
            self.node.runtime.append_log(uid, self.cname, f"old-{i}")
        rc, out = self.kubectl("logs", "web", "-f", "--tail", "2",
                               "--wait", "0.1")
        assert rc == 0
        assert "old-9" in out and "old-8" in out and "old-0" not in out, out
