"""Inter-pod affinity/anti-affinity: device-kernel parity vs golden
semantics on randomized worlds, plus behavioral e2e (anti-affinity
spreading, affinity co-location, wave-internal visibility, symmetry).

Reference behaviors under test:
  pkg/scheduler/algorithm/predicates/predicates.go:1115
    InterPodAffinityMatches (metadata path)
  pkg/scheduler/algorithm/priorities/interpod_affinity.go:118
    CalculateInterPodAffinityPriority
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.api import labels as lbl
from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import encoding as enc
from kubernetes_tpu.ops.kernel import Weights, schedule_wave
from kubernetes_tpu.plugins import golden
from kubernetes_tpu.runtime.store import ObjectStore
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.featurize import PodFeaturizer
from kubernetes_tpu.state.snapshot import Snapshot

from helpers import make_node, make_pod

HOSTNAME = "kubernetes.io/hostname"
ZONE = "failure-domain.beta.kubernetes.io/zone"


def aff_term(match: dict, tk: str, namespaces=()) -> api.PodAffinityTerm:
    return api.PodAffinityTerm(
        label_selector=api.LabelSelector(match_labels=dict(match)),
        namespaces=list(namespaces), topology_key=tk)


def pod_affinity(required=(), preferred=()) -> api.Affinity:
    return api.Affinity(pod_affinity=api.PodAffinity(
        required=list(required),
        preferred=[api.WeightedPodAffinityTerm(weight=w, pod_affinity_term=t)
                   for w, t in preferred]))


def pod_anti_affinity(required=(), preferred=()) -> api.Affinity:
    return api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
        required=list(required),
        preferred=[api.WeightedPodAffinityTerm(weight=w, pod_affinity_term=t)
                   for w, t in preferred]))


def build(nodes, existing):
    cache, snap = SchedulerCache(), Snapshot()
    for n in nodes:
        cache.add_node(n)
        snap.set_node(cache.node_infos[n.name])
    for p in existing:
        cache.add_pod(p)
        snap.refresh_node_resources(cache.node_infos[p.spec.node_name])
        snap.add_pod(p)
    return cache, snap


def run_wave(snap, pods, weights=Weights()):
    feat = PodFeaturizer(snap)
    pb = feat.featurize(pods)
    nt, pm, tt = snap.to_device()
    extra = np.ones((pb.req.shape[0], snap.caps.N), bool)
    return schedule_wave(nt, pm, tt, pb, extra, 0, weights=weights,
                         num_zones=snap.caps.Z,
                         num_label_values=snap.num_label_values, has_ipa=True)


# --- behavioral e2e ----------------------------------------------------------


def test_required_anti_affinity_spreads_one_per_node():
    """The scheduler_perf anti-affinity benchmark shape: each pod requires
    anti-affinity to its own labels on hostname — exactly one per node,
    including wave-internal visibility."""
    nodes = [make_node(f"n{i}", labels={HOSTNAME: f"n{i}"}) for i in range(4)]
    cache, snap = build(nodes, [])
    anti = pod_anti_affinity(required=[aff_term({"app": "w"}, HOSTNAME)])
    pods = [make_pod(f"p{i}", labels={"app": "w"}, affinity=anti)
            for i in range(6)]
    res = run_wave(snap, pods)
    chosen = np.asarray(res.chosen)[:6]
    placed = [c for c in chosen if c >= 0]
    assert len(placed) == 4, f"expected 4 placements, got {chosen}"
    assert len(set(placed)) == 4  # all distinct nodes
    q = enc.PRED_IDX["MatchInterPodAffinity"]
    fail = np.asarray(res.fail_counts)
    for i, c in enumerate(chosen):
        if c < 0:
            assert fail[q, i] == 4  # blocked on every node by wave placements


def test_required_affinity_colocates_by_zone():
    nodes = [make_node(f"n{i}", labels={HOSTNAME: f"n{i}", ZONE: f"z{i // 2}"})
             for i in range(4)]
    existing = [make_pod("db", labels={"app": "db"}, node_name="n3")]
    cache, snap = build(nodes, existing)
    aff = pod_affinity(required=[aff_term({"app": "db"}, ZONE)])
    res = run_wave(snap, [make_pod("web", labels={"app": "web"}, affinity=aff)])
    chosen = int(res.chosen[0])
    # db is on n3 (zone z1) -> web must land on n2 or n3
    assert snap.node_names[chosen] in ("n2", "n3")


def test_affinity_bootstrap_rule_first_pod_of_group():
    """A self-affine pod with no matching pods anywhere may schedule
    (predicates.go:1409); a non-self-matching one may not."""
    nodes = [make_node("n0", labels={HOSTNAME: "n0"})]
    cache, snap = build(nodes, [])
    self_aff = pod_affinity(required=[aff_term({"app": "w"}, HOSTNAME)])
    res = run_wave(snap, [make_pod("first", labels={"app": "w"}, affinity=self_aff)])
    assert int(res.chosen[0]) == 0
    other_aff = pod_affinity(required=[aff_term({"app": "missing"}, HOSTNAME)])
    res2 = run_wave(snap, [make_pod("stuck", labels={"app": "w"}, affinity=other_aff)])
    assert int(res2.chosen[0]) == -1


def test_bootstrap_rule_defeated_by_wave_placement_on_unlabeled_node():
    """The matchingPods existence check is topology-independent
    (predicates.go:1410): once a wave sibling matching the props is placed
    anywhere — even on a node without the topology key — the bootstrap
    exception no longer applies."""
    nodes = [make_node("bare"),  # no zone label
             make_node("zoned", labels={ZONE: "z0"})]
    cache, snap = build(nodes, [])
    plain = make_pod("plain", labels={"app": "w"}, priority=100,
                     node_selector={})  # no affinity; placed first
    aff = pod_affinity(required=[aff_term({"app": "w"}, ZONE)])
    follower = make_pod("follower", labels={"app": "w"}, affinity=aff)
    res = run_wave(snap, [plain, follower])
    first = snap.node_names[int(res.chosen[0])]
    second = int(res.chosen[1])
    if first == "bare":
        # a matching pod exists on a zoneless node: no topology anchor, and
        # bootstrap is off -> follower unschedulable (reference behavior)
        assert second == -1
    else:
        # plain landed on the zoned node: follower must co-locate in z0
        assert snap.node_names[second] == "zoned"


def test_existing_pod_anti_affinity_symmetry():
    """An existing pod's required anti-affinity blocks matching incomers in
    its topology domain (satisfiesExistingPodsAntiAffinity)."""
    nodes = [make_node(f"n{i}", labels={HOSTNAME: f"n{i}", ZONE: "z0" if i < 2 else "z1"})
             for i in range(4)]
    guard = make_pod("guard", labels={"app": "guard"}, node_name="n0",
                     affinity=pod_anti_affinity(
                         required=[aff_term({"app": "noisy"}, ZONE)]))
    cache, snap = build(nodes, [guard])
    res = run_wave(snap, [make_pod("noisy1", labels={"app": "noisy"})])
    # z0 (n0, n1) is blocked by guard's anti-affinity
    assert snap.node_names[int(res.chosen[0])] in ("n2", "n3")


def test_wave_internal_symmetry():
    """A pod placed earlier in the wave carrying anti-affinity blocks a
    later matching pod in the same wave."""
    nodes = [make_node(f"n{i}", labels={HOSTNAME: f"n{i}", ZONE: "z0"})
             for i in range(2)]
    cache, snap = build(nodes, [])
    guard = make_pod("guard", labels={"app": "guard"},
                     affinity=pod_anti_affinity(
                         required=[aff_term({"app": "noisy"}, ZONE)]),
                     priority=100)
    noisy = make_pod("noisy", labels={"app": "noisy"})
    res = run_wave(snap, [guard, noisy])
    assert int(res.chosen[0]) >= 0
    assert int(res.chosen[1]) == -1  # whole zone blocked by in-wave guard


def test_preferred_anti_affinity_steers_away():
    nodes = [make_node(f"n{i}", labels={HOSTNAME: f"n{i}"}) for i in range(3)]
    existing = [make_pod("e0", labels={"app": "w"}, node_name="n1")]
    cache, snap = build(nodes, existing)
    pref = pod_anti_affinity(preferred=[(100, aff_term({"app": "w"}, HOSTNAME))])
    res = run_wave(snap, [make_pod("p", labels={"app": "w"}, affinity=pref)],
                   weights=Weights(least_requested=0.0, balanced=0.0))
    assert snap.node_names[int(res.chosen[0])] != "n1"


def test_namespace_scoping():
    """Affinity terms default to the owner pod's namespace."""
    nodes = [make_node(f"n{i}", labels={HOSTNAME: f"n{i}"}) for i in range(2)]
    existing = [make_pod("other-ns", labels={"app": "db"}, node_name="n0",
                         namespace="prod")]
    cache, snap = build(nodes, existing)
    aff = pod_affinity(required=[aff_term({"app": "db"}, HOSTNAME)])
    # same selector, default ns -> no match (existing pod is in prod)
    res = run_wave(snap, [make_pod("p", namespace="default", affinity=aff,
                                   labels={"app": "x"})])
    assert int(res.chosen[0]) == -1
    # explicit namespaces=['prod'] -> colocated on n0
    aff2 = pod_affinity(required=[aff_term({"app": "db"}, HOSTNAME,
                                           namespaces=["prod"])])
    res2 = run_wave(snap, [make_pod("p2", namespace="default", affinity=aff2,
                                    labels={"app": "x"})])
    assert snap.node_names[int(res2.chosen[0])] == "n0"


# --- randomized parity vs golden ---------------------------------------------

APPS = ["web", "db", "cache", "batch"]


def random_affinity(rng):
    terms_req, terms_pref = [], []
    tk = rng.choice([HOSTNAME, ZONE])
    if rng.random() < 0.7:
        terms_req = [aff_term({"app": rng.choice(APPS)}, tk)]
    if rng.random() < 0.4:
        terms_pref = [(rng.randint(1, 100),
                       aff_term({"app": rng.choice(APPS)},
                                rng.choice([HOSTNAME, ZONE])))]
    kind = rng.random()
    if kind < 0.45:
        return pod_affinity(required=terms_req, preferred=terms_pref)
    if kind < 0.9:
        return pod_anti_affinity(required=terms_req, preferred=terms_pref)
    # both sides
    a = pod_affinity(required=terms_req)
    b = pod_anti_affinity(
        required=[aff_term({"app": rng.choice(APPS)}, rng.choice([HOSTNAME, ZONE]))])
    return api.Affinity(pod_affinity=a.pod_affinity,
                        pod_anti_affinity=b.pod_anti_affinity)


def random_ipa_world(rng, n_nodes=10, n_existing=18, n_pods=10):
    nodes = [make_node(f"n{i}", labels={HOSTNAME: f"n{i}",
                                        ZONE: f"z{i % 3}"})
             for i in range(n_nodes)]
    existing = []
    for i in range(n_existing):
        existing.append(make_pod(
            f"e{i}", labels={"app": rng.choice(APPS)},
            namespace=rng.choice(["default", "prod"]),
            node_name=f"n{rng.randrange(n_nodes)}",
            affinity=random_affinity(rng) if rng.random() < 0.5 else None))
    pods = []
    for i in range(n_pods):
        pods.append(make_pod(
            f"p{i}", labels={"app": rng.choice(APPS)},
            namespace=rng.choice(["default", "prod"]),
            affinity=random_affinity(rng) if rng.random() < 0.8 else None))
    return nodes, existing, pods


@pytest.mark.parametrize("seed", range(8))
def test_interpod_predicate_parity(seed):
    rng = random.Random(seed + 1000)
    nodes, existing, pods = random_ipa_world(rng)
    cache, snap = build(nodes, existing)
    feat = PodFeaturizer(snap)
    pb = feat.featurize(pods)
    nt, pm, tt = snap.to_device()
    from kubernetes_tpu.ops.affinity import incoming_statics

    ipa = incoming_statics(nt, pm, tt, pb, snap.num_label_values, 1.0)
    view = golden.ClusterView(cache.node_infos)
    sym = np.asarray(ipa.sym_blocked)
    ok_aff = np.asarray(ipa.ok_aff)
    any_aff = np.asarray(ipa.any_aff)
    blocked = np.asarray(ipa.blocked_anti)
    for pi, pod in enumerate(pods):
        for ni_idx, node in enumerate(nodes):
            ninfo = cache.node_infos[node.name]
            gold, _ = golden.interpod_affinity_predicate(pod, ninfo, view)
            # reconstruct device verdict from statics (no wave interaction
            # here: statics only)
            ra_terms = golden._affinity_terms(pod)
            dev_ok_aff = True
            if ra_terms:
                dev_ok_aff = bool(ok_aff[pi, ni_idx]) or (
                    not any_aff[pi]
                    and golden._pod_matches_all_term_props(pod, pod, ra_terms))
            rn_terms = golden._anti_affinity_terms(pod)
            dev = (not sym[pi, ni_idx]) and dev_ok_aff and not (
                bool(rn_terms) and blocked[pi, ni_idx])
            assert dev == gold, (
                f"seed={seed}: pod {pod.name} node {node.name} "
                f"device={dev} golden={gold} (sym={sym[pi, ni_idx]} "
                f"okaff={ok_aff[pi, ni_idx]} anyaff={any_aff[pi]} "
                f"blocked={blocked[pi, ni_idx]})")


@pytest.mark.parametrize("seed", range(6))
def test_interpod_priority_parity(seed):
    rng = random.Random(seed + 2000)
    nodes, existing, pods = random_ipa_world(rng)
    cache, snap = build(nodes, existing)
    feat = PodFeaturizer(snap)
    pb = feat.featurize(pods)
    nt, pm, tt = snap.to_device()
    from kubernetes_tpu.ops.affinity import incoming_statics

    hard_w = rng.choice([0, 1, 10])
    ipa = incoming_statics(nt, pm, tt, pb, snap.num_label_values, float(hard_w))
    counts = np.asarray(ipa.counts)
    view = golden.ClusterView(cache.node_infos)
    for pi, pod in enumerate(pods):
        # golden counts (pre-normalization) via the reference algorithm over
        # all nodes as "feasible"
        feasible = [cache.node_infos[n.name] for n in nodes]
        gold_scores = golden.interpod_affinity_priority(pod, feasible, view,
                                                        hard_weight=hard_w)
        # normalize device counts the same way to compare end results
        c = counts[pi, : len(nodes)]
        mx, mn = max(c.max(), 0.0), min(c.min(), 0.0)
        for ni_idx, node in enumerate(nodes):
            dev = int(10.0 * (c[ni_idx] - mn) / (mx - mn)) if mx != mn else 0
            assert dev == gold_scores[node.name], (
                f"seed={seed}: pod {pod.name} node {node.name} "
                f"device={dev} ({c[ni_idx]}) golden={gold_scores[node.name]}")


# --- full scheduler path ------------------------------------------------------


def test_scheduler_e2e_anti_affinity():
    store = ObjectStore()
    sched = Scheduler(store, wave_size=8)
    for i in range(4):
        store.create("nodes", make_node(f"n{i}", labels={HOSTNAME: f"n{i}"}))
    anti = pod_anti_affinity(required=[aff_term({"app": "s"}, HOSTNAME)])
    for i in range(4):
        store.create("pods", make_pod(f"s{i}", labels={"app": "s"}, affinity=anti))
    placed = sched.schedule_pending(max_waves=4)
    assert placed == 4
    hosts = {store.get("pods", "default", f"s{i}").spec.node_name for i in range(4)}
    assert len(hosts) == 4


def test_scheduler_host_path_multi_topology_key():
    """Required terms with two distinct topology keys route through the
    exact golden host path."""
    store = ObjectStore()
    sched = Scheduler(store, wave_size=8)
    for i in range(4):
        store.create("nodes", make_node(
            f"n{i}", labels={HOSTNAME: f"n{i}", ZONE: f"z{i // 2}"}))
    store.create("pods", make_pod("db", labels={"app": "db"}, node_name="n2"))
    aff = api.Affinity(pod_affinity=api.PodAffinity(required=[
        aff_term({"app": "db"}, ZONE),
        aff_term({"app": "db"}, HOSTNAME),
    ]))
    store.create("pods", make_pod("web", labels={"app": "web"}, affinity=aff))
    placed = sched.schedule_pending(max_waves=4)
    assert placed == 1
    assert store.get("pods", "default", "web").spec.node_name == "n2"
