"""Durable bind-intent journal (state/journal.py): the
disconnected-mode write-ahead log.

Tier-1 coverage for the format and the two crash-hardening behaviors
the outage plane leans on: size-cap rotation (one `.1` generation,
replay streams both segments so rotation never loses unresolved
intents) and torn-line tolerance (a crash can tear the final line
mid-write; replay skips it and the next append repairs the tail so new
records stay parseable). The `journal.append` fault point is exercised
in both modes: raise models a full disk at the worst moment, drop
models a write the OS acknowledged but never persisted.
"""

import json
import os

import pytest

from kubernetes_tpu.state import journal as journal_mod
from kubernetes_tpu.state.journal import (CONFIRMED, GONE, ORPHANED,
                                          BindJournal)
from kubernetes_tpu.utils import faultpoints

from helpers import make_pod


@pytest.fixture(autouse=True)
def _clean_faults():
    faultpoints.reset()
    yield
    faultpoints.reset()


def _journal(tmp_path, **kw):
    return BindJournal(str(tmp_path / "bind.journal"), **kw)


class TestFormat:
    def test_append_intent_record_shape(self, tmp_path):
        j = _journal(tmp_path, clock=lambda: 123.456)
        pod = make_pod("web-1")
        seq = j.append_intent(pod, "node-a")
        assert seq == 0
        lines = open(j.path).read().splitlines()
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec == {"v": 1, "k": "intent", "seq": 0, "uid": pod.uid,
                       "ns": "default", "name": "web-1",
                       "node": "node-a", "ts": 123.456}

    def test_seq_monotonic_and_resolve_record(self, tmp_path):
        j = _journal(tmp_path)
        s0 = j.append_intent(make_pod("a"), "n0")
        s1 = j.append_intent(make_pod("b"), "n1")
        assert (s0, s1) == (0, 1)
        j.resolve(s0, CONFIRMED)
        recs = [json.loads(l) for l in open(j.path).read().splitlines()]
        assert recs[-1] == {"v": 1, "k": "resolved", "seq": 0,
                            "outcome": "confirmed"}

    def test_unresolved_is_set_difference_in_seq_order(self, tmp_path):
        j = _journal(tmp_path)
        seqs = [j.append_intent(make_pod(f"p{i}"), f"n{i}")
                for i in range(4)]
        j.resolve(seqs[1], GONE)
        j.resolve(seqs[3], ORPHANED)
        left = j.unresolved()
        assert [r["seq"] for r in left] == [seqs[0], seqs[2]]
        assert [r["name"] for r in left] == ["p0", "p2"]

    def test_fresh_path_has_no_unresolved(self, tmp_path):
        j = _journal(tmp_path)
        assert j.unresolved() == []
        assert j.stats()["unresolved"] == 0

    def test_seq_resumes_past_prior_generation(self, tmp_path):
        j = _journal(tmp_path)
        j.append_intent(make_pod("a"), "n0")
        j.append_intent(make_pod("b"), "n1")
        # a restarted process must never reuse a live seq — resolve
        # records are matched by seq across generations
        j2 = _journal(tmp_path)
        assert j2.append_intent(make_pod("c"), "n2") == 2


class TestRotation:
    def test_rotation_keeps_unresolved_across_segments(self, tmp_path):
        # one generation (`.1`) is kept, so size the cap for exactly
        # ONE rotation: 2.5 lines — the 3rd intent rotates the first
        # two out to `.1`; replay must still see all four
        probe = BindJournal(str(tmp_path / "probe.journal"),
                            clock=lambda: 100.0)
        probe.append_intent(make_pod("rot0"), "n0")
        line = os.path.getsize(probe.path)
        j = BindJournal(str(tmp_path / "bind.journal"),
                        max_bytes=int(2.5 * line), clock=lambda: 100.0)
        seqs = [j.append_intent(make_pod(f"rot{i}"), f"n{i}")
                for i in range(4)]
        assert j.rotations == 1
        assert os.path.exists(j.path + ".1")
        assert [r["seq"] for r in j.unresolved()] == seqs
        # resolving an intent that lives in the OLD segment works: the
        # resolved record lands in the new one, matched by seq
        j.resolve(seqs[0], CONFIRMED)
        assert seqs[0] not in {r["seq"] for r in j.unresolved()}

    def test_default_cap_comes_from_module(self, tmp_path):
        assert _journal(tmp_path, max_bytes=-1).max_bytes == \
            journal_mod.JOURNAL_MAX_BYTES


class TestTornLines:
    def test_torn_tail_skipped_not_fatal(self, tmp_path):
        j = _journal(tmp_path)
        j.append_intent(make_pod("ok"), "n0")
        # crash mid-write: the final line is half a record
        with open(j.path, "ab") as f:
            f.write(b'{"v":1,"k":"intent","seq":1,"uid":"torn')
        left = j.unresolved()
        assert [r["name"] for r in left] == ["ok"]
        assert j.skipped_lines == 1

    def test_append_after_torn_tail_repairs_line_boundary(self, tmp_path):
        j = _journal(tmp_path)
        j.append_intent(make_pod("ok"), "n0")
        with open(j.path, "ab") as f:
            f.write(b'{"v":1,"k":"int')
        # the next append must terminate the torn line first — both the
        # old and the new record stay individually parseable
        j.append_intent(make_pod("after"), "n1")
        names = [r["name"] for r in j.unresolved()]
        assert names == ["ok", "after"]
        assert j.skipped_lines == 1

    def test_garbage_line_in_middle_skipped(self, tmp_path):
        j = _journal(tmp_path)
        j.append_intent(make_pod("a"), "n0")
        with open(j.path, "ab") as f:
            f.write(b"\x00\xff not json at all\n")
        j.append_intent(make_pod("b"), "n1")
        assert [r["name"] for r in j.unresolved()] == ["a", "b"]


class TestFaultPoint:
    def test_raise_mode_propagates_to_caller(self, tmp_path):
        # full disk at the worst moment: append_intent raises, nothing
        # is written, and the caller decides about a memory-only spool
        j = _journal(tmp_path)
        faultpoints.activate("journal.append", "raise", times=1)
        with pytest.raises(faultpoints.FaultInjected):
            j.append_intent(make_pod("a"), "n0")
        assert not os.path.exists(j.path)
        # once the disk "recovers" the journal works again
        j.append_intent(make_pod("b"), "n1")
        assert [r["name"] for r in j.unresolved()] == ["b"]

    def test_drop_mode_loses_exactly_the_acked_write(self, tmp_path):
        j = _journal(tmp_path)
        j.append_intent(make_pod("kept"), "n0")
        faultpoints.activate("journal.append", "drop", times=1)
        j.append_intent(make_pod("lost"), "n1")  # OS lied: no error, no data
        assert [r["name"] for r in j.unresolved()] == ["kept"]

    def test_dropped_resolve_means_reverify_not_corruption(self, tmp_path):
        j = _journal(tmp_path)
        s = j.append_intent(make_pod("a"), "n0")
        faultpoints.activate("journal.append", "drop", times=1)
        j.resolve(s, CONFIRMED)  # the resolved record never lands
        # the intent stays unresolved — replay re-verifies it against
        # truth, which is idempotent by design
        assert [r["seq"] for r in j.unresolved()] == [s]
