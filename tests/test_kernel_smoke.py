"""End-to-end smoke test of the tensor path: cache -> snapshot ->
featurize -> schedule_wave."""

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import encoding as enc
from kubernetes_tpu.ops.kernel import Weights, schedule_wave
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.featurize import PodFeaturizer
from kubernetes_tpu.state.snapshot import Snapshot

from helpers import make_node, make_pod


def build_world(nodes, scheduled_pods=()):
    cache = SchedulerCache()
    snap = Snapshot()
    for n in nodes:
        cache.add_node(n)
        snap.set_node(cache.node_infos[n.name])
    for p in scheduled_pods:
        cache.add_pod(p)
        snap.refresh_node_resources(cache.node_infos[p.spec.node_name])
        snap.add_pod(p)
    return cache, snap


def run_wave(snap, pods, weights=Weights(), feat=None, has_ipa=False):
    feat = feat or PodFeaturizer(snap)
    pb = feat.featurize(pods)
    nt, pm, tt = snap.to_device()
    extra = np.ones((pb.req.shape[0], snap.caps.N), bool)
    res = schedule_wave(nt, pm, tt, pb, extra, 0, weights=weights,
                        num_zones=snap.caps.Z,
                        num_label_values=snap.num_label_values,
                        has_ipa=has_ipa or snap.has_affinity_terms)
    return res


def test_basic_placement():
    nodes = [make_node(f"n{i}", cpu="4", memory="8Gi") for i in range(4)]
    cache, snap = build_world(nodes)
    pods = [make_pod(f"p{i}", cpu="1", memory="1Gi") for i in range(3)]
    res = run_wave(snap, pods)
    chosen = np.asarray(res.chosen)[:3]
    assert (chosen >= 0).all()
    # spreading is off (no owners); least-requested should spread by usage:
    # three pods land on three distinct empty nodes via round-robin ties
    assert len(set(chosen.tolist())) == 3


def test_resource_exhaustion_within_wave():
    nodes = [make_node("n0", cpu="2", memory="4Gi", pods=10)]
    cache, snap = build_world(nodes)
    pods = [make_pod(f"p{i}", cpu="1") for i in range(3)]
    res = run_wave(snap, pods)
    chosen = np.asarray(res.chosen)[:3]
    # only 2 cpus: third pod must fail even though the wave started feasible
    assert chosen[0] == 0 and chosen[1] == 0
    assert chosen[2] == -1
    q = enc.PRED_IDX["PodFitsResources"]
    assert np.asarray(res.fail_counts)[q, 2] == 1


def test_node_selector_and_affinity():
    nodes = [
        make_node("small", labels={"size": "s"}),
        make_node("large", labels={"size": "l"}),
    ]
    cache, snap = build_world(nodes)
    p = make_pod("p", node_selector={"size": "l"})
    res = run_wave(snap, [p])
    assert snap.node_names[int(res.chosen[0])] == "large"
    # unmatched selector -> unschedulable, charged to MatchNodeSelector
    p2 = make_pod("p2", node_selector={"size": "xl"})
    res2 = run_wave(snap, [p2])
    assert int(res2.chosen[0]) == -1
    q = enc.PRED_IDX["MatchNodeSelector"]
    assert np.asarray(res2.fail_counts)[q, 0] == 2


def test_taints_and_tolerations():
    nodes = [
        make_node("tainted", taints=[api.Taint("dedicated", "gpu", api.NO_SCHEDULE)]),
        make_node("open"),
    ]
    cache, snap = build_world(nodes)
    res = run_wave(snap, [make_pod("p")])
    assert snap.node_names[int(res.chosen[0])] == "open"
    tol = api.Toleration(key="dedicated", operator="Equal", value="gpu",
                         effect=api.NO_SCHEDULE)
    res2 = run_wave(snap, [make_pod("p2", tolerations=[tol])])
    assert int(res2.chosen[0]) >= 0  # both feasible now


def test_unschedulable_and_not_ready_nodes():
    nodes = [
        make_node("cordoned", unschedulable=True),
        make_node("down", conditions=[api.NodeCondition(api.NODE_READY, api.COND_FALSE)]),
        make_node("ok"),
    ]
    cache, snap = build_world(nodes)
    res = run_wave(snap, [make_pod("p")])
    assert snap.node_names[int(res.chosen[0])] == "ok"


def test_selector_spreading():
    nodes = [make_node(f"n{i}") for i in range(3)]
    # existing replica of the same group on n0
    existing = make_pod("e0", labels={"app": "web"}, node_name="n0", owner_uid="rs1")
    cache, snap = build_world(nodes, [existing])

    from kubernetes_tpu.api.labels import Selector

    feat = PodFeaturizer(
        snap, group_selectors=lambda pod: [Selector.from_set({"app": "web"})])
    res = run_wave(snap, [make_pod("p", labels={"app": "web"}, owner_uid="rs1")],
                   feat=feat)
    # must avoid n0 (it already holds a replica)
    assert snap.node_names[int(res.chosen[0])] != "n0"
