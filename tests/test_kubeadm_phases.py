"""kubeadm phases architecture, preflight, and upgrade.

Reference: cmd/kubeadm/app/phases/ (init decomposed into re-runnable
phases), cmd/kubeadm/app/preflight/checks.go, cmd/kubeadm/app/cmd/
upgrade/. Round-4 verdict item 10's 'done' bar: kubeadm upgrade on a
running hollow cluster preserves all objects and the scheduler keeps
placing."""

import socket
import time

from kubernetes_tpu.api import types as api
from kubernetes_tpu.cli import kubeadm

from helpers import make_node


class TestPhases:
    def test_phase_list(self, capsys):
        rc = kubeadm.main(["phase", "list"])
        out = capsys.readouterr().out
        assert rc == 0
        for name in ("preflight", "certs", "bootstrap-objects",
                     "upload-config"):
            assert name in out

    def test_single_phase_idempotent_on_durable_store(self, tmp_path):
        d = str(tmp_path / "kv")
        assert kubeadm.main(["phase", "certs", "--data-dir", d]) == 0
        from kubernetes_tpu.runtime.nativestore import NativeObjectStore
        from kubernetes_tpu.server import pki

        st = NativeObjectStore(path=d)
        ca1 = pki.ensure_cluster_ca(st).ca_cert_pem
        st.close()
        # re-running the phase must be a no-op, not a CA rotation
        assert kubeadm.main(["phase", "certs", "--data-dir", d]) == 0
        st = NativeObjectStore(path=d)
        assert pki.ensure_cluster_ca(st).ca_cert_pem == ca1
        st.close()

    def test_unknown_phase_errors(self):
        assert kubeadm.main(["phase", "frobnicate"]) == 1


class TestPreflight:
    def test_occupied_port_fails(self):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        try:
            errors = kubeadm.phase_preflight(port=port)
            assert any("port" in e for e in errors)
        finally:
            s.close()
        assert kubeadm.phase_preflight(port=0) == []

    def test_unwritable_data_dir_fails(self):
        errors = kubeadm.phase_preflight(data_dir="/proc/nope/kv")
        assert any("writable" in e for e in errors)

    def test_init_gates_on_preflight(self, capsys):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        try:
            rc = kubeadm.main(["init", "--port", str(port), "--once"])
        finally:
            s.close()
        assert rc == 1
        assert "preflight" in capsys.readouterr().err


class TestUpgrade:
    def test_live_upgrade_preserves_objects_and_scheduling(self):
        """The 'done' bar: upgrade a RUNNING secure cluster (apiserver
        restart at a new version over the same store+port); every object
        survives, joined clients reconnect, and the scheduler keeps
        placing new pods afterward."""
        cluster = kubeadm.Cluster(secure=True, reconcile_endpoints=False)
        kubeadm.ensure_bootstrap_objects(cluster.store)
        kubeadm.phase_upload_config(cluster.store)
        cluster.start()
        try:
            from kubernetes_tpu.client.reflector import RemoteStore
            from kubernetes_tpu.client.rest import RESTClient
            from kubernetes_tpu.kubemark.hollow import HollowNode

            key, cert, ca_pem = kubeadm.join_with_csr(
                cluster.url, "up-n1", cluster.bootstrap_token)
            rstore = RemoteStore(RESTClient(
                cluster.url, client_cert_pem=cert, client_key_pem=key,
                ca_cert_pem=ca_pem))
            for kind in ("pods", "nodes"):
                rstore.mirror(kind)
            rstore.wait_for_sync()
            hollow = HollowNode(rstore, "up-n1",
                                allocatable=api.resource_list(
                                    cpu="8", memory="16Gi",
                                    pods=20)).run(period=0.1)
            admin = RESTClient(cluster.url, token=cluster.admin_token,
                               ca_cert_pem=ca_pem)

            def mkpod(name):
                return api.Pod(
                    metadata=api.ObjectMeta(name=name),
                    spec=api.PodSpec(containers=[api.Container(
                        resources=api.ResourceRequirements(
                            requests=api.resource_list(
                                cpu="100m", memory="64Mi")))]))

            admin.create("pods", mkpod("pre-upgrade"))
            deadline = time.time() + 20
            while time.time() < deadline:
                if admin.get("pods", "default",
                             "pre-upgrade").spec.node_name:
                    break
                time.sleep(0.1)
            assert admin.get("pods", "default",
                             "pre-upgrade").spec.node_name == "up-n1"

            kubeadm.upgrade_cluster(cluster, "v1.12-tpu.0")

            cm = cluster.store.get("configmaps", "kube-system",
                                   kubeadm.CLUSTER_CONFIG_NAME)
            assert cm.data["clusterVersion"] == "v1.12-tpu.0"
            # objects preserved, served by the NEW apiserver
            assert admin.get("pods", "default",
                             "pre-upgrade").spec.node_name == "up-n1"
            assert admin.get("nodes", "", "up-n1") is not None
            # the scheduler (an API client) keeps placing
            admin.create("pods", mkpod("post-upgrade"))
            deadline = time.time() + 30
            placed = ""
            while time.time() < deadline and not placed:
                placed = admin.get("pods", "default",
                                   "post-upgrade").spec.node_name
                time.sleep(0.1)
            assert placed == "up-n1", "scheduler stopped placing after upgrade"
            hollow.stop()
            rstore.stop()
        finally:
            cluster.stop()

    def test_offline_upgrade_round_trips_conversion(self, tmp_path,
                                                    capsys):
        d = str(tmp_path / "kv")
        from kubernetes_tpu.runtime.nativestore import NativeObjectStore

        st = NativeObjectStore(path=d)
        st.create("nodes", make_node("n1", cpu="2"))
        # a multi-version kind: Deployment serves apps/v1beta1 through
        # the hub — the round-trip the upgrade verifies
        st.create("deployments", api.Deployment(
            metadata=api.ObjectMeta(name="web"),
            spec=api.DeploymentSpec(replicas=3)))
        st.close()
        rc = kubeadm.main(["upgrade", "--data-dir", d,
                           "--to-version", "v1.12-tpu.0"])
        out = capsys.readouterr().out
        assert rc == 0 and "round-trips verified" in out
        st = NativeObjectStore(path=d)
        cm = st.get("configmaps", "kube-system",
                    kubeadm.CLUSTER_CONFIG_NAME)
        assert cm.data["clusterVersion"] == "v1.12-tpu.0"
        assert st.get("deployments", "default", "web").spec.replicas == 3
        st.close()
